"""End-of-round benchmark: DeepFM training throughput on one chip.

Prints exactly ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Config matches the reference notebook's training job (ps notebook cell 4:
batch 1024, feature_size 117,581, field 39, K=32, deep 128/64/32, Adam 5e-4)
with bf16 MXU compute.  The reference publishes no absolute throughput
(BASELINE.md), so ``vs_baseline`` is normalized against the BASELINE.json
north-star target expressed per chip: 1M examples/sec aggregate on a v5e-64
=> 15,625 examples/sec/chip.  vs_baseline = measured / 15625 (>1.0 beats the
per-chip north-star rate).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

NORTH_STAR_PER_CHIP = 1_000_000 / 64  # examples/sec/chip


def main() -> None:
    from deepfm_tpu.core.platform import sanitize_backend

    sanitize_backend()
    import jax

    platform = jax.devices()[0].platform
    from deepfm_tpu.core.config import Config
    from deepfm_tpu.train import create_train_state, make_train_step

    cfg = Config.from_dict(
        {
            "model": {
                "feature_size": 117_581,
                "field_size": 39,
                "embedding_size": 32,
                "deep_layers": (128, 64, 32),
                "dropout_keep": (0.5, 0.5, 0.5),
            },
            "optimizer": {"learning_rate": 0.0005},
            "data": {"batch_size": 1024},
        }
    )
    batch_size = cfg.data.batch_size

    # synthetic Criteo-shaped batches (13 numeric + 26 skewed categorical),
    # pre-staged on device so the bench isolates the training-step rate
    rng = np.random.default_rng(0)
    nb = 8
    batches = []
    for _ in range(nb):
        numeric = rng.integers(1, 14, size=(batch_size, 13))
        cat = 14 + (rng.zipf(1.3, size=(batch_size, 26)) % (117_581 - 14))
        ids = np.concatenate([numeric, cat], axis=1).astype(np.int64)
        vals = np.concatenate(
            [rng.random((batch_size, 13), dtype=np.float32),
             np.ones((batch_size, 26), dtype=np.float32)], axis=1
        )
        labels = (rng.random(batch_size) < 0.25).astype(np.float32)
        batches.append(
            {
                "feat_ids": jax.device_put(ids),
                "feat_vals": jax.device_put(vals),
                "label": jax.device_put(labels),
            }
        )

    steps = 100

    def measure(fused: str, lazy: bool = False) -> tuple[float, float]:
        c = cfg.with_overrides(
            model={"fused_kernel": fused},
            optimizer={"lazy_embedding_updates": lazy},
        )
        state = create_train_state(c)
        train_step = jax.jit(make_train_step(c), donate_argnums=(0,))
        for i in range(3):  # warmup (compile + first dispatches)
            state, metrics = train_step(state, batches[i % nb])
        jax.block_until_ready(metrics)
        t0 = time.perf_counter()
        for i in range(steps):
            state, metrics = train_step(state, batches[i % nb])
        jax.block_until_ready(metrics)
        dt = time.perf_counter() - t0
        return steps * batch_size / dt, float(metrics["loss"])

    # auto-tune: XLA gather vs Pallas fused gather vs lazy (touched-rows)
    # Adam — report the fastest, record all (missing key flags a breakage)
    rates = {"xla": measure("off")}
    variants = [("lazy_adam", ("off", True))]
    if platform == "tpu":
        variants.append(("pallas_fused", ("on", False)))
    for name, (fused, lazy) in variants:
        try:
            rates[name] = measure(fused, lazy)
        except Exception as e:
            print(f"{name} variant failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
    best = max(rates, key=lambda k: rates[k][0])
    examples_per_sec, final_loss = rates[best]
    result = {
        "metric": "deepfm_train_examples_per_sec_per_chip",
        "value": round(examples_per_sec, 1),
        "unit": "examples/s",
        "vs_baseline": round(examples_per_sec / NORTH_STAR_PER_CHIP, 3),
        "platform": platform,
        "batch_size": batch_size,
        "steps": steps,
        "step_ms": round(1000 * batch_size / examples_per_sec, 3),
        "final_loss": round(final_loss, 4),
        "variant": best,
        "variants": {k: round(v[0], 1) for k, v in rates.items()},
    }
    print(json.dumps(result))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:
        # TPU tunnel down?  Re-exec once on CPU so the round still records a
        # real measurement (tagged "platform": "cpu") instead of a zero.
        import os

        if "backend" in str(e).lower() and not os.environ.get("DEEPFM_BENCH_FALLBACK"):
            env = dict(os.environ)
            env["DEEPFM_BENCH_FALLBACK"] = "1"
            env["JAX_PLATFORMS"] = "cpu"
            os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)
        print(json.dumps({"metric": "deepfm_train_examples_per_sec_per_chip",
                          "value": 0, "unit": "examples/s", "vs_baseline": 0,
                          "error": f"{type(e).__name__}: {e}"[:300]}))
        sys.exit(1)
