"""End-of-round benchmark: DeepFM training throughput on one chip.

Prints exactly ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Config matches the reference notebook's training job (ps notebook cell 4:
batch 1024, feature_size 117,581, field 39, K=32, deep 128/64/32, Adam 5e-4)
with bf16 MXU compute.  The reference publishes no absolute throughput
(BASELINE.md), so ``vs_baseline`` is normalized against the BASELINE.json
north-star target expressed per chip: 1M examples/sec aggregate on a v5e-64
=> 15,625 examples/sec/chip.  vs_baseline = measured / 15625 (>1.0 beats the
per-chip north-star rate).  That target is soft (it was set for a 64-chip
pod); the honest perf frame is the HBM roofline included in the artifact:
this model's dense-Adam step at V=117k moves ~90 MB of optimizer/param state
per step, so the floor on a v5e (819 GB/s) is ~110 µs/step.

TPU attach: the tunneled backend ("axon") can hang for many minutes when the
tunnel is down, so readiness (attach + a tiny compile+execute round trip —
the attach alone can succeed while the compile service is wedged) is probed
in a SUBPROCESS with a watchdog
(DEEPFM_TPU_ATTACH_TIMEOUT, default 420 s) and falls back to CPU on timeout.
Every successful TPU measurement is persisted to ``BENCH_TPU.json`` so the
number survives later tunnel outages (judge round-1 finding #1).

Measured variants:
  xla           dense Adam, XLA gather (jit, donated)
  lazy_adam     touched-rows-only Adam (train/lazy.py)
  pallas_fused  Pallas fused gather+FM kernel (TPU only)
  spmd_xla      the PRODUCT path: shard_map train step on a 1-chip mesh
  spmd_lazy     sharded lazy-Adam step on a 1-chip mesh
  spmd_scan8    the product path with run.steps_per_loop=8: K steps fused
                into one scanned dispatch + one stacked transfer
  spmd_scan32   same with K=32 — the deep-amortization headline config
  *_segsum      same step with table_grad='segsum' (sorted-unique-write
                embedding-gradient backward, ops/embedding.py — the round-5
                candidate fix for the serialized scatter); measured right
                after its scatter twin so short windows still decide it
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "benchmarks"))

NORTH_STAR_PER_CHIP = 1_000_000 / 64  # examples/sec/chip
V, F, K = 117_581, 39, 32
DEEP = (128, 64, 32)
# HBM bandwidth by device_kind (GB/s); unknown kind => no roofline claim
HBM_GBPS = {
    "TPU v5 lite": 819.0,   # v5e (the tunneled chip reports this kind)
    "TPU v5e": 819.0,
    "TPU v4": 1228.0,
    "TPU v5p": 2765.0,
    "TPU v6e": 1640.0,
}


def _probe_tpu(timeout_s: int) -> bool:
    """Probe the tunneled TPU in a subprocess with a hard watchdog.

    Readiness = attach AND a tiny compile+execute round trip: the attach
    can succeed while the remote compile service is wedged (observed in
    round 3), and a bench launched into that state burns every variant's
    timeout for nothing."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "axon"
    env.pop("DEEPFM_BENCH_FALLBACK", None)
    code = (
        # value fetch, not block_until_ready: the latter can return with
        # the remote execute outstanding (racy on the tunneled attach)
        "import jax, jax.numpy as jnp; "
        "f = jax.jit(lambda x: (x @ x).sum()); "
        "print('OK', float(f(jnp.ones((128, 128)))))"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            env=env, timeout=timeout_s, capture_output=True, text=True,
        )
        return r.returncode == 0 and "OK" in r.stdout
    except subprocess.TimeoutExpired:
        return False
    except Exception:
        return False


def resolve_platform() -> None:
    """Decide JAX_PLATFORMS before jax initializes: patient, bounded TPU
    attach; CPU fallback so the round always records a real measurement."""
    req = os.environ.get("JAX_PLATFORMS", "")
    if os.environ.get("DEEPFM_BENCH_FALLBACK"):
        os.environ["JAX_PLATFORMS"] = "cpu"
        return
    if req and "axon" not in req:
        return  # explicit non-tunnel request (cpu, tpu, ...) — honor it
    timeout_s = int(os.environ.get("DEEPFM_TPU_ATTACH_TIMEOUT", "420"))
    t0 = time.time()
    print(
        f"probing tunneled TPU attach (watchdog {timeout_s}s)...",
        file=sys.stderr,
    )
    if _probe_tpu(timeout_s):
        print(f"TPU attach ok in {time.time()-t0:.0f}s", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "axon"
    else:
        print(
            f"TPU attach unavailable after {time.time()-t0:.0f}s — "
            f"falling back to CPU", file=sys.stderr,
        )
        os.environ["JAX_PLATFORMS"] = "cpu"


def host_memcpy_gbps(size_mb: int = 100) -> float:
    """Measured host memcpy bandwidth: one warmed ``np.copyto`` over a
    ~100 MB buffer (the size class of the dense optimizer state), best of
    3.  The CPU-fallback stand-in for HBM bandwidth: when the bench runs
    on the dev host, the state traffic divided by THIS is the honest
    local floor on a step — a number instead of null, clearly labeled."""
    src = np.ones(size_mb * 1024 * 1024 // 8, dtype=np.float64)
    dst = np.empty_like(src)
    np.copyto(dst, src)  # warm (faults the pages)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        np.copyto(dst, src)
        best = min(best, time.perf_counter() - t0)
    return src.nbytes / best / 1e9


def dense_adam_roofline(platform: str, device_kind: str = "") -> dict:
    """HBM-traffic floor for the dense-Adam step: params+m+v read & write
    for the two embedding tables (the MLP is negligible), plus the batch
    gathers.  This is the honest per-chip perf frame (the model is
    bandwidth-bound, not FLOPs-bound).  Always attached to the artifact;
    when the measured platform's memory bandwidth is unknown (e.g. the CPU
    fallback) a measured host-memcpy bandwidth stands in for the time
    floor (labeled as such — a host floor, not an HBM claim).

    ``state_bytes_per_step`` carries the per-VARIANT optimizer-state
    traffic: replicated (every data shard reads+writes all of p/m/v — the
    pre-zero path) vs the ZeRO dp-sharded update
    (optimizer.zero_sharding): grads move once (reduce-scatter), moments
    never move and are read/written on the owned 1/dp window only, so
    the per-device state traffic is 1/dp of replicated; the one full-
    width write left is the all-gathered fresh params, accounted
    separately (it replaces the full param write the replicated path
    already paid inside its 6S term)."""
    bw = HBM_GBPS.get(device_kind) if platform == "tpu" else None
    table_bytes = (V * K + V) * 4          # fm_v + fm_w, f32
    mlp = F * K * DEEP[0] + DEEP[0] * DEEP[1] + DEEP[1] * DEEP[2] + DEEP[2]
    param_bytes = table_bytes + mlp * 4
    state_traffic = param_bytes * 3 * 2    # p,m,v x read+write
    batch_gather = 1024 * F * (K + 1) * 4 * 2          # fwd rows + row grads
    total = state_traffic + batch_gather
    roof = {
        "dense_state_bytes_per_step": state_traffic,
        "total_bytes_per_step_est": total,
        # per-variant optimizer-state traffic, replicated vs dp-sharded
        # (~97 MB/step -> ~97/dp MB/step; measured pair: zero_sharding_pair)
        "state_bytes_per_step": {
            "replicated": state_traffic,
            **{
                f"zero_dp{d}": {
                    "state_bytes_per_step": state_traffic // d,
                    "allgather_param_write_bytes": param_bytes,
                    "moments_bytes_per_device": 2 * param_bytes // d,
                }
                for d in (2, 4, 8)
            },
            "note": (
                "replicated: every data shard reads+writes p/m/v in "
                "full; zero_dpN: each shard touches only its 1/N "
                "window (grads reduce-scatter once, moments never "
                "move), plus the all-gathered full param write"
            ),
        },
    }
    if bw is None:
        memcpy_bw = host_memcpy_gbps()
        roof["hbm_bw_gbps"] = None
        roof["host_memcpy_bw_gbps"] = round(memcpy_bw, 2)
        roof["roofline_step_us"] = round(total / (memcpy_bw * 1e9) * 1e6, 1)
        roof["roofline_bw_source"] = "host_memcpy"
        # the state-traffic delta's time-floor context: what the
        # replicated-vs-sharded byte difference is worth at this host's
        # measured copy bandwidth
        roof["state_delta_floor_us_zero_dp8"] = round(
            (state_traffic - state_traffic // 8)
            / (memcpy_bw * 1e9) * 1e6, 1
        )
        roof["note"] = (
            f"memory bandwidth unknown for platform={platform!r} "
            f"device_kind={device_kind!r}; time floor computed from "
            f"MEASURED host memcpy bandwidth (np.copyto over "
            f"~100 MB) — a dev-host floor, not an HBM claim"
        )
    else:
        roof["hbm_bw_gbps"] = bw
        roof["roofline_step_us"] = round(total / (bw * 1e9) * 1e6, 1)
        roof["roofline_bw_source"] = "hbm"
    return roof


def spmd_ici_estimate(dp: int = 2, mp: int = 4) -> dict:
    """Per-step ICI bytes for the sharded step's embedding collectives —
    psum vs alltoall (ModelConfig.shard_exchange) — from B/F/K/M plus the
    MEASURED dedup rate of the shared synthetic Criteo batch, so the
    BENCH/MULTICHIP artifacts carry the comms math, not just HBM bytes.

    psum: ring all-reduce of the dense local [B/dp, F(, K)] row tensor per
    table, forward and backward -> 2 * 2(M-1)/M * S bytes each.
    alltoall: request ids [M, C] one way, response rows [M, C, K] forward
    and summed per-unique-row grads backward -> (M-1)/M of each buffer; C
    is the static per-destination capacity (auto = ceil(N/M)), so the
    traffic scales with the batch's deduped rows, not its dense volume.
    """
    from deepfm_tpu.parallel.embedding import exchange_capacity

    import _bench_util as bu

    b_local = BATCH // dp
    n = b_local * F
    host = bu.make_host_ctr_batches(BATCH, 1, v=V)[0]
    ids = np.asarray(host["feat_ids"]).reshape(dp, -1)
    per_shard_unique = [np.unique(s).size for s in ids]
    dedup_rate = round(float(np.mean(per_shard_unique)) / n, 4)
    cap_auto = exchange_capacity(n, mp, 0.0)
    # capacity sized to the measured dedup (what the flagship bench uses;
    # benchmarks/multichip_flagship.py A2A_CAPACITY) — the worst owner
    # bucket of the unpermuted Criteo shape needs ~dedup_rate * N slots
    cap_meas = exchange_capacity(n, mp, min(1.0, dedup_rate * 1.3))
    ring = 2.0 * (mp - 1) / mp
    wire = float(mp - 1) / mp

    def psum_bytes():
        s_v, s_w = n * K * 4, n * 4
        return int(2 * ring * (s_v + s_w))  # fwd + bwd, both tables

    def a2a_bytes(cap):
        per_table_req = wire * mp * cap * 4
        resp_v = wire * mp * cap * K * 4
        resp_w = wire * mp * cap * 1 * 4
        return int(2 * per_table_req + 2 * resp_v + 2 * resp_w)

    out = {
        "mesh": [dp, mp], "batch_local": b_local, "fields": F, "k": K,
        "dedup_unique_fraction": dedup_rate,
        "psum_bytes_per_step_est": psum_bytes(),
        "alltoall_bytes_per_step_est": a2a_bytes(cap_auto),
        "alltoall_bytes_per_step_est_capacity_measured": a2a_bytes(cap_meas),
        "capacity_auto_rows": cap_auto,
        "capacity_measured_rows": cap_meas,
    }
    out["alltoall_over_psum"] = round(
        out["alltoall_bytes_per_step_est"] / out["psum_bytes_per_step_est"],
        3,
    )
    out["alltoall_over_psum_capacity_measured"] = round(
        out["alltoall_bytes_per_step_est_capacity_measured"]
        / out["psum_bytes_per_step_est"], 3,
    )
    return out


def _flagship_cfg(fused: str = "off", lazy: bool = False,
                  table_grad: str = "scatter"):
    from deepfm_tpu.core.config import Config

    return Config.from_dict(
        {
            "model": {
                "feature_size": V,
                "field_size": F,
                "embedding_size": K,
                "deep_layers": DEEP,
                "dropout_keep": (0.5, 0.5, 0.5),
                "fused_kernel": fused,
                "table_grad": table_grad,
            },
            "optimizer": {"learning_rate": 0.0005,
                          "lazy_embedding_updates": lazy},
            "data": {"batch_size": 1024},
        }
    )


def _synth_batches(batch_size: int, nb: int = 8, device_put: bool = True):
    """Synthetic Criteo-shaped batches (the shared generator in
    _bench_util), pre-staged on device so the bench isolates the
    training-step rate."""
    import _bench_util as bu

    if device_put:
        return bu.make_ctr_batches(batch_size, nb, v=V)
    return bu.make_host_ctr_batches(batch_size, nb, v=V)


STEPS = 100
BATCH = 1024


def _time_loop(step_fn, state, bs) -> tuple[float, float]:
    """Fetch-based timing via the shared helper (_bench_util.time_step_loop):
    block_until_ready can return with remote work still outstanding on the
    tunneled attach (racy; measured round 5 — docs/TPU_REPORT.md), so the
    timed region ends with a device->host value fetch whose measured wire
    RTT is subtracted.  One timing policy, one implementation."""
    import _bench_util as bu

    # examples per dispatch: [B] single-step or [K, B] stacked-scan batches
    batch_size = int(np.prod(bs[0]["label"].shape))
    r = bu.time_step_loop(step_fn, state, bs, STEPS, batch_size)
    return r["examples_per_sec"], r["final_loss"]


def measure(fused: str, lazy: bool = False,
            table_grad: str = "scatter") -> tuple[float, float]:
    import jax

    from deepfm_tpu.train import create_train_state, make_train_step

    c = _flagship_cfg(fused, lazy, table_grad)
    state = create_train_state(c)
    train_step = jax.jit(make_train_step(c), donate_argnums=(0,))
    return _time_loop(train_step, state, _synth_batches(BATCH))


def measure_spmd(lazy: bool, steps_per_loop: int = 1,
                 table_grad: str = "scatter") -> tuple[float, float]:
    """The product path: shard_map step on a [1,1] mesh — measures the
    shard_map/collective overhead vs the plain jit step.  With
    ``steps_per_loop > 1``, K optimizer steps fuse into one scanned dispatch
    with one stacked transfer (run.steps_per_loop; parallel/spmd.py)."""
    from deepfm_tpu.core.config import MeshConfig
    from deepfm_tpu.parallel import (
        build_mesh, create_spmd_state, make_context, make_spmd_train_loop,
        make_spmd_train_step, shard_batch, shard_batch_stacked,
    )

    c = _flagship_cfg("off", lazy, table_grad).with_overrides(
        mesh={"data_parallel": 1, "model_parallel": 1},
    )
    mesh = build_mesh(MeshConfig(data_parallel=1, model_parallel=1))
    ctx = make_context(c, mesh)
    state = create_spmd_state(ctx)
    if steps_per_loop > 1:
        # DISTINCT stacked batches (nb*k host batches) so dispatches do not
        # replay identical data (round-3 advisor #2); nb shrinks for large K
        # to cap host staging (~62 MB at K=32 — the tunneled h2d path runs
        # ~6-10 MB/s)
        k = steps_per_loop
        nb = max(2, min(8, 256 // k))
        host = _synth_batches(BATCH, nb=nb * k, device_put=False)
        step_fn = make_spmd_train_loop(ctx, k)
        sb = [shard_batch_stacked(ctx, host[i * k:(i + 1) * k],
                                  validate_ids=False)
              for i in range(nb)]
        rate, loss = _time_loop(step_fn, state, sb)
        return rate, loss
    host = _synth_batches(BATCH, device_put=False)
    step_fn = make_spmd_train_step(ctx)  # donated, jitted inside
    sb = [shard_batch(ctx, hb, validate_ids=False) for hb in host]
    return _time_loop(step_fn, state, sb)


def measure_zero_pair(zero: bool) -> dict:
    """One arm of the measured before/after pair for the ZeRO dp-sharded
    weight update (optimizer.zero_sharding): the flagship config on the
    8-device virtual [2,4] mesh, replicated vs dp-sharded update.  Runs
    on the CPU virtual mesh by design (the pair measures the update
    restructure and the state-residency claim, not chip throughput); the
    parent forces the platform.  Reports the measured per-device
    optimizer-state bytes (the moments-never-move claim as a live
    artifact: replicated / dp-sharded ≈ dp for the dominant leaves) and
    final_loss, which must be BIT-IDENTICAL across the pair
    (tests/test_zero_sharding.py pins the same at step level)."""
    import jax

    from deepfm_tpu.core.config import MeshConfig
    from deepfm_tpu.parallel import (
        build_mesh, create_spmd_state, make_context, make_spmd_train_step,
        shard_batch,
    )

    dp, mp = 2, 4
    c = _flagship_cfg().with_overrides(
        mesh={"data_parallel": dp, "model_parallel": mp},
        optimizer={"zero_sharding": "on" if zero else "off"},
    )
    mesh = build_mesh(MeshConfig(data_parallel=dp, model_parallel=mp))
    ctx = make_context(c, mesh)
    state = create_spmd_state(ctx)
    opt_bytes_dev0 = int(sum(
        leaf.addressable_shards[0].data.nbytes
        for leaf in jax.tree_util.tree_leaves(state.opt_state)
        if hasattr(leaf, "addressable_shards")
    ))
    step_fn = make_spmd_train_step(ctx)
    host = _synth_batches(BATCH, device_put=False)
    sb = [shard_batch(ctx, hb, validate_ids=False) for hb in host]
    import _bench_util as bu

    r = bu.time_step_loop(step_fn, state, sb, STEPS, BATCH)
    return {
        "zero_sharding": "on" if zero else "off",
        "mesh": [dp, mp],
        "examples_per_sec": r["examples_per_sec"],
        "final_loss": r["final_loss_exact"],
        "opt_state_bytes_per_device": opt_bytes_dev0,
    }


# the measured before/after pair (run on the forced-CPU 8-device mesh by
# main(); not part of the throughput auto-tune set)
ZERO_PAIR = {
    "zero_off": lambda: measure_zero_pair(False),
    "zero_on": lambda: measure_zero_pair(True),
}


# ordered by information value under the time budget: each scatter variant
# is immediately followed by its segsum twin (ops/embedding.py segsum_lookup
# — the round-5 candidate fix for the serialized table-grad scatter), so a
# short window still yields the comparison that decides table_grad's default
VARIANTS = {
    "xla": lambda: measure("off"),
    "xla_segsum": lambda: measure("off", table_grad="segsum"),
    # the product path with deep dispatch amortization — the headline
    # run.steps_per_loop configuration (full K sweep: benchmarks/spmd_sweep.py)
    "spmd_scan32": lambda: measure_spmd(False, steps_per_loop=32),
    "spmd_scan32_segsum": lambda: measure_spmd(
        False, steps_per_loop=32, table_grad="segsum"),
    "lazy_adam": lambda: measure("off", True),
    "spmd_xla": lambda: measure_spmd(False),
    "spmd_lazy": lambda: measure_spmd(True),
    "spmd_scan8": lambda: measure_spmd(False, steps_per_loop=8),
    "pallas_fused": lambda: measure("on", False),
}


def _device_kind(platform: str) -> str:
    """Fetch device_kind via a bounded subprocess (the parent never holds a
    client on the tunneled attach); best-effort — '' on any failure."""
    if platform != "tpu":
        return ""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].device_kind)"],
            capture_output=True, text=True, timeout=120,
        )
        return r.stdout.strip() if r.returncode == 0 else ""
    except Exception:
        return ""


def run_variant(name: str) -> None:
    """Child mode (--variant NAME): measure one variant in THIS process and
    print its JSON row.  Variants are isolated in subprocesses because
    in-process sequential measurement cross-contaminates on the tunneled
    backend (round 3: lazy_adam measured 144k ex/s after three prior
    variants in one process vs 6.9M ex/s isolated, docs/BENCH_TPU_TUNE.json)."""
    from deepfm_tpu.core.platform import sanitize_backend

    sanitize_backend()
    if name in ZERO_PAIR:
        print(json.dumps({"variant": name, **ZERO_PAIR[name]()}))
        return
    rate, loss = VARIANTS[name]()
    print(json.dumps({"variant": name, "examples_per_sec": rate,
                      "final_loss": loss}))


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--obs":
        # the observability overhead gate (benchmarks/obs_overhead.py):
        # closed-loop serve throughput at concurrency 16, full trace +
        # registry + flight pipeline vs bare, medians over interleaved
        # trials; emits docs/BENCH_OBS.json and FAILS (exit 1) when the
        # instrumented median falls more than 3% under bare.  Host-only
        # by design — the obs layer never touches lowered code
        # (audit_observability pins that), so chips are irrelevant here.
        import obs_overhead

        r = obs_overhead.main()
        sys.exit(0 if r["within_noise"] else 1)
    if len(sys.argv) > 1 and sys.argv[1] == "--multitenant":
        # the multi-tenant fleet gate (benchmarks/multitenant.py): 4
        # same-spec tenants + 1 shadow challenger on a 2-group pool —
        # per-tenant p50/p99 vs the single-tenant baseline, a mid-load
        # single-tenant swap (FAILS on any failed / mixed-version /
        # cross-tenant-contaminated response), and a paired toggled-window
        # check that shadow scoring adds no response-path latency.  Emits
        # docs/BENCH_MULTITENANT.json.  CPU virtual mesh by design — the
        # drill measures the fleet control plane, not chip throughput.
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        sys.argv = [sys.argv[0], "--persist"] + sys.argv[2:]
        import multitenant

        r = multitenant.main()
        sys.exit(0 if r["ok"] else 1)
    if len(sys.argv) > 1 and sys.argv[1] == "--multiregion":
        # the cross-region gate (benchmarks/multiregion.py): two regions
        # (pool + region store each) behind the region front, manifests
        # replicated marker-last from the home root; kills one region
        # mid-load and FAILS (exit 1) on any admitted-then-failed
        # request, a post-failover tail outside the SLO, a stale-but-
        # healthy region re-admitted before its store caught up, or
        # post-recovery traffic off the newest version / off its home
        # region.  Emits docs/BENCH_MULTIREGION.json.  CPU virtual mesh
        # by design — the drill measures the region control plane
        # (audit_region_front pins it out of the lowered predict).
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        sys.argv = [sys.argv[0], "--persist"] + sys.argv[2:]
        import multiregion

        sys.exit(multiregion.main())
    if len(sys.argv) > 1 and sys.argv[1] == "--funnel":
        # the recommendation-funnel gate (benchmarks/funnel.py): naive
        # loop vs fused engine vs pool, plus the exact/int8/int8+pallas
        # retrieval-mode comparison at flagship V AND a synthetic 2e6-row
        # corpus — FAILS (exit 1) unless the fused engine beats the naive
        # loop and, at the synthetic corpus, int8 (or int8+pallas) makes
        # >= 1.5x exact candidates/s with recall@K >= min_recall vs
        # brute_force_topk.  Emits docs/BENCH_FUNNEL.json.  CPU virtual
        # mesh by design off-TPU; on a TPU backend the int8+pallas row
        # measures the fused Pallas kernel (kernel_engaged=true).
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        sys.argv = [sys.argv[0], "--persist"] + sys.argv[2:]
        import funnel

        r = funnel.main()
        sys.exit(0 if r["ok"] else 1)
    if len(sys.argv) > 1 and sys.argv[1] == "--slo":
        # the SLO control-plane gate (benchmarks/slo_control.py): one
        # diurnal + 10x-spike trace against a static 2-group pool vs the
        # adaptive pool (deadline-aware admission + shed ladder, hedged
        # tails under a 5% budget, AutoScaler-driven 1→4 group scaling
        # through the router's add/remove_group path); emits
        # docs/BENCH_SLO.json and FAILS (exit 1) unless adaptive beats
        # static on SLO attainment with hedges inside budget, zero
        # admitted-then-failed requests, and the pool converged back to
        # min_groups after the spike.  Host-only by design — the control
        # plane is host-side policy (audit_control_plane pins it out of
        # the lowered predict), so chips are irrelevant here.
        import slo_control

        r = slo_control.main()
        sys.exit(0 if r["ok"] else 1)
    if len(sys.argv) > 1 and sys.argv[1] == "--elastic":
        # the elastic chaos drill (benchmarks/elastic_drill.py): shrink
        # [2,4]→[1,4] and grow back mid-run under serving load; emits
        # docs/BENCH_ELASTIC.json (reshard wall-time, steps lost, serving
        # error counts, loss continuity).  CPU virtual mesh by design —
        # the drill measures the robustness layer, not chip throughput.
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        import elastic_drill

        elastic_drill.main()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--elastic-multihost":
        # the multi-host elastic drill (benchmarks/elastic_multihost.py):
        # the same [2,4]→[1,4]→[2,4] cycle under lease-fenced epoch
        # consensus, with the MPMD trainer/publisher split across real
        # processes, a scripted coordinator outage (frozen-topology
        # training), and stale-token writers refused on both the commit
        # and the publish path; emits docs/BENCH_ELASTIC_MULTIHOST.json
        # and FAILS (exit 1) on any violation.  CPU virtual mesh by
        # design — the drill measures the coordination layer, not chips.
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        import elastic_multihost

        elastic_multihost.main()
        return
    if len(sys.argv) > 2 and sys.argv[1] == "--variant":
        # child: platform was resolved by the parent and passed via env
        run_variant(sys.argv[2])
        return

    resolve_platform()
    from deepfm_tpu.core.platform import sanitize_backend

    sanitize_backend()

    # auto-tune: XLA gather vs Pallas fused gather vs lazy (touched-rows)
    # Adam vs the shard_map product path — each in an isolated subprocess;
    # report the fastest, record all (a missing key flags a breakage)
    from deepfm_tpu.core.platform import _TUNNEL_PLATFORMS

    platform_req = os.environ["JAX_PLATFORMS"]
    # the parent resolved the platform WITHOUT initializing jax on the
    # tunneled backend (probe ran in a subprocess), so children don't
    # contend with a parent-held client; they inherit the resolved env
    platform = "tpu" if platform_req in _TUNNEL_PLATFORMS else platform_req
    names = [n for n in VARIANTS
             if n != "pallas_fused" or platform == "tpu"]
    rates: dict[str, tuple[float, float]] = {}
    # global budget: a wedged-mid-bench tunnel must not burn a per-variant
    # timeout SIX times — stop launching new variants past the budget and
    # report what was measured
    budget_s = int(os.environ.get("DEEPFM_BENCH_TOTAL_BUDGET", "1500"))
    t_bench0 = time.time()
    for name in names:
        if rates and time.time() - t_bench0 > budget_s:
            print(f"bench budget ({budget_s}s) exhausted; skipping {name}",
                  file=sys.stderr)
            continue
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--variant", name],
                capture_output=True, text=True,
                timeout=int(os.environ.get("DEEPFM_BENCH_VARIANT_TIMEOUT",
                                           "600")),
            )
            if r.returncode == 0 and r.stdout.strip():
                row = json.loads(r.stdout.strip().splitlines()[-1])
                rates[name] = (row["examples_per_sec"], row["final_loss"])
            else:
                print(f"{name} variant failed: {(r.stderr or 'no output')[-200:]}",
                      file=sys.stderr)
        except subprocess.TimeoutExpired:
            print(f"{name} variant timed out", file=sys.stderr)
        except Exception as e:
            print(f"{name} variant failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
    if not rates:
        raise RuntimeError("every bench variant failed")
    best = max(rates, key=lambda k: rates[k][0])
    examples_per_sec, final_loss = rates[best]
    batch_size = BATCH
    result = {
        "metric": "deepfm_train_examples_per_sec_per_chip",
        "value": round(examples_per_sec, 1),
        "unit": "examples/s",
        "vs_baseline": round(examples_per_sec / NORTH_STAR_PER_CHIP, 3),
        # The north-star denominator (15,625 ex/s/chip) is a per-TPU-chip
        # target; a CPU-fallback rate divided by it is NOT a baseline claim.
        "vs_baseline_valid": platform == "tpu",
        "platform": platform,
        "batch_size": batch_size,
        "steps": STEPS,
        "step_ms": round(1000 * batch_size / examples_per_sec, 3),
        "final_loss": round(final_loss, 4),
        "variant": best,
        "variants": {k: round(v[0], 1) for k, v in rates.items()},
        # round 5: fetch-based timing (block_until_ready is racy on the
        # tunneled attach; pre-round-5 TPU rows were block-timed — suspect)
        "timing_method": "fetch",
    }
    roof = dense_adam_roofline(platform, _device_kind(platform))
    # comms math for the SPMD variants: what a [2,4] flagship mesh moves
    # over ICI per step, psum vs the deduplicated alltoall exchange
    try:
        roof["ici_bytes_per_step_est"] = spmd_ici_estimate()
    except Exception as e:  # estimate-only: never sink the measurement
        roof["ici_bytes_per_step_est"] = {"error": f"{type(e).__name__}: {e}"}
    # the measured before/after pair for the dp-sharded weight update
    # (always on the CPU 8-device virtual mesh — it measures the update
    # restructure and state residency, not chip throughput): replicated
    # vs zero_sharding=on, same batches, final_loss must be bit-identical
    # and per-device opt-state bytes must shrink ~dp-fold on the
    # dp-sharded leaves
    pair: dict = {}
    pair_env = dict(os.environ)
    pair_env["JAX_PLATFORMS"] = "cpu"
    pair_env.pop("DEEPFM_BENCH_FALLBACK", None)
    pflags = pair_env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in pflags:
        pair_env["XLA_FLAGS"] = (
            pflags + " --xla_force_host_platform_device_count=8"
        ).strip()
    for name in ZERO_PAIR:
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--variant",
                 name],
                capture_output=True, text=True, env=pair_env,
                timeout=int(os.environ.get("DEEPFM_BENCH_VARIANT_TIMEOUT",
                                           "600")),
            )
            if r.returncode == 0 and r.stdout.strip():
                pair[name] = json.loads(r.stdout.strip().splitlines()[-1])
            else:
                pair[name] = {
                    "error": (r.stderr or "no output")[-200:]
                }
        except Exception as e:
            pair[name] = {"error": f"{type(e).__name__}: {e}"[:200]}
    if "final_loss" in pair.get("zero_off", {}) \
            and "final_loss" in pair.get("zero_on", {}):
        pair["final_loss_bit_identical"] = (
            pair["zero_off"]["final_loss"] == pair["zero_on"]["final_loss"]
        )
        off_b = pair["zero_off"]["opt_state_bytes_per_device"]
        on_b = pair["zero_on"]["opt_state_bytes_per_device"]
        pair["opt_state_bytes_ratio"] = round(off_b / max(1, on_b), 3)
    result["zero_sharding_pair"] = pair
    xla_rate = rates.get("xla", (0.0, 0.0))[0]
    if xla_rate:
        meas_us = 1e6 * batch_size / xla_rate
        roof["measured_xla_step_us"] = round(meas_us, 1)
        if roof.get("roofline_step_us"):
            roof["hbm_utilization_xla"] = round(
                roof["roofline_step_us"] / meas_us, 3
            )
    result["roofline"] = roof
    if platform != "tpu":
        result["note"] = (
            "platform fallback: vs_baseline compares a non-TPU rate to the "
            "per-chip TPU north star and is not a perf claim; see "
            "BENCH_TPU.json for hardware measurements when available"
        )
    if platform == "tpu":
        # persist the TPU measurement so it survives tunnel outages
        artifact = dict(result)
        artifact["recorded_unix_time"] = int(time.time())
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_TPU.json")
        history = []
        if os.path.exists(path):
            try:
                with open(path) as f:
                    history = json.load(f).get("runs", [])
            except Exception:
                history = []
        history.append(artifact)
        with open(path, "w") as f:
            json.dump({"latest": artifact, "runs": history}, f, indent=1)
        print(f"TPU measurement persisted to {path}", file=sys.stderr)
    print(json.dumps(result))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:
        # TPU flaked mid-run?  Re-exec once on CPU so the round still records
        # a real measurement (tagged "platform": "cpu") instead of a zero.
        if not os.environ.get("DEEPFM_BENCH_FALLBACK"):
            env = dict(os.environ)
            env["DEEPFM_BENCH_FALLBACK"] = "1"
            env["JAX_PLATFORMS"] = "cpu"
            print(f"bench failed ({type(e).__name__}: {e}); retrying on CPU",
                  file=sys.stderr)
            os.execve(sys.executable,
                      [sys.executable, os.path.abspath(__file__)], env)
        print(json.dumps({"metric": "deepfm_train_examples_per_sec_per_chip",
                          "value": 0, "unit": "examples/s", "vs_baseline": 0,
                          "error": f"{type(e).__name__}: {e}"[:300]}))
        sys.exit(1)
