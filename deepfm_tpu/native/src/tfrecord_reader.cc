// Native streaming TFRecord reader + fused CTR Example decoder.
//
// This is the framework's equivalent of the reference's native data plane:
// the C++ tf.data runtime (TFRecordDataset, reference ps:147) and the
// sagemaker_tensorflow PipeModeDataset C++ dataset op (reference ps:150,
// hvd:136) — see SURVEY.md §2b.  One handle streams records from an ordered
// list of sources (regular files or FIFOs), verifies the masked-CRC32C
// framing, applies round-robin record sharding (dataset.shard semantics:
// record i belongs to shard i % n), and decodes the fixed CTR schema
// (label f32[1], ids i64[F], values f32[F] — reference
// tools/libsvm_to_tfrecord.py:41-53) straight into caller-owned buffers,
// so Python sees whole numpy batches with zero per-record overhead.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).
//
// Wire formats implemented:
//   TFRecord framing: u64le length | u32le masked_crc32c(length bytes)
//                     | payload | u32le masked_crc32c(payload)
//   tf.train.Example proto subset: Example.features(1) -> map entry(1)
//     -> key(1)/Feature(2); Feature: float_list(2)|int64_list(3);
//     *List.value(1) packed (wire 2) or unpacked (wire 5 / wire 0).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <string>
#include <vector>

#if defined(__x86_64__)
#include <cpuid.h>
#include <nmmintrin.h>
#endif

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli): slice-by-8 software path + SSE4.2 hardware path.
// ---------------------------------------------------------------------------

namespace {

constexpr uint32_t kPoly = 0x82F63B78u;  // reflected 0x1EDC6F41
constexpr uint32_t kMaskDelta = 0xA282EAD8u;

uint32_t g_tables[8][256];
bool g_tables_init = false;
bool g_have_sse42 = false;

void init_crc_tables() {
  for (int n = 0; n < 256; ++n) {
    uint32_t c = static_cast<uint32_t>(n);
    for (int k = 0; k < 8; ++k) c = (c & 1) ? (c >> 1) ^ kPoly : c >> 1;
    g_tables[0][n] = c;
  }
  for (int k = 1; k < 8; ++k)
    for (int n = 0; n < 256; ++n)
      g_tables[k][n] = g_tables[0][g_tables[k - 1][n] & 0xFF] ^
                       (g_tables[k - 1][n] >> 8);
#if defined(__x86_64__)
  unsigned int eax, ebx, ecx, edx;
  if (__get_cpuid(1, &eax, &ebx, &ecx, &edx)) g_have_sse42 = (ecx >> 20) & 1;
#endif
  g_tables_init = true;
}

uint32_t crc32c_sw(const uint8_t* p, size_t n, uint32_t crc) {
  crc = ~crc;
  while (n >= 8) {
    uint32_t lo, hi;
    std::memcpy(&lo, p, 4);
    std::memcpy(&hi, p + 4, 4);
    crc ^= lo;
    crc = g_tables[7][crc & 0xFF] ^ g_tables[6][(crc >> 8) & 0xFF] ^
          g_tables[5][(crc >> 16) & 0xFF] ^ g_tables[4][crc >> 24] ^
          g_tables[3][hi & 0xFF] ^ g_tables[2][(hi >> 8) & 0xFF] ^
          g_tables[1][(hi >> 16) & 0xFF] ^ g_tables[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n--) crc = g_tables[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

#if defined(__x86_64__)
__attribute__((target("sse4.2")))
uint32_t crc32c_hw(const uint8_t* p, size_t n, uint32_t crc) {
  uint64_t c = ~crc;
  while (n >= 8) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    c = _mm_crc32_u64(c, v);
    p += 8;
    n -= 8;
  }
  uint32_t c32 = static_cast<uint32_t>(c);
  while (n--) c32 = _mm_crc32_u8(c32, *p++);
  return ~c32;
}
#endif

uint32_t crc32c(const uint8_t* p, size_t n) {
#if defined(__x86_64__)
  if (g_have_sse42) return crc32c_hw(p, n, 0);
#endif
  return crc32c_sw(p, n, 0);
}

inline uint32_t masked_crc32c(const uint8_t* p, size_t n) {
  uint32_t crc = crc32c(p, n);
  return ((crc >> 15) | (crc << 17)) + kMaskDelta;
}

// ---------------------------------------------------------------------------
// Reader handle
// ---------------------------------------------------------------------------

struct Reader {
  std::vector<std::string> paths;
  size_t path_idx = 0;
  FILE* f = nullptr;
  std::vector<char> iobuf;       // stdio buffer (setvbuf)
  std::vector<uint8_t> record;   // current record payload
  bool verify = true;
  // round-robin record sharding across the whole stream (dataset.shard)
  int64_t shard_n = 1;
  int64_t shard_i = 0;
  int64_t record_idx = 0;        // global (pre-shard) record counter
  std::string error;
  bool eof = false;

  bool fail(const std::string& msg) {
    error = msg;
    return false;
  }

  bool open_next_file() {
    if (f) {
      std::fclose(f);
      f = nullptr;
    }
    if (path_idx >= paths.size()) {
      eof = true;
      return false;
    }
    const std::string& p = paths[path_idx++];
    f = std::fopen(p.c_str(), "rb");
    if (!f) return fail("cannot open " + p);
    std::setvbuf(f, iobuf.data(), _IOFBF, iobuf.size());
    return true;
  }

  // Read exactly n bytes.  fread blocks until n bytes or EOF, which is the
  // right semantics for both regular files and FIFOs (short reads loop
  // inside stdio).  Returns bytes read.
  size_t read_exactly(uint8_t* dst, size_t n) {
    return std::fread(dst, 1, n, f);
  }

  // Advance to the next raw record (any shard).  Returns:
  //   1 record ready, 0 clean end-of-stream, -1 error (see .error)
  int next_raw() {
    for (;;) {
      if (!f && !open_next_file()) return error.empty() ? 0 : -1;
      uint8_t header[12];
      size_t got = read_exactly(header, 12);
      if (got == 0) {
        // 0 bytes is only a clean EOF if no stream error is pending; an I/O
        // error at a record boundary must not silently truncate the dataset
        if (std::ferror(f)) return fail("read error at record boundary"), -1;
        if (!open_next_file()) return error.empty() ? 0 : -1;
        continue;
      }
      if (got < 12) return fail("truncated record header"), -1;
      uint64_t len;
      uint32_t len_crc;
      std::memcpy(&len, header, 8);
      std::memcpy(&len_crc, header + 8, 4);
      if (verify && masked_crc32c(header, 8) != len_crc)
        return fail("length CRC mismatch"), -1;
      if (len > (1ull << 31)) return fail("record too large"), -1;
      record.resize(len + 4);
      if (read_exactly(record.data(), len + 4) < len + 4)
        return fail("truncated record body"), -1;
      uint32_t data_crc;
      std::memcpy(&data_crc, record.data() + len, 4);
      if (verify && masked_crc32c(record.data(), len) != data_crc)
        return fail("data CRC mismatch"), -1;
      record.resize(len);
      return 1;
    }
  }

  // Next record belonging to this shard.
  int next() {
    for (;;) {
      int rc = next_raw();
      if (rc != 1) return rc;
      bool mine = (record_idx % shard_n) == shard_i;
      ++record_idx;
      if (mine) return 1;
    }
  }
};

// ---------------------------------------------------------------------------
// tf.train.Example subset parser (fixed CTR schema)
// ---------------------------------------------------------------------------

struct Span {
  const uint8_t* p;
  size_t n;
};

bool read_varint(const uint8_t*& p, const uint8_t* end, uint64_t* out) {
  uint64_t result = 0;
  int shift = 0;
  while (p < end) {
    uint8_t b = *p++;
    result |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = result;
      return true;
    }
    shift += 7;
    if (shift > 63) return false;
  }
  return false;
}

// Skip a field of the given wire type; p points just past the tag.
bool skip_field(const uint8_t*& p, const uint8_t* end, uint32_t wire) {
  uint64_t tmp;
  switch (wire) {
    case 0:
      return read_varint(p, end, &tmp);
    case 1:
      if (end - p < 8) return false;
      p += 8;
      return true;
    case 2:
      if (!read_varint(p, end, &tmp) || static_cast<uint64_t>(end - p) < tmp)
        return false;
      p += tmp;
      return true;
    case 5:
      if (end - p < 4) return false;
      p += 4;
      return true;
    default:
      return false;
  }
}

// Parse FloatList bytes -> up to cap floats into out; returns count or -1.
int64_t parse_float_list(Span s, float* out, int64_t cap) {
  const uint8_t* p = s.p;
  const uint8_t* end = s.p + s.n;
  int64_t count = 0;
  while (p < end) {
    uint64_t tag;
    if (!read_varint(p, end, &tag)) return -1;
    uint32_t fn = tag >> 3, wire = tag & 7;
    if (fn == 1 && wire == 2) {  // packed
      uint64_t ln;
      if (!read_varint(p, end, &ln) || static_cast<uint64_t>(end - p) < ln ||
          ln % 4)
        return -1;
      int64_t k = ln / 4;
      if (count + k > cap) return -1;
      std::memcpy(out + count, p, ln);
      count += k;
      p += ln;
    } else if (fn == 1 && wire == 5) {  // unpacked
      if (end - p < 4 || count + 1 > cap) return -1;
      std::memcpy(out + count, p, 4);
      ++count;
      p += 4;
    } else if (!skip_field(p, end, wire)) {
      return -1;
    }
  }
  return count;
}

int64_t parse_int64_list(Span s, int64_t* out, int64_t cap) {
  const uint8_t* p = s.p;
  const uint8_t* end = s.p + s.n;
  int64_t count = 0;
  while (p < end) {
    uint64_t tag;
    if (!read_varint(p, end, &tag)) return -1;
    uint32_t fn = tag >> 3, wire = tag & 7;
    if (fn == 1 && wire == 2) {  // packed varints
      uint64_t ln;
      if (!read_varint(p, end, &ln) || static_cast<uint64_t>(end - p) < ln)
        return -1;
      const uint8_t* pe = p + ln;
      while (p < pe) {
        uint64_t v;
        if (!read_varint(p, pe, &v) || count + 1 > cap) return -1;
        out[count++] = static_cast<int64_t>(v);
      }
    } else if (fn == 1 && wire == 0) {
      uint64_t v;
      if (!read_varint(p, end, &v) || count + 1 > cap) return -1;
      out[count++] = static_cast<int64_t>(v);
    } else if (!skip_field(p, end, wire)) {
      return -1;
    }
  }
  return count;
}

// Walk one Example, locating the Feature payloads for label/ids/values.
// Returns false on malformed proto.
bool find_ctr_features(Span ex, Span* label, Span* ids, Span* values) {
  label->p = ids->p = values->p = nullptr;
  const uint8_t* p = ex.p;
  const uint8_t* end = ex.p + ex.n;
  while (p < end) {
    uint64_t tag;
    if (!read_varint(p, end, &tag)) return false;
    uint32_t fn = tag >> 3, wire = tag & 7;
    if (fn != 1 || wire != 2) {  // not Example.features
      if (!skip_field(p, end, wire)) return false;
      continue;
    }
    uint64_t flen;
    if (!read_varint(p, end, &flen) || static_cast<uint64_t>(end - p) < flen)
      return false;
    const uint8_t* fp = p;
    const uint8_t* fend = p + flen;
    p += flen;
    // Features: repeated map entry (field 1)
    while (fp < fend) {
      uint64_t etag;
      if (!read_varint(fp, fend, &etag)) return false;
      if ((etag >> 3) != 1 || (etag & 7) != 2) {
        if (!skip_field(fp, fend, etag & 7)) return false;
        continue;
      }
      uint64_t elen;
      if (!read_varint(fp, fend, &elen) ||
          static_cast<uint64_t>(fend - fp) < elen)
        return false;
      const uint8_t* ep = fp;
      const uint8_t* eend = fp + elen;
      fp += elen;
      // map entry: key=1 (string), value=2 (Feature)
      Span key{nullptr, 0}, feat{nullptr, 0};
      while (ep < eend) {
        uint64_t mtag;
        if (!read_varint(ep, eend, &mtag)) return false;
        uint32_t mfn = mtag >> 3, mwire = mtag & 7;
        if (mwire == 2) {
          uint64_t mlen;
          if (!read_varint(ep, eend, &mlen) ||
              static_cast<uint64_t>(eend - ep) < mlen)
            return false;
          if (mfn == 1) key = {ep, mlen};
          else if (mfn == 2) feat = {ep, mlen};
          ep += mlen;
        } else if (!skip_field(ep, eend, mwire)) {
          return false;
        }
      }
      if (!key.p || !feat.p) continue;
      // Feature oneof: float_list=2 | int64_list=3 (bytes_list=1 unused).
      // We hand back the *List payload span.
      const uint8_t* vp = feat.p;
      const uint8_t* vend = feat.p + feat.n;
      while (vp < vend) {
        uint64_t vtag;
        if (!read_varint(vp, vend, &vtag)) return false;
        uint32_t vfn = vtag >> 3, vwire = vtag & 7;
        if (vwire != 2) {
          if (!skip_field(vp, vend, vwire)) return false;
          continue;
        }
        uint64_t vlen;
        if (!read_varint(vp, vend, &vlen) ||
            static_cast<uint64_t>(vend - vp) < vlen)
          return false;
        Span list{vp, vlen};
        vp += vlen;
        if (key.n == 5 && !std::memcmp(key.p, "label", 5) && vfn == 2)
          *label = list;
        else if (key.n == 3 && !std::memcmp(key.p, "ids", 3) && vfn == 3)
          *ids = list;
        else if (key.n == 6 && !std::memcmp(key.p, "values", 6) && vfn == 2)
          *values = list;
      }
    }
  }
  return true;
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// paths: NUL-separated, double-NUL terminated list of source paths.
void* dfm_reader_open(const char* paths, int verify_crc, int64_t shard_n,
                      int64_t shard_i) {
  if (!g_tables_init) init_crc_tables();
  auto* r = new Reader();
  const char* p = paths;
  while (*p) {
    r->paths.emplace_back(p);
    p += r->paths.back().size() + 1;
  }
  r->iobuf.resize(1 << 20);
  r->verify = verify_crc != 0;
  r->shard_n = shard_n > 0 ? shard_n : 1;
  r->shard_i = shard_i;
  return r;
}

void dfm_reader_close(void* h) {
  auto* r = static_cast<Reader*>(h);
  if (r->f) std::fclose(r->f);
  delete r;
}

const char* dfm_reader_error(void* h) {
  return static_cast<Reader*>(h)->error.c_str();
}

// Next raw record (this shard).  On success returns length and sets *data to
// an internal buffer valid until the next call.  Returns -1 on clean EOF,
// -2 on error.
int64_t dfm_reader_next_record(void* h, const uint8_t** data) {
  auto* r = static_cast<Reader*>(h);
  int rc = r->next();
  if (rc == 0) return -1;
  if (rc < 0) return -2;
  *data = r->record.data();
  return static_cast<int64_t>(r->record.size());
}

// Fused: read up to `batch` records of this shard and decode the CTR schema
// into ids_out [batch*field_size] i64, vals_out [batch*field_size] f32,
// labels_out [batch] f32.  Returns number of records decoded (< batch only
// at end-of-stream), or -2 on error.
int64_t dfm_reader_next_ctr_batch(void* h, int64_t batch, int64_t field_size,
                                  int64_t* ids_out, float* vals_out,
                                  float* labels_out) {
  auto* r = static_cast<Reader*>(h);
  for (int64_t i = 0; i < batch; ++i) {
    int rc = r->next();
    if (rc == 0) return i;
    if (rc < 0) return -2;
    Span ex{r->record.data(), r->record.size()};
    Span label, ids, values;
    if (!find_ctr_features(ex, &label, &ids, &values)) {
      r->fail("malformed Example proto");
      return -2;
    }
    if (!label.p || !ids.p || !values.p) {
      r->fail("Example missing label/ids/values feature");
      return -2;
    }
    float lab[2];
    if (parse_float_list(label, lab, 1) != 1) {
      r->fail("label must be FloatList[1]");
      return -2;
    }
    labels_out[i] = lab[0];
    if (parse_int64_list(ids, ids_out + i * field_size, field_size) !=
        field_size) {
      r->fail("ids count != field_size");
      return -2;
    }
    if (parse_float_list(values, vals_out + i * field_size, field_size) !=
        field_size) {
      r->fail("values count != field_size");
      return -2;
    }
  }
  return batch;
}

// Standalone CRC for tests/tools.
uint32_t dfm_masked_crc32c(const uint8_t* data, uint64_t n) {
  if (!g_tables_init) init_crc_tables();
  return masked_crc32c(data, n);
}

int dfm_have_hw_crc(void) {
  if (!g_tables_init) init_crc_tables();
  return g_have_sse42 ? 1 : 0;
}

}  // extern "C"
