// Native Criteo raw-TSV -> TFRecord hash encoder (the 1TB-path prep).
//
// Byte-identical to the Python pipeline it accelerates:
//   data/criteo.py CriteoHashEncoder.encode  (blake2b-8 of "field:token",
//     little-endian, mod (feature_size - 14), +14; log1p numerics)
//   data/example_proto.serialize_ctr_example (label FloatList[1],
//     ids Int64List[39] packed varint, values FloatList[39] packed,
//     map entries in label/ids/values order)
//   data/tfrecord.frame_record               (u64 length LE + masked CRC32C
//     of header + payload + masked CRC32C of payload — CRC from
//     tfrecord_reader.cc, same shared library)
//   data/criteo.convert_criteo_to_tfrecords  (blank lines skipped, shards
//     "{prefix}-%05d.tfrecords" of records_per_shard each)
//
// Python measured ~5k lines/s on one core; this path exists so the
// Criteo-1TB (4.4B-line) prep is not interpreter-bound.  Exposed via
// ctypes as dfm_criteo_hash_encode; dfm_blake2b64 is exported separately
// so tests can pin hash equality against hashlib.
//
// BLAKE2b per RFC 7693, unkeyed, digest_length=8 — matching
// hashlib.blake2b(data, digest_size=8).

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

extern "C" uint32_t dfm_masked_crc32c(const char* data, uint64_t len);

// ---------------------------------------------------------------------------
// BLAKE2b (compact, unkeyed, variable digest)
// ---------------------------------------------------------------------------

static const uint64_t B2B_IV[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
    0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL,
};

static const uint8_t B2B_SIGMA[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
};

static inline uint64_t rotr64(uint64_t x, int n) {
    return (x >> n) | (x << (64 - n));
}

static void b2b_compress(uint64_t h[8], const uint8_t block[128],
                         uint64_t t0, uint64_t t1, bool last) {
    uint64_t m[16], v[16];
    std::memcpy(m, block, 128);  // little-endian host assumed (x86/arm64)
    for (int i = 0; i < 8; i++) v[i] = h[i];
    for (int i = 0; i < 8; i++) v[i + 8] = B2B_IV[i];
    v[12] ^= t0;
    v[13] ^= t1;
    if (last) v[14] = ~v[14];
#define B2B_G(a, b, c, d, x, y)                  \
    do {                                         \
        v[a] = v[a] + v[b] + (x);                \
        v[d] = rotr64(v[d] ^ v[a], 32);          \
        v[c] = v[c] + v[d];                      \
        v[b] = rotr64(v[b] ^ v[c], 24);          \
        v[a] = v[a] + v[b] + (y);                \
        v[d] = rotr64(v[d] ^ v[a], 16);          \
        v[c] = v[c] + v[d];                      \
        v[b] = rotr64(v[b] ^ v[c], 63);          \
    } while (0)
    for (int r = 0; r < 12; r++) {
        const uint8_t* s = B2B_SIGMA[r];
        B2B_G(0, 4, 8, 12, m[s[0]], m[s[1]]);
        B2B_G(1, 5, 9, 13, m[s[2]], m[s[3]]);
        B2B_G(2, 6, 10, 14, m[s[4]], m[s[5]]);
        B2B_G(3, 7, 11, 15, m[s[6]], m[s[7]]);
        B2B_G(0, 5, 10, 15, m[s[8]], m[s[9]]);
        B2B_G(1, 6, 11, 12, m[s[10]], m[s[11]]);
        B2B_G(2, 7, 8, 13, m[s[12]], m[s[13]]);
        B2B_G(3, 4, 9, 14, m[s[14]], m[s[15]]);
    }
#undef B2B_G
    for (int i = 0; i < 8; i++) h[i] ^= v[i] ^ v[i + 8];
}

// 8-byte unkeyed BLAKE2b of data, returned as the little-endian uint64 the
// Python side builds with int.from_bytes(digest, "little")
extern "C" uint64_t dfm_blake2b64(const uint8_t* data, uint64_t len) {
    uint64_t h[8];
    for (int i = 0; i < 8; i++) h[i] = B2B_IV[i];
    h[0] ^= 0x01010000ULL ^ 8ULL;  // digest_length=8, fanout=1, depth=1
    uint64_t t = 0;
    uint8_t block[128];
    while (len > 128) {
        std::memcpy(block, data, 128);
        t += 128;
        b2b_compress(h, block, t, 0, false);
        data += 128;
        len -= 128;
    }
    std::memset(block, 0, 128);
    if (len) std::memcpy(block, data, len);
    t += len;
    b2b_compress(h, block, t, 0, true);
    return h[0];  // first 8 digest bytes == h[0] little-endian
}

// ---------------------------------------------------------------------------
// proto + framing writers (buffered)
// ---------------------------------------------------------------------------

static inline void put_varint(std::string& out, uint64_t n) {
    while (n >= 0x80) {
        out.push_back(static_cast<char>((n & 0x7f) | 0x80));
        n >>= 7;
    }
    out.push_back(static_cast<char>(n));
}

static inline void put_len_delimited(std::string& out, int field,
                                     const std::string& payload) {
    put_varint(out, (static_cast<uint64_t>(field) << 3) | 2);
    put_varint(out, payload.size());
    out.append(payload);
}

static constexpr int kNumNumeric = 13;
static constexpr int kNumCat = 26;
static constexpr int kFields = kNumNumeric + kNumCat;
static constexpr int kFirstCatId = kNumNumeric + 1;

struct EncodeState {
    int64_t feature_size;
    uint64_t buckets;
    // reused buffers
    std::string ex, tmp, inner, framed;
};

// serialize_ctr_example parity: Example{Features{label, ids, values}}
static void serialize_example(EncodeState& st, float label,
                              const int64_t ids[kFields],
                              const float vals[kFields]) {
    std::string& features = st.tmp;
    features.clear();

    auto map_entry = [&](const char* name, int kind,
                         const std::string& list_payload) {
        // entry = { key=1 string, value=2 Feature{kind: List} }
        std::string& entry = st.inner;
        entry.clear();
        size_t nk = std::strlen(name);
        put_varint(entry, (1ULL << 3) | 2);
        put_varint(entry, nk);
        entry.append(name, nk);
        std::string feature;
        std::string list;
        put_len_delimited(list, 1, list_payload);  // List.value (packed)
        put_len_delimited(feature, kind, list);    // Feature.<kind>_list
        put_len_delimited(entry, 2, feature);
        put_len_delimited(features, 1, entry);     // Features.feature
    };

    std::string payload;
    payload.resize(sizeof(float));
    std::memcpy(payload.data(), &label, sizeof(float));
    map_entry("label", 2, payload);  // FloatList = Feature field 2

    payload.clear();
    for (int i = 0; i < kFields; i++)
        put_varint(payload, static_cast<uint64_t>(ids[i]));
    map_entry("ids", 3, payload);    // Int64List = Feature field 3

    payload.resize(kFields * sizeof(float));
    std::memcpy(payload.data(), vals, kFields * sizeof(float));
    map_entry("values", 2, payload);

    st.ex.clear();
    put_len_delimited(st.ex, 1, features);  // Example.features
}

static void frame_record(EncodeState& st) {
    std::string& out = st.framed;
    out.clear();
    uint64_t n = st.ex.size();
    char header[8];
    std::memcpy(header, &n, 8);  // little-endian
    uint32_t hcrc = dfm_masked_crc32c(header, 8);
    uint32_t dcrc = dfm_masked_crc32c(st.ex.data(), st.ex.size());
    out.append(header, 8);
    out.append(reinterpret_cast<char*>(&hcrc), 4);
    out.append(st.ex);
    out.append(reinterpret_cast<char*>(&dcrc), 4);
}

// Python float() parity: strtod over the WHOLE field (leading/trailing
// whitespace tolerated, anything else rejects), arbitrary field length.
// strtod's grammar is wider than Python's in two silent ways, both closed
// here: hex floats ("0x1p3") are rejected, and an embedded NUL (which
// would truncate the C-string parse and ACCEPT a field Python rejects)
// is rejected up front.  The reverse direction — Python-only spellings
// like underscore grouping ("1_0") or non-ASCII digits — is already a
// rejection on this path, matching the documented contract that the
// native encoder accepts a SUBSET of float() inputs byte-identically
// (tests/test_criteo.py parity suite).
static bool parse_full_double(EncodeState& st, const char* s, size_t n,
                              double* out) {
    if (memchr(s, '\0', n) != nullptr) return false;
    // strtod's NAN(char-seq) extension — Python float() rejects any
    // parenthesized payload, so '(' anywhere in the field is a reject
    if (memchr(s, '(', n) != nullptr) return false;
    st.inner.assign(s, n);
    const char* c = st.inner.c_str();
    // reject strtod's hex-float extension: optional sign, then 0x/0X
    const char* h = c;
    while (*h == ' ' || *h == '\t' || *h == '\r' || *h == '\f' ||
           *h == '\v') {
        h++;
    }
    if (*h == '+' || *h == '-') h++;
    if (h[0] == '0' && (h[1] == 'x' || h[1] == 'X')) return false;
    char* endp = nullptr;
    double x = std::strtod(c, &endp);
    if (endp == c) return false;
    while (*endp == ' ' || *endp == '\t' || *endp == '\r' ||
           *endp == '\f' || *endp == '\v') {
        endp++;
    }
    if (*endp != '\0') return false;
    *out = x;
    return true;
}

// parse + encode one TSV line; returns false on anything the Python path
// (parse_criteo_line + float()) would raise on: field count != 40, or a
// non-numeric label/I-field
static bool encode_line(EncodeState& st, const char* line, size_t len,
                        float* label, int64_t ids[kFields],
                        float vals[kFields]) {
    const char* p = line;
    const char* end = line + len;
    const char* field_start[1 + kFields];
    size_t field_len[1 + kFields];
    int nf = 0;
    const char* s = p;
    for (const char* q = p;; q++) {
        if (q == end || *q == '\t') {
            if (nf < 1 + kFields) {
                field_start[nf] = s;
                field_len[nf] = static_cast<size_t>(q - s);
            }
            nf++;
            if (q == end) break;
            s = q + 1;
        }
    }
    if (nf != 1 + kFields) return false;  // parse_criteo_line raises

    {  // label: float(field) — empty/invalid rejects the line
        double x;
        if (field_len[0] == 0 ||
            !parse_full_double(st, field_start[0], field_len[0], &x)) {
            return false;
        }
        *label = static_cast<float>(x);
    }
    for (int i = 0; i < kNumNumeric; i++) {
        ids[i] = i + 1;
        size_t n = field_len[1 + i];
        if (n == 0) {
            vals[i] = 0.0f;  // missing numeric -> 0.0
            continue;
        }
        double x;
        if (!parse_full_double(st, field_start[1 + i], n, &x)) return false;
        vals[i] = static_cast<float>(x >= 0 ? std::log1p(x) : x);
    }
    for (int j = 0; j < kNumCat; j++) {
        // hash input "j:token" — '' hashes like any token (stable missing id)
        std::string& key = st.inner;
        key.clear();
        char jb[8];
        int jn = std::snprintf(jb, sizeof(jb), "%d:", j);
        key.append(jb, static_cast<size_t>(jn));
        key.append(field_start[1 + kNumNumeric + j],
                   field_len[1 + kNumNumeric + j]);
        uint64_t h = dfm_blake2b64(
            reinterpret_cast<const uint8_t*>(key.data()), key.size());
        ids[kNumNumeric + j] =
            kFirstCatId + static_cast<int64_t>(h % st.buckets);
        vals[kNumNumeric + j] = 1.0f;
    }
    return true;
}

static void set_err(char* err, int64_t cap, const char* msg) {
    if (err && cap > 0) {
        std::snprintf(err, static_cast<size_t>(cap), "%s", msg);
    }
}

// Streams input_path (TSV) into {prefix}-NNNNN.tfrecords shards under
// output_dir.  Returns records written, or -1 with err filled.
extern "C" int64_t dfm_criteo_hash_encode(
    const char* input_path, const char* output_dir, const char* prefix,
    int64_t feature_size, int64_t records_per_shard,
    char* err, int64_t err_cap) {
    if (feature_size <= kFirstCatId + kNumCat) {
        set_err(err, err_cap, "feature_size leaves no categorical hash space");
        return -1;
    }
    if (records_per_shard <= 0) {
        set_err(err, err_cap, "records_per_shard must be positive");
        return -1;
    }
    FILE* in = std::fopen(input_path, "rb");
    if (!in) {
        set_err(err, err_cap, "cannot open input");
        return -1;
    }
    EncodeState st;
    st.feature_size = feature_size;
    st.buckets = static_cast<uint64_t>(feature_size - kFirstCatId);

    FILE* out = nullptr;
    int shard = 0;
    int64_t in_shard = 0, total = 0;
    char* line = nullptr;
    size_t cap = 0;
    ssize_t n;
    int64_t bad = 0;
    float label;
    int64_t ids[kFields];
    float vals[kFields];
    std::string outbuf;
    outbuf.reserve(1 << 20);
    char path[4096];

    auto flush = [&]() {
        if (out && !outbuf.empty()) {
            std::fwrite(outbuf.data(), 1, outbuf.size(), out);
            outbuf.clear();
        }
    };

    while ((n = getline(&line, &cap, in)) != -1) {
        size_t len = static_cast<size_t>(n);
        // Python parity: the Python path reads in TEXT mode (universal
        // newlines), so "\r\n" arrives as "\n" and rstrip('\n') removes
        // it — strip the '\n' then ONE '\r' here.  (Classic-Mac lone-\r
        // line endings are not supported on this path; Python text mode
        // would split them, getline would not.)
        while (len && line[len - 1] == '\n') len--;
        if (len && line[len - 1] == '\r') len--;
        // blank check == `not line.strip()` (all str.strip() whitespace)
        bool blank = true;
        for (size_t i = 0; i < len; i++) {
            char c = line[i];
            if (c != ' ' && c != '\t' && c != '\r' && c != '\f' &&
                c != '\v') {
                blank = false;
                break;
            }
        }
        if (blank) continue;
        if (!encode_line(st, line, len, &label, ids, vals)) {
            bad++;
            continue;
        }
        if (!out || in_shard >= records_per_shard) {
            flush();
            if (out) std::fclose(out);
            std::snprintf(path, sizeof(path), "%s/%s-%05d.tfrecords",
                          output_dir, prefix, shard);
            out = std::fopen(path, "wb");
            if (!out) {
                set_err(err, err_cap, "cannot open output shard");
                std::free(line);
                std::fclose(in);
                return -1;
            }
            shard++;
            in_shard = 0;
        }
        serialize_example(st, label, ids, vals);
        frame_record(st);
        outbuf.append(st.framed);
        if (outbuf.size() >= (1 << 20)) flush();
        in_shard++;
        total++;
    }
    flush();
    if (out) std::fclose(out);
    std::fclose(in);
    std::free(line);
    if (bad) {
        // malformed lines are a data bug the caller must see, not silence
        char msg[128];
        std::snprintf(msg, sizeof(msg),
                      "%lld malformed line(s) skipped",
                      static_cast<long long>(bad));
        set_err(err, err_cap, msg);
    }
    return total;
}
