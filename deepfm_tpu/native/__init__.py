"""Native (C++) data plane: streaming TFRecord reader + fused CTR decoder.

This package owns the framework's native-runtime surface for ingest — the
capability the reference inherits from tf.data's C++ runtime and the
``sagemaker_tensorflow`` PipeModeDataset C++ op (SURVEY.md §2b; reference
ps:147,150, hvd:136).  The shared library is compiled from
``src/tfrecord_reader.cc`` with the system ``g++`` on first use and cached
next to the source; set ``DEEPFM_NO_NATIVE=1`` to force the pure-Python
fallback (``deepfm_tpu.data.tfrecord`` / ``example_proto``).

The hot entry point is :class:`NativeCtrReader`, which streams whole decoded
numpy batches out of C++ — framing, CRC32C (SSE4.2 when available), record
sharding, and Example-proto parsing all happen without touching the Python
interpreter per record.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterable, Iterator, Sequence

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRCS = [
    os.path.join(_HERE, "src", "tfrecord_reader.cc"),
    os.path.join(_HERE, "src", "criteo_encoder.cc"),
]
_LIB_DIR = os.path.join(_HERE, "_build")
_LIB = os.path.join(_LIB_DIR, "libdeepfm_native.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_build_error: str | None = None


def _needs_build() -> bool:
    if not os.path.exists(_LIB):
        return True
    lib_mtime = os.path.getmtime(_LIB)
    return any(lib_mtime < os.path.getmtime(s) for s in _SRCS)


def _build() -> None:
    os.makedirs(_LIB_DIR, exist_ok=True)
    tmp = f"{_LIB}.{os.getpid()}.tmp"  # unique per builder: concurrent
    # processes each compile their own file; os.replace publishes whichever
    # finishes last, atomically
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        "-fno-exceptions", "-Wall", *_SRCS, "-o", tmp,
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"native build failed:\n{proc.stderr}")
    os.replace(tmp, _LIB)


def _load() -> ctypes.CDLL:
    global _lib, _build_error
    with _lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            raise RuntimeError(_build_error)
        try:
            if _needs_build():
                # da:allow[blocking-under-lock] build-once lazy init: the lock exists to make the slow compile happen exactly once; callers blocking behind it is the design
                _build()
            lib = ctypes.CDLL(_LIB)
        except Exception as e:  # remember failure; don't retry per call
            _build_error = f"{type(e).__name__}: {e}"
            raise
        lib.dfm_reader_open.restype = ctypes.c_void_p
        lib.dfm_reader_open.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
        ]
        lib.dfm_reader_close.argtypes = [ctypes.c_void_p]
        lib.dfm_reader_error.restype = ctypes.c_char_p
        lib.dfm_reader_error.argtypes = [ctypes.c_void_p]
        lib.dfm_reader_next_record.restype = ctypes.c_int64
        lib.dfm_reader_next_record.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ]
        lib.dfm_reader_next_ctr_batch.restype = ctypes.c_int64
        lib.dfm_reader_next_ctr_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.dfm_masked_crc32c.restype = ctypes.c_uint32
        lib.dfm_masked_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.dfm_have_hw_crc.restype = ctypes.c_int
        lib.dfm_blake2b64.restype = ctypes.c_uint64
        lib.dfm_blake2b64.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.dfm_criteo_hash_encode.restype = ctypes.c_int64
        lib.dfm_criteo_hash_encode.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
        ]
        _lib = lib
        return lib


def available() -> bool:
    """True when the native library is usable (builds it on first call)."""
    if os.environ.get("DEEPFM_NO_NATIVE"):
        return False
    try:
        _load()
        return True
    # da:allow[swallowed-exception] availability probe: build/load failure means "use the python path"
    except Exception:
        return False


def have_hw_crc() -> bool:
    return bool(_load().dfm_have_hw_crc())


def masked_crc32c(data: bytes) -> int:
    return _load().dfm_masked_crc32c(data, len(data))


def blake2b64(data: bytes) -> int:
    """8-byte unkeyed BLAKE2b as a little-endian int — the criteo hash
    (== int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(),
    'little'))."""
    return _load().dfm_blake2b64(data, len(data))


def criteo_hash_encode_file(
    input_path: str | os.PathLike,
    output_dir: str | os.PathLike,
    *,
    feature_size: int,
    records_per_shard: int = 1_000_000,
    prefix: str = "tr",
) -> int:
    """Native drop-in for ``data.criteo.convert_criteo_to_tfrecords`` with a
    ``CriteoHashEncoder`` — byte-identical shards (same hash, proto bytes,
    framing, shard naming), interpreter-free per line.  Returns records
    written; raises ValueError if any line was malformed (the Python
    encoder raises on the first one; here the count is reported after the
    well-formed lines were written)."""
    os.makedirs(output_dir, exist_ok=True)
    err = ctypes.create_string_buffer(256)
    n = _load().dfm_criteo_hash_encode(
        os.fsencode(os.fspath(input_path)),
        os.fsencode(os.fspath(output_dir)),
        prefix.encode(),
        feature_size,
        records_per_shard,
        err,
        len(err),
    )
    if n < 0:
        raise NativeReaderError(err.value.decode(errors="replace"))
    if err.value:
        raise ValueError(err.value.decode(errors="replace"))
    return int(n)


def _pack_paths(paths: Sequence[str | os.PathLike]) -> bytes:
    out = b""
    for p in paths:
        out += os.fsencode(os.fspath(p)) + b"\x00"
    return out + b"\x00"


class NativeReaderError(IOError):
    pass


class _Handle:
    """RAII wrapper over a dfm_reader handle."""

    def __init__(self, paths, verify: bool, shard_n: int, shard_i: int):
        self._lib = _load()
        self._h = self._lib.dfm_reader_open(
            _pack_paths(paths), 1 if verify else 0, shard_n, shard_i
        )
        if not self._h:
            raise NativeReaderError("dfm_reader_open failed")

    def error(self) -> str:
        return self._lib.dfm_reader_error(self._h).decode(errors="replace")

    def close(self) -> None:
        if self._h:
            self._lib.dfm_reader_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        # da:allow[swallowed-exception] finalizer: interpreter teardown may have dropped the lib handle
        except Exception:
            pass


def read_records(
    paths: str | os.PathLike | Sequence[str | os.PathLike],
    *,
    verify: bool = True,
    shard_n: int = 1,
    shard_i: int = 0,
) -> Iterator[bytes]:
    """Yield raw record payloads (this shard) from the native reader.

    Drop-in analog of ``deepfm_tpu.data.tfrecord.read_records`` but over a
    *list* of sources with sharding pushed into C++.
    """
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    h = _Handle(paths, verify, shard_n, shard_i)
    lib = h._lib
    ptr = ctypes.POINTER(ctypes.c_uint8)()
    try:
        while True:
            n = lib.dfm_reader_next_record(h._h, ctypes.byref(ptr))
            if n == -1:
                return
            if n < 0:
                raise NativeReaderError(h.error())
            yield ctypes.string_at(ptr, n)
    finally:
        h.close()


class NativeCtrReader:
    """Stream decoded CTR batches out of the C++ reader.

    Yields ``{"feat_ids": i64 [B,F], "feat_vals": f32 [B,F], "label": f32 [B]}``
    exactly like ``data.pipeline.batched_ctr_batches`` — but the whole
    record→batch path (framing, CRC, shard filter, proto decode) runs native.
    """

    def __init__(
        self,
        paths: Sequence[str | os.PathLike],
        *,
        batch_size: int,
        field_size: int,
        shard_n: int = 1,
        shard_i: int = 0,
        drop_remainder: bool = True,
        verify: bool = True,
        skip_counter: list[int] | None = None,
    ):
        self._paths = list(paths)
        self._batch = batch_size
        self._fields = field_size
        self._shard = (shard_n, shard_i)
        self._drop = drop_remainder
        self._verify = verify
        self._skip_counter = skip_counter

    def __iter__(self) -> Iterator[dict]:
        h = _Handle(self._paths, self._verify, *self._shard)
        lib = h._lib
        B, F = self._batch, self._fields
        try:
            # input-position resume: fast-forward whole batches at the raw-
            # record level (framing+CRC only, no Example decode, no copies).
            # The shared counter lets the caller spread a skip across epochs;
            # a partial tail doesn't decrement it (drop_remainder parity).
            ptr = ctypes.POINTER(ctypes.c_uint8)()
            while self._skip_counter and self._skip_counter[0] > 0:
                pulled = 0
                while pulled < B:
                    n = lib.dfm_reader_next_record(h._h, ctypes.byref(ptr))
                    if n == -1:
                        # stream ended mid-skip: with remainders kept the
                        # partial tail counts as one skipped step
                        if pulled and not self._drop:
                            self._skip_counter[0] -= 1
                        return
                    if n < 0:
                        raise NativeReaderError(h.error())
                    pulled += 1
                self._skip_counter[0] -= 1
            while True:
                ids = np.empty((B, F), np.int64)
                vals = np.empty((B, F), np.float32)
                labels = np.empty((B,), np.float32)
                n = lib.dfm_reader_next_ctr_batch(
                    h._h, B, F,
                    ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                )
                if n < 0:
                    raise NativeReaderError(h.error())
                if n == B:
                    yield {"feat_ids": ids, "feat_vals": vals, "label": labels}
                    continue
                if n > 0 and not self._drop:
                    yield {
                        "feat_ids": ids[:n],
                        "feat_vals": vals[:n],
                        "label": labels[:n],
                    }
                return
        finally:
            h.close()
