"""Flywheel record codecs and the deterministic sampling gate.

Impressions and clicks ride the same ``tf.train.Example`` wire format as
the training stream (data/example_proto.py), so segments stay inspectable
with the repo's own tooling, but they are NOT the trainer's CTR schema —
only the join's *output* is (plain ``serialize_ctr_example`` records,
which ``decode_ctr_batch`` accepts unchanged).

Timestamps are int64 **milliseconds** on the wire: the float feature kind
is float32, whose 24-bit mantissa quantizes epoch seconds to ~minute
granularity — useless against a minutes-scale attribution window.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import numpy as np

from ..data.example_proto import parse_example, serialize_example
from ..fleet.split import sampled

# Distinct salt: the flywheel's keep/drop slice must be stable per
# impression id regardless of how the shadow split is tuned.
FLYWHEEL_SALT = "flywheel"


def impression_sampled(impression_id: str, sample_rate: float) -> bool:
    """Hash-stable keep/drop decision for one impression id.

    A pure function of the id, so every party — the router-side logger,
    shadow scoring keyed off the same request, and the join service —
    recomputes the identical decision with no coordination."""
    return sampled(impression_id, float(sample_rate) * 100.0,
                   salt=FLYWHEEL_SALT)


class Impression(NamedTuple):
    impression_id: str
    trace_id: str
    tenant: str
    model_version: int
    ids: np.ndarray  # [F] int64
    values: np.ndarray  # [F] float32
    score: float
    deadline_class: str
    ts_ms: int


class Click(NamedTuple):
    impression_id: str
    ts_ms: int


def serialize_impression(
    *,
    impression_id: str,
    trace_id: str,
    tenant: str,
    model_version: int,
    ids: Sequence[int],
    values: Sequence[float],
    score: float,
    deadline_class: str,
    ts_ms: int,
) -> bytes:
    return serialize_example(
        {
            "impression_id": ("bytes", [impression_id.encode()]),
            "trace_id": ("bytes", [trace_id.encode()]),
            "tenant": ("bytes", [tenant.encode()]),
            "model_version": ("int64", [int(model_version)]),
            "ids": ("int64", [int(i) for i in ids]),
            "values": ("float", [float(v) for v in values]),
            "score": ("float", [float(score)]),
            "deadline_class": ("bytes", [deadline_class.encode()]),
            "ts_ms": ("int64", [int(ts_ms)]),
        }
    )


def _one_bytes(doc: dict, name: str) -> str:
    vals = doc.get(name)
    if not isinstance(vals, list) or len(vals) != 1:
        raise ValueError(f"record missing bytes field {name!r}")
    return vals[0].decode()


def _one_scalar(doc: dict, name: str) -> float:
    vals = doc.get(name)
    if vals is None or len(vals) != 1:
        raise ValueError(f"record missing scalar field {name!r}")
    return float(vals[0])


def parse_impression(buf: bytes) -> Impression:
    doc = parse_example(buf)
    ids = np.asarray(doc.get("ids", ()), np.int64)
    values = np.asarray(doc.get("values", ()), np.float32)
    if ids.shape != values.shape:
        raise ValueError(
            f"impression ids/values shape mismatch: "
            f"{ids.shape} vs {values.shape}"
        )
    return Impression(
        impression_id=_one_bytes(doc, "impression_id"),
        trace_id=_one_bytes(doc, "trace_id"),
        tenant=_one_bytes(doc, "tenant"),
        model_version=int(_one_scalar(doc, "model_version")),
        ids=ids,
        values=values,
        score=_one_scalar(doc, "score"),
        deadline_class=_one_bytes(doc, "deadline_class"),
        ts_ms=int(_one_scalar(doc, "ts_ms")),
    )


def serialize_click(*, impression_id: str, ts_ms: int) -> bytes:
    return serialize_example(
        {
            "impression_id": ("bytes", [impression_id.encode()]),
            "ts_ms": ("int64", [int(ts_ms)]),
        }
    )


def parse_click(buf: bytes) -> Click:
    doc = parse_example(buf)
    return Click(
        impression_id=_one_bytes(doc, "impression_id"),
        ts_ms=int(_one_scalar(doc, "ts_ms")),
    )
