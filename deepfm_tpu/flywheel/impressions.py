"""Bounded, sampled impression logging off the serving response path.

Same structural guarantee as shadow scoring (fleet/shadow.py): the
router answers every request as always, and *after* the answer is
formed the request is **offered** here — a hash-stable sampling gate,
then ``put_nowait`` into a bounded queue.  A full queue drops the offer
(counted, never blocks); one background writer drains the queue,
serializes impression records off-path, and publishes them through the
shared :class:`~deepfm_tpu.online.stream.SegmentWriter` size/age roll
into the immutable-segment format the join service tails.

The sampling decision is per impression id (the trace id when the
request carried one, else the routing key) via
:func:`~deepfm_tpu.flywheel.records.impression_sampled` — deterministic,
so the join service recomputes the identical keep/drop slice and a click
for a sampled-out impression is recognized as such, not treated as an
orphan.
"""

from __future__ import annotations

import queue
import threading
import time

from ..obs.metrics import MetricsRegistry
from ..online.stream import SegmentWriter
from .records import impression_sampled, serialize_impression


class ImpressionLogger:
    """Router-side scored-impression logger: sample → bound → segment."""

    def __init__(
        self,
        root: str,
        *,
        sample_rate: float = 1.0,
        queue_depth: int = 1024,
        roll_bytes: int = 1 << 20,
        roll_age_secs: float = 10.0,
        join_output_url: str = "",
        registry: MetricsRegistry | None = None,
    ):
        if not root:
            raise ValueError("ImpressionLogger needs a log root")
        if not 0.0 < sample_rate <= 1.0:
            raise ValueError(
                f"sample_rate must be in (0, 1], got {sample_rate}")
        self.root = root
        self.join_output_url = join_output_url
        self._sample_rate = float(sample_rate)
        self._q: queue.Queue = queue.Queue(maxsize=int(queue_depth))
        self._writer = SegmentWriter(
            root, roll_bytes=roll_bytes, roll_age_secs=roll_age_secs)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        events = self.registry.counter(
            "deepfm_flywheel_impressions_total",
            "impression-logging events by kind",
            labels=("event",))
        self._c_logged = events.labels("logged")
        self._c_sampled_out = events.labels("sampled_out")
        self._c_dropped = events.labels("dropped")
        self._c_errors = events.labels("error")

    # -- serving-path side (must stay O(1) and non-blocking) ----------------
    def offer(
        self,
        *,
        key: str,
        trace_id: str = "",
        tenant: str = "",
        model_version: int = -1,
        instances: list,
        scores: list,
        deadline_class: str = "",
    ) -> int:
        """Offer one scored request; returns rows enqueued.

        One impression row per instance, ids ``{base}#{row}`` so clicks
        attribute at item granularity while the sampling decision is
        made once per request on the base id (trace id, else routing
        key).  Serialization happens on the writer thread — the serving
        path pays one tuple enqueue per row, or a counted drop."""
        base = trace_id or key
        if not impression_sampled(base, self._sample_rate):
            self._c_sampled_out.inc(len(instances))
            return 0
        ts_ms = int(time.time() * 1000)
        enqueued = 0
        for row, (inst, score) in enumerate(zip(instances, scores)):
            # the serving request schema (serve/server.py): feat_ids /
            # feat_vals per instance
            item = (f"{base}#{row}", trace_id, tenant, int(model_version),
                    inst.get("feat_ids", ()), inst.get("feat_vals", ()),
                    float(score), deadline_class, ts_ms)
            try:
                self._q.put_nowait(item)
                enqueued += 1
            except queue.Full:
                self._c_dropped.inc()
        return enqueued

    # -- writer side --------------------------------------------------------
    def _write_one(self, item: tuple) -> None:
        (imp_id, trace_id, tenant, version, ids, values, score,
         deadline_class, ts_ms) = item
        record = serialize_impression(
            impression_id=imp_id, trace_id=trace_id, tenant=tenant,
            model_version=version, ids=ids, values=values, score=score,
            deadline_class=deadline_class, ts_ms=ts_ms)
        self._writer.append(record)
        self._c_logged.inc()

    def _run(self) -> None:
        while True:
            try:
                item = self._q.get(timeout=0.2)
            except queue.Empty:
                if self._stop.is_set():
                    return
                self._safe(self._writer.poll)
                continue
            if item is None:
                if self._stop.is_set():
                    return
                continue
            self._safe(self._write_one, item)
            self._safe(self._writer.poll)

    def _safe(self, fn, *args) -> None:
        try:
            fn(*args)
        # da:allow[swallowed-exception] advisory by contract: a log-store outage costs impressions — counted in errors_total — never a crash loop next to the serving process
        except Exception:
            self._c_errors.inc()

    def start(self) -> "ImpressionLogger":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="flywheel-impressions")
            self._thread.start()
        return self

    def stop(self) -> None:
        """Drain the queue, publish the tail segment, park the worker."""
        self.drain()
        self._stop.set()
        try:
            self._q.put_nowait(None)  # wake the worker past its timeout
        except queue.Full:
            pass
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self._safe(self._writer.flush)
        self._stop = threading.Event()

    def drain(self, timeout_secs: float = 10.0) -> None:
        """Block until the queue is empty (bench/test synchronization)."""
        deadline = time.monotonic() + timeout_secs
        while not self._q.empty() and time.monotonic() < deadline:
            time.sleep(0.01)

    def flush(self) -> None:
        """Publish whatever the writer has buffered (tests/benches)."""
        self.drain()
        self._safe(self._writer.flush)

    # -- observability ------------------------------------------------------
    def stats(self) -> dict:
        out = {
            "root": self.root,
            "sample_rate": self._sample_rate,
            "logged_total": int(self._c_logged.value),
            "sampled_out_total": int(self._c_sampled_out.value),
            "dropped_total": int(self._c_dropped.value),
            "errors_total": int(self._c_errors.value),
            "segments_published": self._writer.segments_published_total,
            "queue_depth": self._q.qsize(),
        }
        if self.join_output_url:
            from .join import load_status

            out["join"] = load_status(self.join_output_url)
        return out
