"""Data flywheel: serve → log → join → train on our own traffic.

The paper's pipeline trains on an externally supplied dataset and the
online trainer (``online/``) eats a hand-fed event stream — yet the
serving tier already observes every impression it scores.  This package
closes the loop with the classic delayed-feedback CTR join:

* :mod:`.impressions` — a bounded, hash-stable-sampled logger hooked at
  the pool router's response path; scored impressions land in the
  ``online/stream.py`` immutable-segment format, and a full queue drops
  with a metric — the serve path is never blocked.
* :mod:`.join` — a standalone process (``python -m
  deepfm_tpu.flywheel.join``) that tails the impression log and a
  click-event log, matches clicks to impressions inside an attribution
  window, synthesizes negatives when the window expires under a
  watermark keyed to segment publish times, and emits joined labeled
  examples as a stream the online trainer cursors over unchanged.
  Its ``{cursors, pending-window}`` state commits atomically, and its
  emission schedule is a pure function of (checkpoint, log contents), so
  crash-resume re-publishes bit-identical segments instead of
  double-emitting or dropping.
* :mod:`.records` — the impression/click record codecs riding the
  ``tf.train.Example`` wire format, plus the deterministic per-trace-id
  sampling gate both the logger and the join recompute independently.

``--task_type feedback-train`` (launch/cli.py) then points the existing
online trainer at the join's output stream — train/publish/serve close
into one self-improving loop.
"""

from .impressions import ImpressionLogger
from .join import JoinService
from .records import (
    impression_sampled,
    parse_click,
    parse_impression,
    serialize_click,
    serialize_impression,
)

__all__ = [
    "ImpressionLogger",
    "JoinService",
    "impression_sampled",
    "parse_click",
    "parse_impression",
    "serialize_click",
    "serialize_impression",
]
