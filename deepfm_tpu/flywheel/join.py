"""Delayed-label join: impressions ⋈ clicks → labeled training stream.

The classic CTR feedback problem: the positive label for an impression
arrives seconds-to-hours later (a click), or never.  This service tails
two event logs — scored impressions (the router's
:class:`~deepfm_tpu.flywheel.impressions.ImpressionLogger`) and click
events (the application's) — and resolves every sampled impression to
exactly one labeled example:

* a click inside the **attribution window** → positive, emitted when the
  click is read (order tolerant: a click read *before* its impression
  waits in an early-click buffer);
* window expiry with no click → **synthesized negative**;
* a click after the negative was already emitted → counted as a
  label-flip (metric + flight event), never a duplicate example.

**Watermark.**  Time is *segment publish time* (mtime locally,
first-seen remotely — stream.py's watermark convention), not event
payload time: the click watermark is the publish time of the newest
fully-consumed click segment, and an impression expires once the click
watermark passes its own segment's publish time plus the window.  Late
and out-of-order events inside segments are therefore harmless; only
segment publish order matters, and that is what producers guarantee.

**Exactly-once.**  The join's whole schedule — which segment is consumed
next (heads of the two logs merged by publish time), what is emitted,
and where output segments split (byte-roll only, no age-roll) — is a
pure function of ``(checkpoint state, log contents)``.  Each checkpoint
first flushes the output writer, then commits ``{cursors,
pending-window, counters, next output seq}`` atomically (tmp+rename /
single PUT).  A crash between the two re-runs the interval on resume
and re-publishes byte-identical segments under the same names — an
idempotent overwrite, not a double emit; a crash before the flush loses
only uncommitted work that replay regenerates.  Hence the drill's
bit-exact guarantee: kill the join anywhere, resume, and the emitted
stream equals the uninterrupted run's.
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
from collections import deque

from ..data.example_proto import serialize_ctr_example
from ..data.object_store import get_store, is_url, join_url
from ..data.tfrecord import read_records
from ..obs import flight as obs_flight
from ..obs.metrics import MetricsRegistry
from ..online.stream import SegmentWriter, StreamCursor, open_tail
from .records import impression_sampled, parse_click, parse_impression

STATE_NAME = "_join_state.json"

_EVENTS = ("positive", "negative", "flip", "orphan_click",
           "sampled_out", "duplicate")


def _state_path(output_root: str) -> str:
    return (join_url(output_root, STATE_NAME) if is_url(output_root)
            else os.path.join(output_root, STATE_NAME))


def load_status(output_root: str) -> dict | None:
    """The join's latest committed checkpoint as an observability doc
    (None before the first checkpoint) — what the router's
    ``/v1/metrics`` flywheel section reports for the join half without
    sharing a process with it."""
    state = load_state(output_root)
    if state is None:
        return None
    wm = float(state.get("watermark", 0.0))
    return {
        "watermark": wm,
        "lag_seconds": (round(max(0.0, time.time() - wm), 3)
                        if wm > 0 else None),
        "pending_window": len(state.get("pending", ())),
        "early_clicks": len(state.get("early", ())),
        "next_out_seq": int(state.get("next_out_seq", 0)),
        "counters": state.get("counters", {}),
    }


class JoinService:
    """One delayed-label join over (impression log, click log) → output
    stream.  Construct, then :meth:`run` (one-shot or follow)."""

    def __init__(
        self,
        impressions_url: str,
        clicks_url: str,
        output_url: str,
        *,
        attribution_window_secs: float,
        sample_rate: float = 1.0,
        roll_bytes: int = 1 << 20,
        checkpoint_every_segments: int = 8,
        stall_flight_secs: float = 30.0,
        registry: MetricsRegistry | None = None,
        resume: bool = True,
    ):
        if attribution_window_secs <= 0:
            raise ValueError(
                f"attribution_window_secs must be > 0, "
                f"got {attribution_window_secs}")
        if checkpoint_every_segments <= 0:
            raise ValueError(
                f"checkpoint_every_segments must be > 0, "
                f"got {checkpoint_every_segments}")
        self._imp_tail = open_tail(impressions_url)
        self._click_tail = open_tail(clicks_url)
        self.output_url = output_url
        self._window = float(attribution_window_secs)
        self._sample_rate = float(sample_rate)
        self._checkpoint_every = int(checkpoint_every_segments)
        self._stall_secs = float(stall_flight_secs)
        self._since_checkpoint = 0
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        events = self.registry.counter(
            "deepfm_flywheel_join_events_total",
            "join resolutions and anomalies by kind", labels=("event",))
        self._c = {ev: events.labels(ev) for ev in _EVENTS}
        self._g_pending = self.registry.gauge(
            "deepfm_flywheel_join_pending",
            "impressions awaiting a click or window expiry")
        self._g_lag = self.registry.gauge(
            "deepfm_flywheel_join_lag_seconds",
            "wall time minus the click watermark")
        self._c_stalls = self.registry.counter(
            "deepfm_flywheel_join_stalls_total",
            "watermark stalls with a non-empty pending window")
        # test/drill hooks: raise from either to inject a crash at the
        # exact fault-window boundary it names
        self.on_segment = None  # called with each published segment name
        self.on_checkpoint = None  # called after each committed checkpoint

        state = load_state(output_url) if resume else None
        if state is None:
            self._imp_cursor = StreamCursor()
            self._click_cursor = StreamCursor()
            self._watermark = 0.0
            self._imp_watermark = 0.0
            self._pending: dict[str, dict] = {}
            self._early: dict[str, float] = {}
            self._expired: dict[str, float] = {}
            self.emitted_total = 0
            next_seq = 0
        else:
            self._imp_cursor = StreamCursor(*state["imp_cursor"])
            self._click_cursor = StreamCursor(*state["click_cursor"])
            self._watermark = float(state["watermark"])
            self._imp_watermark = float(state["imp_watermark"])
            self._pending = dict(state["pending"])
            self._early = dict(state["early"])
            self._expired = dict(state["expired"])
            counters = state.get("counters", {})
            for ev in _EVENTS:
                self._c[ev].inc(float(counters.get(ev, 0)))
            self.emitted_total = int(counters.get("emitted", 0))
            next_seq = int(state["next_out_seq"])
        # no age roll: output segment boundaries must be a pure function
        # of the emitted records (see module docstring)
        self._writer = SegmentWriter(
            output_url, roll_bytes=roll_bytes, roll_age_secs=0,
            start_seq=next_seq)

    # -- segment consumption ------------------------------------------------
    def _unconsumed(self, tail, cursor: StreamCursor) -> list[str]:
        return [n for n in tail.list_segments()
                if n != STATE_NAME
                and (not cursor.segment or n > cursor.segment)]

    def _read_segment(self, tail, name: str) -> list[bytes]:
        # read fully BEFORE mutating any state: a failed read then
        # retries next poll with nothing half-applied
        with tail.open_segment(name) as f:
            return list(read_records(f))

    def _emit(self, label: float, ids, values) -> None:
        rolled = self._writer.append(
            serialize_ctr_example(label, ids, values))
        self.emitted_total += 1
        if rolled and self.on_segment is not None:
            self.on_segment(rolled)

    def _consume_impressions(self, name: str) -> None:
        records = self._read_segment(self._imp_tail, name)
        pub = self._imp_tail.segment_time(name)
        for rec in records:
            imp = parse_impression(rec)
            pid = imp.impression_id
            base = pid.rsplit("#", 1)[0]
            if not impression_sampled(base, self._sample_rate):
                self._c["sampled_out"].inc()
                continue
            if pid in self._pending or pid in self._expired:
                self._c["duplicate"].inc()
                continue
            entry = {
                "pub": pub,
                "ids": [int(i) for i in imp.ids],
                "values": [float(v) for v in imp.values],
            }
            if pid in self._early:
                self._early.pop(pid)
                self._emit(1.0, entry["ids"], entry["values"])
                self._c["positive"].inc()
            else:
                self._pending[pid] = entry
        self._imp_watermark = max(self._imp_watermark, pub)
        self._imp_cursor = StreamCursor(name, len(records))

    def _consume_clicks(self, name: str) -> None:
        records = self._read_segment(self._click_tail, name)
        pub = self._click_tail.segment_time(name)
        for rec in records:
            click = parse_click(rec)
            pid = click.impression_id
            entry = self._pending.pop(pid, None)
            if entry is not None:
                self._emit(1.0, entry["ids"], entry["values"])
                self._c["positive"].inc()
            elif pid in self._expired:
                # the window already closed and the negative is on the
                # wire — count the flip, never emit a duplicate example
                self._c["flip"].inc()
                obs_flight.record(
                    "label_flip_after_emit", subsystem="flywheel",
                    impression_id=pid, watermark=self._watermark)
            elif not impression_sampled(
                    pid.rsplit("#", 1)[0], self._sample_rate):
                self._c["sampled_out"].inc()
            else:
                # click before its impression was read — out-of-order
                # tolerance; waits up to one window for the impression
                self._early.setdefault(pid, pub)
        self._watermark = max(self._watermark, pub)
        self._click_cursor = StreamCursor(name, len(records))
        self._expire()

    def _expire(self) -> None:
        w = self._watermark
        due = sorted(
            (e["pub"], pid) for pid, e in self._pending.items()
            if e["pub"] + self._window <= w)
        for _, pid in due:
            entry = self._pending.pop(pid)
            self._emit(0.0, entry["ids"], entry["values"])
            self._c["negative"].inc()
            self._expired[pid] = w
        for pid in sorted(pid for pid, t in self._early.items()
                          if t + self._window <= w):
            self._early.pop(pid)
            self._c["orphan_click"].inc()
        # flip detection keeps an expired id for one further window,
        # then forgets it — bounded memory, deterministic horizon
        for pid in [pid for pid, t in self._expired.items()
                    if t + self._window <= w]:
            del self._expired[pid]

    def _run_pass(self, *, max_segments: int = 0) -> int:
        """Consume every currently-listed unconsumed segment, heads of
        the two logs merged by (publish time, stream, name) — the
        deterministic schedule replay depends on."""
        imps = deque(self._unconsumed(self._imp_tail, self._imp_cursor))
        clicks = deque(
            self._unconsumed(self._click_tail, self._click_cursor))
        processed = 0
        while imps or clicks:
            if not clicks:
                take_click = False
            elif not imps:
                take_click = True
            else:
                take_click = (
                    (self._click_tail.segment_time(clicks[0]), "c")
                    <= (self._imp_tail.segment_time(imps[0]), "i"))
            if take_click:
                self._consume_clicks(clicks.popleft())
            else:
                self._consume_impressions(imps.popleft())
            processed += 1
            self._since_checkpoint += 1
            if self._since_checkpoint >= self._checkpoint_every:
                self.checkpoint()
            if max_segments and processed >= max_segments:
                break
        return processed

    # -- durability ---------------------------------------------------------
    def checkpoint(self) -> None:
        """Flush output, then commit state atomically — in that order:
        resume after a crash between the two regenerates the flushed
        segment bit-identically (idempotent overwrite)."""
        name = self._writer.flush()
        if name and self.on_segment is not None:
            self.on_segment(name)
        counters = {ev: int(self._c[ev].value) for ev in _EVENTS}
        counters["emitted"] = self.emitted_total
        state = {
            "schema": 1,
            "imp_cursor": list(self._imp_cursor),
            "click_cursor": list(self._click_cursor),
            "watermark": self._watermark,
            "imp_watermark": self._imp_watermark,
            "pending": sorted(self._pending.items()),
            "early": sorted(self._early.items()),
            "expired": sorted(self._expired.items()),
            "next_out_seq": self._writer.next_seq,
            "counters": counters,
        }
        payload = json.dumps(state).encode()
        path = _state_path(self.output_url)
        if is_url(path):
            get_store().put(path, payload)
        else:
            os.makedirs(self.output_url, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(payload)
            os.replace(tmp, path)
        self._since_checkpoint = 0
        if self.on_checkpoint is not None:
            self.on_checkpoint(state)

    # -- driving ------------------------------------------------------------
    def run(
        self,
        *,
        follow: bool = False,
        stop: threading.Event | None = None,
        idle_timeout_secs: float = 0.0,
        poll_interval_secs: float = 0.2,
        drain_at_eof: bool = False,
    ) -> int:
        """Consume the logs; returns segments processed.

        ``follow=False`` reads the logs as they stand;
        ``drain_at_eof=True`` then advances the watermark past every
        read impression so all still-pending windows expire (negatives
        emitted) — the one-shot batch-join mode.  ``follow=True`` tails
        until ``stop`` / ``idle_timeout_secs`` without progress, flight-
        recording watermark stalls.  A final checkpoint always commits
        before returning."""
        total = 0
        now = time.monotonic()
        last_progress = now
        last_wm, last_wm_change, stalled = self._watermark, now, False
        while True:
            n = self._run_pass()
            total += n
            now = time.monotonic()
            if n:
                last_progress = now
            if self._watermark != last_wm:
                last_wm, last_wm_change, stalled = \
                    self._watermark, now, False
            elif (follow and self._pending and not stalled
                    and now - last_wm_change >= self._stall_secs):
                self._c_stalls.inc()
                stalled = True
                obs_flight.record(
                    "join_watermark_stall", subsystem="flywheel",
                    watermark=self._watermark,
                    pending=len(self._pending),
                    stalled_secs=round(now - last_wm_change, 1))
            if stop is not None and stop.is_set():
                break
            if not follow:
                break
            if (idle_timeout_secs > 0
                    and now - last_progress >= idle_timeout_secs):
                break
            if stop is not None:
                stop.wait(poll_interval_secs)
            else:
                time.sleep(poll_interval_secs)
        if drain_at_eof and not follow and (self._pending or self._early):
            self._watermark = max(
                self._watermark, self._imp_watermark + self._window)
            self._expire()
        self.checkpoint()
        return total

    # -- observability ------------------------------------------------------
    def stats(self) -> dict:
        wm = self._watermark
        lag = round(max(0.0, time.time() - wm), 3) if wm > 0 else None
        self._g_pending.set(len(self._pending))
        if lag is not None:
            self._g_lag.set(lag)
        return {
            "watermark": wm,
            "lag_seconds": lag,
            "pending_window": len(self._pending),
            "early_clicks": len(self._early),
            "emitted_total": self.emitted_total,
            "stalls_total": int(self._c_stalls.value),
            **{f"{ev}_total": int(self._c[ev].value) for ev in _EVENTS},
        }


def load_state(output_root: str) -> dict | None:
    """The raw committed checkpoint (None when absent/unreadable)."""
    path = _state_path(output_root)
    try:
        if is_url(path):
            data = get_store().open_read_resuming(path).read()
        else:
            with open(path, "rb") as f:
                data = f.read()
        return json.loads(data)
    except (OSError, ValueError):
        return None


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m deepfm_tpu.flywheel.join",
        description="delayed-label join: impressions + clicks -> "
                    "labeled training stream",
    )
    p.add_argument("--config", help="JSON config file; the flywheel "
                                    "section supplies defaults")
    p.add_argument("--impressions", help="impression log root")
    p.add_argument("--clicks", help="click-event log root")
    p.add_argument("--out", help="joined output stream root")
    p.add_argument("--window", type=float,
                   help="attribution window seconds")
    p.add_argument("--sample-rate", type=float)
    p.add_argument("--roll-bytes", type=int)
    p.add_argument("--checkpoint-every", type=int)
    p.add_argument("--follow", action="store_true",
                   help="tail the logs (default: one shot)")
    p.add_argument("--idle-timeout", type=float, default=0.0)
    p.add_argument("--poll-interval", type=float, default=0.2)
    p.add_argument("--drain", action="store_true",
                   help="one-shot mode: expire every pending window at "
                        "end of log (synthesizes the tail negatives)")
    args = p.parse_args(argv)

    fw = None
    if args.config:
        from ..core.config import Config

        fw = Config.from_json(args.config).flywheel
    pick = lambda flag, attr, dflt: (  # noqa: E731
        flag if flag is not None
        else (getattr(fw, attr) if fw is not None else dflt))
    impressions = pick(args.impressions, "impression_log_url", "")
    clicks = pick(args.clicks, "click_log_url", "")
    out = pick(args.out, "join_output_url", "")
    if not (impressions and clicks and out):
        p.error("need --impressions, --clicks and --out "
                "(or a --config with a filled flywheel section)")
    svc = JoinService(
        impressions, clicks, out,
        attribution_window_secs=pick(
            args.window, "attribution_window_secs", 1800.0),
        sample_rate=pick(args.sample_rate, "sample_rate", 1.0),
        roll_bytes=pick(args.roll_bytes, "segment_roll_bytes", 1 << 20),
        checkpoint_every_segments=pick(
            args.checkpoint_every, "join_checkpoint_every_segments", 8),
    )

    stop = threading.Event()
    import signal

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    svc.run(follow=args.follow, stop=stop,
            idle_timeout_secs=args.idle_timeout,
            poll_interval_secs=args.poll_interval,
            drain_at_eof=args.drain)
    print(json.dumps(svc.stats(), indent=2))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
