from .optimizer import build_optimizer, ftrl  # noqa: F401
from .step import (  # noqa: F401
    TrainState,
    create_train_state,
    jitted_train_step,
    make_eval_step,
    make_loss_fn,
    make_predict_step,
    make_train_step,
    new_auc_state,
    sigmoid_cross_entropy,
)
from .retrieval import (  # noqa: F401
    create_retrieval_state,
    make_retrieval_eval_step,
    make_retrieval_train_step,
)
