"""Jitted train/eval/predict steps — the Estimator-loop capability (ps:492-521)
re-expressed as pure functions over an explicit ``TrainState``.

One traced, compiled function per mode (TRAIN/EVAL/PREDICT) replaces the
reference's mode-switched ``model_fn``: no graph collections, no sessions —
each step is a single XLA executable dispatched per batch, donation-friendly
so parameter buffers update in place in HBM.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

from ..core.config import Config
from ..models.base import get_model
from ..ops.auc import AUCState, auc_init, auc_update
from .optimizer import build_optimizer


class TrainState(NamedTuple):
    step: jnp.ndarray          # i32 scalar — the global_step (ps:307)
    params: Any
    model_state: Any           # non-trainable (BN moving stats)
    opt_state: Any
    rng: jax.Array             # dropout key, folded per step


def sigmoid_cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Elementwise ``tf.nn.sigmoid_cross_entropy_with_logits`` (ps:276)."""
    return jnp.maximum(logits, 0.0) - logits * labels + jnp.log1p(
        jnp.exp(-jnp.abs(logits))
    )


def make_loss_fn(cfg: Config, model, lookup_fn=None) -> Callable:
    """loss = mean CE + the model family's L2 penalty (reference: ps:275-279
    applies l2_reg·(½‖FM_W‖²+½‖FM_V‖²); each ModelDef declares its own)."""
    apply_fn, l2_penalty = model.apply, model.l2_penalty

    def loss_fn(params, model_state, batch, rng, train: bool):
        kwargs = {} if lookup_fn is None else {"lookup_fn": lookup_fn}
        logits, new_state = apply_fn(
            params,
            model_state,
            batch["feat_ids"],
            batch["feat_vals"],
            cfg=cfg.model,
            train=train,
            rng=rng,
            **kwargs,
        )
        labels = batch["label"].reshape(-1).astype(jnp.float32)
        ce = jnp.mean(sigmoid_cross_entropy(logits, labels))
        loss = ce + l2_penalty(params, cfg.model.l2_reg)
        return loss, (logits, new_state)

    return loss_fn


def create_train_state(cfg: Config, key: jax.Array | None = None) -> TrainState:
    key = jax.random.PRNGKey(cfg.run.seed) if key is None else key
    init_key, step_key = jax.random.split(key)
    model = get_model(cfg.model)
    params, model_state = model.init(init_key, cfg.model)
    tx = build_optimizer(cfg.optimizer, data_parallel_size=_dp_size(cfg))
    opt_state = tx.init(params)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        model_state=model_state,
        opt_state=opt_state,
        rng=step_key,
    )


def _dp_size(cfg: Config) -> int:
    n = cfg.mesh.data_parallel
    if n > 0:
        return n
    return max(1, jax.device_count() // max(1, cfg.mesh.model_parallel))


def make_train_step(cfg: Config, lookup_fn=None) -> Callable:
    """Build ``(state, batch) -> (state, metrics)``.  Jit it yourself or via
    pjit in ``deepfm_tpu/parallel`` — this function stays sharding-agnostic."""
    model = get_model(cfg.model)
    loss_fn = make_loss_fn(cfg, model, lookup_fn)
    tx = build_optimizer(cfg.optimizer, data_parallel_size=_dp_size(cfg))

    def train_step(state: TrainState, batch: dict) -> tuple[TrainState, dict]:
        step_rng = jax.random.fold_in(state.rng, state.step)
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
        (loss, (logits, new_model_state)), grads = grad_fn(
            state.params, state.model_state, batch, step_rng, True
        )
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {
            "loss": loss,
            "pred_mean": jnp.mean(jax.nn.sigmoid(logits)),
            "label_mean": jnp.mean(batch["label"].astype(jnp.float32)),
        }
        return (
            TrainState(
                step=state.step + 1,
                params=new_params,
                model_state=new_model_state,
                opt_state=new_opt_state,
                rng=state.rng,
            ),
            metrics,
        )

    return train_step


def make_eval_step(cfg: Config, lookup_fn=None) -> Callable:
    """``(state, auc_state, batch) -> (auc_state, metrics)``: loss + streaming
    AUC accumulation (the reference's eval metric, ps:282)."""
    model = get_model(cfg.model)
    loss_fn = make_loss_fn(cfg, model, lookup_fn)

    def eval_step(
        state: TrainState, auc_state: AUCState, batch: dict
    ) -> tuple[AUCState, dict]:
        loss, (logits, _) = loss_fn(
            state.params, state.model_state, batch, None, False
        )
        preds = jax.nn.sigmoid(logits)
        labels = batch["label"].reshape(-1)
        new_auc = auc_update(auc_state, labels, preds)
        return new_auc, {"loss": loss, "count": jnp.asarray(labels.shape[0])}

    return eval_step


def make_predict_step(cfg: Config, lookup_fn=None) -> Callable:
    """``(state, batch) -> prob [B]`` — the PREDICT/serving path (ps:262-272)."""
    model = get_model(cfg.model)

    def predict_step(state: TrainState, batch: dict) -> jnp.ndarray:
        kwargs = {} if lookup_fn is None else {"lookup_fn": lookup_fn}
        logits, _ = model.apply(
            state.params,
            state.model_state,
            batch["feat_ids"],
            batch["feat_vals"],
            cfg=cfg.model,
            train=False,
            rng=None,
            **kwargs,
        )
        return jax.nn.sigmoid(logits)

    return predict_step


def new_auc_state(num_thresholds: int = 200) -> AUCState:
    return auc_init(num_thresholds)
