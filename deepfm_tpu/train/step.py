"""Jitted train/eval/predict steps — the Estimator-loop capability (ps:492-521)
re-expressed as pure functions over an explicit ``TrainState``.

One traced, compiled function per mode (TRAIN/EVAL/PREDICT) replaces the
reference's mode-switched ``model_fn``: no graph collections, no sessions —
each step is a single XLA executable dispatched per batch, donation-friendly
so parameter buffers update in place in HBM.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

from ..core.config import Config
from ..models.base import get_model
from ..ops.auc import AUCState, auc_init, auc_update
from .optimizer import build_optimizer


class TrainState(NamedTuple):
    step: jnp.ndarray          # i32 scalar — the global_step (ps:307)
    params: Any
    model_state: Any           # non-trainable (BN moving stats)
    opt_state: Any
    rng: jax.Array             # dropout key, folded per step


def sigmoid_cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Elementwise ``tf.nn.sigmoid_cross_entropy_with_logits`` (ps:276)."""
    return jnp.maximum(logits, 0.0) - logits * labels + jnp.log1p(
        jnp.exp(-jnp.abs(logits))
    )


def make_loss_fn(cfg: Config, model, lookup_fn=None) -> Callable:
    """loss = mean CE + the model family's L2 penalty (reference: ps:275-279
    applies l2_reg·(½‖FM_W‖²+½‖FM_V‖²); each ModelDef declares its own).

    Aux carries the bare CE so every path (dense and lazy — whose 'loss' is
    CE-only, the table L2 being folded into the lazy update) can log a
    comparable ``ce`` metric; see docs/PARITY.md."""
    apply_fn, l2_penalty = model.apply, model.l2_penalty

    def loss_fn(params, model_state, batch, rng, train: bool):
        kwargs = {} if lookup_fn is None else {"lookup_fn": lookup_fn}
        logits, new_state = apply_fn(
            params,
            model_state,
            batch["feat_ids"],
            batch["feat_vals"],
            cfg=cfg.model,
            train=train,
            rng=rng,
            **kwargs,
        )
        labels = batch["label"].reshape(-1).astype(jnp.float32)
        ce = jnp.mean(sigmoid_cross_entropy(logits, labels))
        loss = ce + l2_penalty(params, cfg.model.l2_reg)
        return loss, (ce, logits, new_state)

    return loss_fn


# tables eligible for lazy updates: the CTR families gather fm_w (1-D, the
# wide term — absent in dcnv2) and fm_v (2-D) exactly once via lookup_fn
LAZY_TABLE_KEYS = ("fm_w", "fm_v")


def _lazy_keys(params: Any) -> list[str]:
    return [k for k in LAZY_TABLE_KEYS if k in params]


def _check_lazy(cfg: Config, params: Any) -> bool:
    if not cfg.optimizer.lazy_embedding_updates:
        return False
    if cfg.optimizer.name.lower() != "adam":
        raise ValueError(
            "lazy_embedding_updates supports the Adam optimizer only"
        )
    if not _lazy_keys(params):
        raise ValueError(
            f"lazy_embedding_updates needs at least one of {LAZY_TABLE_KEYS} "
            f"(CTR model families); {cfg.model.model_name!r} has "
            f"{sorted(params)}"
        )
    return True


def init_opt_state(cfg: Config, params: Any, tx) -> Any:
    """Optimizer state for ``params``: plain ``tx.init`` normally, or the
    ``(rest_opt, LazyAdamState)`` pair when lazy embedding updates are on.
    The single source of truth for the lazy state layout — the SPMD init
    (parallel/spmd.py) calls this too, so checkpoints stay interchangeable."""
    if _check_lazy(cfg, params):
        from .lazy import init_lazy_state

        keys = _lazy_keys(params)
        rest = {k: v for k, v in params.items() if k not in keys}
        tables = {k: params[k] for k in keys}
        return (tx.init(rest), init_lazy_state(tables))
    return tx.init(params)


def create_train_state(cfg: Config, key: jax.Array | None = None) -> TrainState:
    key = jax.random.PRNGKey(cfg.run.seed) if key is None else key
    init_key, step_key = jax.random.split(key)
    model = get_model(cfg.model)
    params, model_state = model.init(init_key, cfg.model)
    tx = build_optimizer(cfg.optimizer, data_parallel_size=_dp_size(cfg))
    opt_state = init_opt_state(cfg, params, tx)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        model_state=model_state,
        opt_state=opt_state,
        rng=step_key,
    )


def _dp_size(cfg: Config) -> int:
    n = cfg.mesh.data_parallel
    if n > 0:
        return n
    return max(1, jax.device_count() // max(1, cfg.mesh.model_parallel))


def make_train_step(cfg: Config, lookup_fn=None) -> Callable:
    """Build ``(state, batch) -> (state, metrics)``.  Jit it yourself or via
    pjit in ``deepfm_tpu/parallel`` — this function stays sharding-agnostic."""
    model = get_model(cfg.model)
    loss_fn = make_loss_fn(cfg, model, lookup_fn)
    tx = build_optimizer(cfg.optimizer, data_parallel_size=_dp_size(cfg))
    if cfg.optimizer.lazy_embedding_updates:
        if lookup_fn is not None:
            raise ValueError(
                "lazy_embedding_updates builds its own row lookup; custom "
                "lookup_fn (sharded tables) is the SPMD dense path"
            )
        return _make_lazy_train_step(cfg, model, tx)

    def train_step(state: TrainState, batch: dict) -> tuple[TrainState, dict]:
        step_rng = jax.random.fold_in(state.rng, state.step)
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
        (loss, (ce, logits, new_model_state)), grads = grad_fn(
            state.params, state.model_state, batch, step_rng, True
        )
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {
            "loss": loss,
            "ce": ce,
            "pred_mean": jnp.mean(jax.nn.sigmoid(logits)),
            "label_mean": jnp.mean(batch["label"].astype(jnp.float32)),
        }
        return (
            TrainState(
                step=state.step + 1,
                params=new_params,
                model_state=new_model_state,
                opt_state=new_opt_state,
                rng=state.rng,
            ),
            metrics,
        )

    return train_step


def _make_lazy_train_step(cfg: Config, model, tx) -> Callable:
    """Sparse-table variant of the train step (train/lazy.py).

    The gradient is taken w.r.t. the *gathered rows* — the dense [V, K]
    table gradient (and its scatter) never exists — and the tables update
    via touched-rows-only lazy Adam.  The CE loss drops the dense table-L2
    term (ps:275-279); its gradient ``l2·w`` is applied inside the lazy
    update on touched rows instead (see train/lazy.py semantics notes)."""
    from ..ops.embedding import dense_lookup, narrow_ids
    from .lazy import LazyAdamState, lazy_adam_update, shared_segments

    from .optimizer import build_lr_schedule, schedule_value

    # constant or step->lr schedule, evaluated at state.step inside the
    # traced step; the embedding lr split applies to the lazy tables
    # (the dense `rest` params get it via optax in build_optimizer)
    lr_sched = build_lr_schedule(cfg.optimizer, data_parallel_size=_dp_size(cfg))
    emb_mult = cfg.optimizer.embedding_lr_multiplier

    def train_step(state: TrainState, batch: dict) -> tuple[TrainState, dict]:
        lr = schedule_value(lr_sched, state.step) * emb_mult
        step_rng = jax.random.fold_in(state.rng, state.step)
        params = state.params
        keys = _lazy_keys(params)
        rest = {k: v for k, v in params.items() if k not in keys}
        tables = {k: params[k] for k in keys}
        # raw batch ids are UNVALIDATED here; narrow_ids clips to
        # [0, feature_size) before its int32 cast so an out-of-range int64
        # id cannot wrap onto an arbitrary row (see its docstring)
        ids = narrow_ids(batch["feat_ids"], cfg.model.feature_size,
                         cfg.model.narrow_ids)
        ids = ids.reshape(-1, cfg.model.field_size)
        rows = {k: dense_lookup(tables[k], ids) for k in keys}

        def loss_fn(rest, rows):
            # row substitution: the CTR families gather fm_w (1-D) and fm_v
            # (2-D) exactly once through lookup_fn, so ndim disambiguates
            def row_lookup(table, _ids):
                return rows["fm_w"] if table.ndim == 1 else rows["fm_v"]

            logits, new_state = model.apply(
                {**rest, **tables},
                state.model_state,
                batch["feat_ids"],
                batch["feat_vals"],
                cfg=cfg.model,
                train=True,
                rng=step_rng,
                lookup_fn=row_lookup,
            )
            labels = batch["label"].reshape(-1).astype(jnp.float32)
            return jnp.mean(sigmoid_cross_entropy(logits, labels)), (
                logits,
                new_state,
            )

        grad_fn = jax.value_and_grad(loss_fn, argnums=(0, 1), has_aux=True)
        (loss, (logits, new_model_state)), (g_rest, g_rows) = grad_fn(
            rest, rows
        )
        rest_opt, lazy_state = state.opt_state
        updates, new_rest_opt = tx.update(g_rest, rest_opt, rest)
        new_rest = optax.apply_updates(rest, updates)

        # one sort shared by the tables (identical ids); clip to the smallest
        # table (fm_v may carry aligned-window padding rows beyond fm_w)
        min_rows = min(tables[k].shape[0] for k in keys)
        flat_ids = jnp.clip(ids.reshape(-1), 0, min_rows - 1)
        segs = shared_segments(flat_ids, min_rows)
        step1 = state.step + 1
        new_tables, new_m, new_v = {}, {}, {}
        for key in keys:
            new_tables[key], new_m[key], new_v[key] = lazy_adam_update(
                tables[key], lazy_state.m[key], lazy_state.v[key],
                flat_ids, g_rows[key], step1, cfg.optimizer,
                learning_rate=lr, l2_reg=cfg.model.l2_reg, segmented=segs,
            )
        metrics = {
            # CE only: the table-L2 gradient is folded into the lazy update,
            # so no dense penalty term exists here; 'ce' is the cross-path
            # comparable quantity (docs/PARITY.md)
            "loss": loss,
            "ce": loss,
            "pred_mean": jnp.mean(jax.nn.sigmoid(logits)),
            "label_mean": jnp.mean(batch["label"].astype(jnp.float32)),
        }
        return (
            TrainState(
                step=step1,
                params={**new_rest, **new_tables},
                model_state=new_model_state,
                opt_state=(new_rest_opt, LazyAdamState(m=new_m, v=new_v)),
                rng=state.rng,
            ),
            metrics,
        )

    return train_step


def jitted_train_step(cfg: Config, *, donate: bool = True) -> Callable:
    """The canonical single-device compiled step: ``jax.jit`` of
    :func:`make_train_step` with the state argument DONATED, so parameter
    and optimizer buffers update in place instead of paying a full copy
    per step (the SPMD paths in ``parallel/`` already donate; this is the
    same contract for every plain-jit consumer — online trainer, replay
    oracle, benches).  The donation audit (analysis/trace_audit.py) lowers
    this function and verifies the aliasing made it into the executable.

    Donation contract for callers: the passed-in state is CONSUMED — rebind
    (``state, metrics = step(state, batch)``) and never touch the old
    reference again.  Every loop in this repo already follows that shape."""
    return jax.jit(make_train_step(cfg),
                   donate_argnums=(0,) if donate else ())


def make_eval_step(cfg: Config, lookup_fn=None) -> Callable:
    """``(state, auc_state, batch) -> (auc_state, metrics)``: loss + streaming
    AUC accumulation (the reference's eval metric, ps:282)."""
    model = get_model(cfg.model)
    loss_fn = make_loss_fn(cfg, model, lookup_fn)

    def eval_step(
        state: TrainState, auc_state: AUCState, batch: dict
    ) -> tuple[AUCState, dict]:
        loss, (_, logits, _) = loss_fn(
            state.params, state.model_state, batch, None, False
        )
        preds = jax.nn.sigmoid(logits)
        labels = batch["label"].reshape(-1)
        new_auc = auc_update(auc_state, labels, preds)
        return new_auc, {"loss": loss, "count": jnp.asarray(labels.shape[0])}

    return eval_step


def make_predict_step(cfg: Config, lookup_fn=None) -> Callable:
    """``(state, batch) -> prob [B]`` — the PREDICT/serving path (ps:262-272)."""
    model = get_model(cfg.model)

    def predict_step(state: TrainState, batch: dict) -> jnp.ndarray:
        kwargs = {} if lookup_fn is None else {"lookup_fn": lookup_fn}
        logits, _ = model.apply(
            state.params,
            state.model_state,
            batch["feat_ids"],
            batch["feat_vals"],
            cfg=cfg.model,
            train=False,
            rng=None,
            **kwargs,
        )
        return jax.nn.sigmoid(logits)

    return predict_step


def new_auc_state(num_thresholds: int = 200) -> AUCState:
    return auc_init(num_thresholds)
