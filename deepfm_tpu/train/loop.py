"""Training driver: the task dispatcher (train/eval/infer/export) over the
SPMD machinery — the ``main()`` capability of the reference scripts
(ps:389-556, hvd:331-493) without sessions, hooks, or Estimator.

The ``train`` task runs the epoch loop with periodic structured logging
(log_steps), periodic checkpointing, optional jax.profiler traces, resume-
from-latest on startup (the spot-restart capability, SURVEY §5), end-of-
training eval, and a final export — mirroring the reference's
train_and_evaluate + export flow (ps:501-521, 535-551).
"""

from __future__ import annotations

import contextlib
import itertools
import os
import time
from typing import Iterator

import jax
import numpy as np

from ..checkpoint import Checkpointer, make_checkpointer, maybe_clear, restore_resharded
from ..core.config import Config
from ..launch.preemption import PreemptedError, PreemptionGuard
from ..data.pipeline import (
    DevicePrefetcher,
    ctr_batches_from_sources,
    discover_files,
    make_input_pipeline,
)
from ..data.sharding import WorkerTopology
from ..ops.auc import auc_value
from ..parallel import (
    SPMDContext,
    build_mesh,
    create_spmd_state,
    initialize_distributed,
    make_context,
    make_spmd_eval_step,
    make_spmd_predict_step,
    make_spmd_train_loop,
    make_spmd_train_step,
    shard_batch,
    shard_batch_stacked,
)
from ..obs.trace import StepPhases
from ..serve import export_servable, write_predictions
from ..train.step import TrainState
from ..utils import MetricLogger
from .step import new_auc_state


def worker_topology(cfg: Config) -> WorkerTopology:
    return WorkerTopology(
        num_hosts=cfg.run.num_hosts,
        host_rank=cfg.run.host_rank,
        workers_per_host=cfg.run.workers_per_host,
        local_rank=0,  # one process per host in the JAX runtime model
    )


def setup(cfg: Config) -> SPMDContext:
    initialize_distributed(cfg.mesh)
    mesh = build_mesh(cfg.mesh)
    return make_context(cfg, mesh)


def _cpu_serialize_dispatch() -> bool:
    """True on the CPU backend, where sharded dispatch must be serialized.

    XLA:CPU runs every virtual device's thunks on one shared executor pool;
    with async dispatch two in-flight sharded programs can interleave so the
    second program's thunks occupy the threads the first program's
    collective rendezvous is waiting for — a deadlock (observed as
    `rendezvous.cc` watchdog kills on a 1-core host).  Blocking each step
    keeps at most one N-participant program in flight.  Virtual CPU meshes
    are a CI/test construct; TPU dispatch stays fully pipelined."""
    return jax.default_backend() == "cpu"


def _train_batches(
    cfg: Config, ctx: SPMDContext, *, skip_batches: int = 0
) -> DevicePrefetcher:
    topo = worker_topology(cfg)
    batches = make_input_pipeline(
        cfg.data,
        topo,
        field_size=cfg.model.field_size,
        channel=cfg.data.training_channel_name,
        data_dir=cfg.data.training_data_dir,
        feature_size=ctx.true_feature_size,
        seed=cfg.run.seed,
        # input-position resume: the file-mode stream is deterministic (file
        # order and shuffles are seed-derived), so the pipeline fast-forwards
        # past already-consumed batches at the raw-record level; stream mode
        # (live FIFO, fresh data) ignores the skip inside make_input_pipeline
        skip_batches=skip_batches,
    )
    k = max(1, cfg.run.steps_per_loop)
    if k == 1:
        return DevicePrefetcher(
            batches, lambda b: shard_batch(ctx, b), depth=cfg.data.prefetch_batches
        )

    # steps_per_loop: group K host batches -> ONE stacked transfer + ONE
    # K-step scan dispatch.  The stream tail (< K batches left) falls back
    # to single-step items so no record is dropped or duplicated.
    def chunked(it):
        buf = []
        for b in it:
            buf.append(b)
            if len(buf) == k:
                yield ("stack", buf)
                buf = []
        for b in buf:
            yield ("one", b)

    def place(item):
        tag, payload = item
        if tag == "stack":
            return tag, shard_batch_stacked(ctx, payload)
        return tag, shard_batch(ctx, payload)

    return DevicePrefetcher(
        chunked(batches), place, depth=cfg.data.prefetch_batches
    )


def _padded_batches(
    batches: Iterator[dict], dp: int
) -> Iterator[tuple[dict, int]]:
    """Pads each batch (notably the tail) to the data-parallel multiple;
    yields (batch, true_count) so metrics can exclude the padding.  Takes a
    batch *iterator* so eval/infer memory stays O(batch), independent of
    channel size."""
    for batch in batches:
        b = int(batch["label"].shape[0])
        pad = (-b) % dp
        if pad:
            batch = {
                k: np.concatenate([v, np.repeat(v[-1:], pad, 0)])
                for k, v in batch.items()
            }
        yield batch, b


def _eval_channel_path(cfg: Config) -> str:
    """Stream-mode evaluation channel FIFO: ``<dir>/<evaluation_channel>``
    (the reference reads eval data from the 'evaluation' channel in pipe
    mode, hvd:420-424, README.md:81)."""
    base = cfg.data.val_data_dir or cfg.data.training_data_dir
    return os.path.join(base, cfg.data.evaluation_channel_name)


def _has_eval_source(cfg: Config) -> bool:
    if cfg.data.stream_mode:
        return os.path.exists(_eval_channel_path(cfg))
    return bool(cfg.data.val_data_dir)


def _eval_batches(cfg: Config, ctx: SPMDContext) -> Iterator[dict]:
    """Host batches of the evaluation source, streamed incrementally.

    Never materializes the channel: both the FIFO (pipe-mode) and file paths
    decode record-by-record through ``ctr_batches_from_sources``, so eval
    memory is O(batch_size) regardless of channel size — the capability the
    reference delegated to tf.data's streaming evaluate (hvd:436-441)."""
    permute = ctx.true_feature_size if cfg.data.permute_ids else 0
    if cfg.data.stream_mode:
        # bounded channel read: until the writer closes the FIFO (EOF), or
        # eval_max_batches when set (a live channel may never close).  Each
        # eval pass opens the channel anew — the feeder re-fills it per eval,
        # mirroring pipe-mode's one-FIFO-per-pass semantics.
        fifo = _eval_channel_path(cfg)
        if not os.path.exists(fifo):
            raise FileNotFoundError(
                f"stream_mode eval needs the evaluation channel at {fifo!r} "
                f"(data.evaluation_channel_name)"
            )
        sources = [fifo]
    else:
        base = cfg.data.val_data_dir or cfg.data.training_data_dir
        sources = discover_files(base, patterns=("va", "val", "eval"), shuffle=False)
        if not sources:
            raise FileNotFoundError(f"no va*/val*/eval* tfrecords under {base!r}")
    batches = ctr_batches_from_sources(
        sources,
        batch_size=cfg.data.batch_size,
        field_size=cfg.model.field_size,
        drop_remainder=False,
        permute_vocab=permute,
    )
    if cfg.data.stream_mode and cfg.data.eval_max_batches > 0:
        batches = itertools.islice(batches, cfg.data.eval_max_batches)
    return batches


def restore_latest(
    ckpt: Checkpointer, ctx: SPMDContext, state: TrainState,
    log: MetricLogger | None = None,
) -> TrainState:
    """Restore the latest checkpoint into the running mesh: exact-shape
    restore first; on a table-shape mismatch (the checkpoint was written
    under a different mesh topology — padded vocab differs) fall back to
    the cross-topology resharding restore."""
    try:
        return ckpt.restore(state)
    except Exception as e:
        msg = str(e)
        if not any(k in msg for k in ("shape", "Sizes", "fm_v", "embedding")):
            raise
        if log is not None:
            log.event("resume_reshard", reason=msg[:200])
        return restore_resharded(ckpt, ctx)


def run_eval(cfg: Config, ctx: SPMDContext, state: TrainState, log: MetricLogger) -> dict:
    """EVAL task: streaming AUC + mean loss over the FULL validation set
    (ps:282, ps:522-525).  Tail batches are padded to the data-parallel
    multiple with zero-weight rows, so every record counts exactly once."""
    eval_step = make_spmd_eval_step(ctx)
    dp = ctx.mesh.shape["data"]
    nproc, pid = jax.process_count(), jax.process_index()
    # feeding policy across processes:
    #   dp % nproc == 0 -> each process feeds its row slice (exact partition)
    #   dp == 1         -> the single data row spans processes via the model
    #                      axis; every process feeds the identical full batch
    #                      and assembly replicates it (no double-count)
    #   otherwise       -> data rows straddle process boundaries; neither
    #                      scheme is well-defined — fail loudly
    slice_rows = nproc > 1 and dp % nproc == 0
    if nproc > 1 and not slice_rows and dp != 1:
        raise ValueError(
            f"multi-process eval needs the data axis ({dp}) divisible by "
            f"the process count ({nproc}), or data_parallel=1 (replicated "
            f"feed); this mesh straddles data rows across processes"
        )
    auc_state = new_auc_state()
    loss_sum, counts = 0.0, 0
    fed_rows = 0.0  # non-padding rows THIS process placed on the mesh
    for batch, true_count in _padded_batches(_eval_batches(cfg, ctx), dp):
        b = batch["label"].shape[0]
        batch["weight"] = np.concatenate(
            [np.ones(true_count, np.float32), np.zeros(b - true_count, np.float32)]
        )
        if slice_rows:
            # every process reads the IDENTICAL global stream (collective
            # eval steps must stay in lockstep — per-process sharding could
            # leave uneven step counts and deadlock); each feeds only its
            # row slice, so no record enters the global batch twice.  b is
            # a dp multiple (padded above) and dp % nproc == 0 (checked),
            # so the slices partition the batch exactly.
            lb = b // nproc
            batch = {k: v[pid * lb : (pid + 1) * lb] for k, v in batch.items()}
        fed_rows += float(batch["weight"].sum())
        sb = shard_batch(ctx, batch)
        auc_state, m = eval_step(state, auc_state, sb)
        # float(m["loss"]) below blocks per batch, which also keeps CPU-mesh
        # dispatch serialized (see _cpu_serialize_dispatch)
        loss_sum += float(m["loss"]) * true_count
        counts += true_count
    result = {
        "auc": float(auc_value(auc_state)),
        "loss": (loss_sum / counts) if counts else float("nan"),
        "examples": counts,
        # the observable no-double-feed invariant: sums to `examples`
        # across processes when rows are sliced (dp % nproc == 0); equals
        # `examples` on every process in the replicated dp==1 feed (the
        # assembly deduplicates replicas there, not the feed)
        "fed_rows": int(fed_rows),
    }
    log.event("eval", **result)
    return result


def run_train(cfg: Config) -> TrainState:
    """TRAIN task: resume-or-init, epoch loop, periodic ckpt, final eval+export."""
    if cfg.model.tiered_embeddings:
        return run_train_tiered(cfg)
    # Handlers install BEFORE setup: a spot/maintenance SIGTERM is likeliest
    # during the expensive create/compile/restore phase of a big job, and
    # before round 4 it hit the default handler there (uncaught kill, no
    # clean exit — round-3 verdict weak #1).  A mid-setup signal now lets
    # setup finish, skips the train loop, persists the initialized/restored
    # state, and raises PreemptedError like a mid-loop one.
    with PreemptionGuard() as guard:
        return _run_train_guarded(cfg, guard)


def run_train_tiered(cfg: Config):
    """TRAIN task, tiered giant-vocab mode (``model.tiered_embeddings``):
    the table pages through the HBM←host←object-store tiers
    (deepfm_tpu/tiered) instead of living resident.  Single-controller:
    the hot cache is one device's budget (row-sharding a paged cache is
    the ROADMAP's distributed-serving follow-on).

    Same rhythm as the resident loop — resume-or-init, epoch feed with
    the id-stream prefetch observer, periodic STREAMING paged
    checkpoints, preemption-safe save — and a final ``publish_tiered``
    (consistent cold-tier snapshot in the manifest) when a servable dir
    is configured.  Returns the final ``PagedState``."""
    if jax.process_count() > 1 or cfg.mesh.model_parallel > 1:
        raise RuntimeError(
            "tiered embeddings are single-process, model_parallel=1 "
            "(the hot cache lives on one device); drop the mesh flags "
            "or use the resident row-sharded path"
        )
    from ..tiered import TieredTrainer

    log = MetricLogger(log_steps=cfg.run.log_steps)
    maybe_clear(cfg.run.model_dir, cfg.run.clear_existing_model)
    ckpt_dir = os.path.join(cfg.run.model_dir, "tiered_ckpt")
    cold_root = cfg.model.tiered_cold_url or os.path.join(
        cfg.run.model_dir, "cold"
    )
    with PreemptionGuard() as guard:
        if os.path.exists(os.path.join(ckpt_dir, "tiered_meta.json")):
            trainer = TieredTrainer.restore(cfg, ckpt_dir, virtual=True)
            log.event("resume", step=int(trainer.state.step))
        else:
            trainer = TieredTrainer.create_virtual(cfg, cold_root)
        step = int(trainer.state.step)
        log.seed_step(step)
        topo = worker_topology(cfg)
        batches = make_input_pipeline(
            cfg.data,
            topo,
            field_size=cfg.model.field_size,
            channel=cfg.data.training_channel_name,
            data_dir=cfg.data.training_data_dir,
            feature_size=cfg.model.feature_size,
            seed=cfg.run.seed,
            skip_batches=step,
        )
        # the observer IS the cold→host prefetch: this feed sees batches
        # prefetch_batches ahead of the step consuming them
        feed = DevicePrefetcher(
            batches, lambda b: b, depth=cfg.data.prefetch_batches,
            observer=trainer.observer(),
        )
        ckpt_every = cfg.run.checkpoint_every_steps
        with feed:
            for batch in feed:
                if guard.should_stop:
                    break
                metrics = trainer.train_batch(batch)
                step += 1
                log.step(step, int(batch["label"].shape[0]), metrics)
                if ckpt_every and step % ckpt_every == 0:
                    trainer.save(ckpt_dir)
        trainer.save(ckpt_dir)
        if guard.should_stop:
            log.event("preempted", step=step)
            trainer.close()
            raise PreemptedError(f"preempted at step {step}")
        if cfg.run.servable_model_dir:
            from ..online.publisher import ModelPublisher

            manifest = ModelPublisher(
                cfg.run.servable_model_dir,
                keep=cfg.run.keep_checkpoints,
                keep_window=cfg.regions.publish_keep_window,
            ).publish_tiered(cfg, trainer)
            log.event("publish_tiered", version=manifest.version,
                      step=manifest.step)
        paging = trainer.paging_snapshot()
        log.event("tiered_done", step=step,
                  hit_rate=paging["pager"]["hit_rate"])
        state = trainer.state
        trainer.close()
        return state


def _run_train_guarded(cfg: Config, guard: PreemptionGuard) -> TrainState:
    ctx = setup(cfg)
    maybe_clear(cfg.run.model_dir, cfg.run.clear_existing_model)
    log = MetricLogger(log_steps=cfg.run.log_steps)
    # checkpoint cadence lives HERE (the step % N gate below) — Checkpointer
    # itself has no interval policy, so there is exactly one mechanism
    ckpt = make_checkpointer(cfg.run.model_dir, max_to_keep=cfg.run.keep_checkpoints)
    state = create_spmd_state(ctx)
    if ckpt.latest_step() is not None:
        state = restore_latest(ckpt, ctx, state, log)
        log.event("resume", step=int(state.step))
    train_step = make_spmd_train_step(ctx)
    steps_per_loop = max(1, cfg.run.steps_per_loop)
    loop_step = (
        make_spmd_train_loop(ctx, steps_per_loop) if steps_per_loop > 1 else None
    )

    profile_cm = (
        jax.profiler.trace(cfg.run.profile_dir)
        if cfg.run.profile_dir
        else contextlib.nullcontext()
    )
    # host-side step counter: int(state.step) every iteration would block on
    # the just-dispatched step and defeat async-dispatch pipelining
    step = int(state.step)
    log.seed_step(step)
    # when a schedule is active, surface the live lr on each logged line
    # (evaluated only on emitting calls — MetricLogger.step `extra`).
    # ctx.cfg, not cfg: make_context resolved mesh.data_parallel (the raw
    # config may carry the -1 auto sentinel).  The last update in the
    # logged window ran at schedule(step - 1) — optax and the lazy path
    # both evaluate the schedule at the PRE-increment count — so that is
    # the value reported.
    from ..train.optimizer import build_lr_schedule, schedule_value

    lr_sched = build_lr_schedule(
        ctx.cfg.optimizer, data_parallel_size=ctx.cfg.mesh.data_parallel
    )
    # step-phase spans (obs/trace.py): where each logged window's host
    # time went — input-pipeline wait vs host bookkeeping vs device
    # dispatch — attributable from the metrics line alone, no profiler.
    # Evaluated only on emitting calls (MetricLogger.step `extra`), like
    # the scheduled lr below.
    phases = StepPhases()

    def lr_extra():
        out = phases.snapshot_ms()
        if callable(lr_sched):
            out["lr"] = float(schedule_value(lr_sched, max(0, step - 1)))
        return out
    # periodic in-training eval, the train_and_evaluate cadence (ps:510-520):
    # no eval before start_delay, then at most one per throttle interval.
    # 0/0 (default) means end-of-training eval only — the reference's values
    # (1000/1200) are config away (run.eval_start_delay_secs/throttle_secs)
    eval_enabled = _has_eval_source(cfg) and cfg.run.eval_throttle_secs > 0
    t_start = time.time()
    next_eval = t_start + max(cfg.run.eval_start_delay_secs, cfg.run.eval_throttle_secs)
    cpu_serial = _cpu_serialize_dispatch()
    ckpt_every = cfg.run.checkpoint_every_steps
    # a signal during setup skips the loop entirely (empty feed): the state
    # still gets persisted below and the run raises PreemptedError cleanly
    feed_cm = (
        _train_batches(cfg, ctx, skip_batches=step)
        if not guard.should_stop
        else contextlib.nullcontext(())
    )
    _END = object()
    with profile_cm, feed_cm as batches:
        it = iter(batches)
        while True:
            # data_wait: time blocked on the input pipeline's next item
            with phases.phase("data_wait"):
                item = next(it, _END)
            if item is _END:
                break
            if steps_per_loop > 1:
                tag, batch = item
            else:
                tag, batch = "one", item
            if tag == "stack":
                # K fused optimizer steps; metrics come back stacked [K] —
                # log the last sub-step's values (no extra device sync)
                with phases.phase("dispatch"):
                    state, stacked_metrics = loop_step(state, batch)
                    if cpu_serial:
                        jax.block_until_ready(stacked_metrics)
                metrics = {k: v[-1] for k, v in stacked_metrics.items()}
                inc = steps_per_loop
                batch_size = int(batch["label"].shape[1]) * inc
            else:
                with phases.phase("dispatch"):
                    state, metrics = train_step(state, batch)
                    if cpu_serial:
                        jax.block_until_ready(metrics)
                inc = 1
                batch_size = int(batch["label"].shape[0])
            step += inc
            phases.step_done(inc)
            with phases.phase("host"):
                log.step(step, batch_size,
                         {k: v for k, v in metrics.items()
                          if k != "loss_per_shard"},
                         extra=lr_extra)
                # boundary-crossing test: a K-step dispatch may jump past
                # the exact multiple (same as `step % N == 0` when inc == 1)
                if (ckpt_every
                        and step // ckpt_every > (step - inc) // ckpt_every):
                    ckpt.save(state)
            if eval_enabled and time.time() >= next_eval:
                run_eval(cfg, ctx, state, log)
                next_eval = time.time() + cfg.run.eval_throttle_secs
            if guard.should_stop:
                break

    ckpt.save(state)
    if guard.should_stop:
        # spot/maintenance interruption: persist and stop without the final
        # eval/export — the next run of the same command resumes from this
        # checkpoint (restore-on-startup above).  Raising (rather than
        # returning) lets supervisors distinguish preemption from completion;
        # the CLI converts it to a clean exit 0, and run_with_restarts never
        # retries it (the platform that sent the signal owns the reschedule)
        log.event("preempted", step=step)
        ckpt.close()
        raise PreemptedError(f"preempted at step {step}")
    if _has_eval_source(cfg):
        run_eval(cfg, ctx, state, log)
    if cfg.run.servable_model_dir:
        # ctx.cfg, not cfg: the servable config must record the mesh-PADDED
        # vocab so load_servable's restore target matches the saved shapes
        export_servable(ctx.cfg, state, cfg.run.servable_model_dir)
        log.event("export", path=cfg.run.servable_model_dir)
    ckpt.close()
    return state


def run_infer(cfg: Config, *, output_path: str | None = None) -> str:
    """INFER task: batch-score te*/test* records to pred.txt (ps:526-533)."""
    ctx = setup(cfg)
    if jax.process_count() > 1:
        # predict output is data-sharded across processes; device_get of
        # non-addressable shards cannot work.  The reference's infer is a
        # single-host batch job too (ps:526-533) — run it that way.
        raise RuntimeError(
            "task_type=infer is a single-process batch job; run it without "
            "DEEPFM_COORDINATOR (the trained model_dir restores fine on one "
            "process — shardings adapt to the local mesh)"
        )
    ckpt = make_checkpointer(cfg.run.model_dir)
    state = restore_latest(ckpt, ctx, create_spmd_state(ctx))
    predict_step = make_spmd_predict_step(ctx)
    # fallback chain, not a union: te*/test* first (the reference's infer
    # globs te* only, ps:526-533); va*/val* only when no test files exist
    base = cfg.data.test_data_dir or cfg.data.val_data_dir
    files = discover_files(base, patterns=("te", "test"), shuffle=False)
    if not files:
        files = discover_files(base, patterns=("va", "val"), shuffle=False)
    if not files:
        raise FileNotFoundError("no te*/test* (or va*/val*) tfrecords to score")
    batches = ctr_batches_from_sources(
        files,
        batch_size=cfg.data.batch_size,
        field_size=cfg.model.field_size,
        drop_remainder=False,
        permute_vocab=ctx.true_feature_size if cfg.data.permute_ids else 0,
    )
    out = output_path or os.path.join(base, "pred.txt")

    def _probs() -> Iterator[np.ndarray]:
        # generator, not a list: predictions stream to disk batch-by-batch,
        # so infer memory is O(batch) like eval (ps:526-533 writes per line)
        for batch, true_count in _padded_batches(batches, ctx.mesh.shape["data"]):
            sb = shard_batch(ctx, batch)
            p = np.asarray(jax.device_get(predict_step(state, sb)))
            yield p[:true_count]

    n = write_predictions(_probs(), out)
    ckpt.close()
    MetricLogger().event("infer", path=out, examples=n)
    return out


def run_export(cfg: Config) -> str:
    """EXPORT task: restore latest checkpoint -> servable (ps:535-551)."""
    ctx = setup(cfg)
    ckpt = make_checkpointer(cfg.run.model_dir)
    state = restore_latest(ckpt, ctx, create_spmd_state(ctx))
    path = export_servable(ctx.cfg, state, cfg.run.servable_model_dir)
    ckpt.close()
    MetricLogger().event("export", path=path)
    return path


def _retrieval_setup(cfg: Config):
    from ..parallel.retrieval import make_retrieval_context

    initialize_distributed(cfg.mesh)
    mesh = build_mesh(cfg.mesh)
    return make_retrieval_context(cfg, mesh)


def _retrieval_batches(cfg: Config, ctx, data_dir: str, *, num_epochs: int,
                       shuffle: bool):
    from ..data.ratings import RatingsDataset

    ds = RatingsDataset.from_path(data_dir)
    max_u, max_i = ds.max_ids()
    if max_u >= ctx.true_user_vocab or max_i >= ctx.true_item_vocab:
        raise ValueError(
            f"ratings ids exceed configured vocabs: max user {max_u} vs "
            f"user_vocab_size {ctx.true_user_vocab}, max item {max_i} vs "
            f"item_vocab_size {ctx.true_item_vocab} — set model.user_vocab_size/"
            f"model.item_vocab_size"
        )
    min_u, min_i = ds.min_ids()
    if min_u < 0 or min_i < 0:
        # full range check here is what lets the hot loop pass
        # validate_ids=False: without it a negative id would silently train
        # on a masked-to-zero embedding row
        raise ValueError(
            f"ratings contain negative ids (min user {min_u}, min item {min_i})"
        )
    return ds.batches(
        cfg.data.batch_size, num_epochs=num_epochs, shuffle=shuffle,
        seed=cfg.run.seed,
    )


def run_retrieval_train(cfg: Config) -> TrainState:
    """TRAIN for the two-tower family: ratings file(s) in, in-batch-softmax
    SPMD steps, periodic ckpt, final retrieval eval + servable export."""
    # guard installs before setup/compile/restore, same rationale as
    # run_train (round-3 verdict weak #1)
    with PreemptionGuard() as guard:
        return _run_retrieval_train_guarded(cfg, guard)


def _run_retrieval_train_guarded(
    cfg: Config, guard: PreemptionGuard
) -> TrainState:
    from ..parallel.retrieval import (
        create_retrieval_spmd_state,
        make_retrieval_spmd_train_step,
        shard_retrieval_batch,
    )

    ctx = _retrieval_setup(cfg)
    maybe_clear(cfg.run.model_dir, cfg.run.clear_existing_model)
    log = MetricLogger(log_steps=cfg.run.log_steps)
    ckpt = make_checkpointer(cfg.run.model_dir, max_to_keep=cfg.run.keep_checkpoints)
    state = create_retrieval_spmd_state(ctx)
    if ckpt.latest_step() is not None:
        state = ckpt.restore(state)
        log.event("resume", step=int(state.step))
    train_step = make_retrieval_spmd_train_step(ctx)

    step = int(state.step)
    log.seed_step(step)
    if guard.should_stop:
        # mid-setup signal: skip feed construction entirely (it loads and
        # range-checks the whole ratings dataset) — persist and stop cleanly
        batches = iter(())
    else:
        batches = _retrieval_batches(
            cfg, ctx, cfg.data.training_data_dir,
            num_epochs=cfg.data.num_epochs, shuffle=True,
        )
        if step:
            # input-position resume (same contract as _train_batches): the
            # ratings batch stream is seed-deterministic, so skip what the
            # interrupted run already consumed
            batches = itertools.islice(batches, step, None)
    with DevicePrefetcher(
        # validate_ids=False: _retrieval_batches already range-checked the
        # whole dataset against both vocabs
        batches, lambda b: shard_retrieval_batch(ctx, b, validate_ids=False),
        depth=cfg.data.prefetch_batches,
    ) as prefetched:
        for batch in prefetched:
            batch_size = int(batch["user_ids"].shape[0])
            state, metrics = train_step(state, batch)
            step += 1
            log.step(step, batch_size, metrics)
            if cfg.run.checkpoint_every_steps and step % cfg.run.checkpoint_every_steps == 0:
                ckpt.save(state)
            if guard.should_stop:
                break

    ckpt.save(state)
    if guard.should_stop:
        log.event("preempted", step=step)
        ckpt.close()
        raise PreemptedError(f"preempted at step {step}")
    if cfg.data.val_data_dir:
        run_retrieval_eval(cfg, ctx, state, log)
    if cfg.run.servable_model_dir:
        export_servable(ctx.cfg, state, cfg.run.servable_model_dir)
        log.event("export", path=cfg.run.servable_model_dir)
    ckpt.close()
    return state


def run_retrieval_eval(cfg: Config, ctx, state: TrainState, log: MetricLogger) -> dict:
    """EVAL for two-tower: mean in-batch-softmax loss + top1/recall@10 over
    full batches of the validation ratings (remainder dropped: in-batch
    metrics need a constant candidate-pool size to be comparable)."""
    from ..parallel.retrieval import (
        make_retrieval_spmd_eval_step,
        shard_retrieval_batch,
    )

    eval_step = make_retrieval_spmd_eval_step(ctx)
    sums: dict[str, float] = {}
    batches = 0
    for batch in _retrieval_batches(
        cfg, ctx, cfg.data.val_data_dir, num_epochs=1, shuffle=False,
    ):
        m = eval_step(state, shard_retrieval_batch(ctx, batch))
        batches += 1
        for k, v in m.items():
            sums[k] = sums.get(k, 0.0) + float(v)
    if not batches:
        raise ValueError(
            f"validation ratings under {cfg.data.val_data_dir!r} have fewer "
            f"rows than one batch ({cfg.data.batch_size}) — nothing to eval"
        )
    result = {
        "loss": sums["loss"] / batches,
        "top1_acc": sums["top1_acc"] / batches,
        "recall_at_10": sums["recall_at_10"] / batches,
        "examples": sums["count"],
    }
    log.event("eval", **result)
    return result


def run_retrieval_task(cfg: Config):
    """Two-tower task dispatch: train | eval | export (infer has no meaning
    without a candidate corpus to rank — use eval, or load the servable and
    encode corpora with models.two_tower.apply_two_tower)."""
    from ..parallel.retrieval import create_retrieval_spmd_state

    task = cfg.run.task_type
    if task == "train":
        return run_retrieval_train(cfg)
    if task == "eval":
        ctx = _retrieval_setup(cfg)
        ckpt = make_checkpointer(cfg.run.model_dir)
        state = ckpt.restore(create_retrieval_spmd_state(ctx))
        result = run_retrieval_eval(cfg, ctx, state, MetricLogger())
        ckpt.close()
        return result
    if task == "export":
        ctx = _retrieval_setup(cfg)
        ckpt = make_checkpointer(cfg.run.model_dir)
        state = ckpt.restore(create_retrieval_spmd_state(ctx))
        path = export_servable(ctx.cfg, state, cfg.run.servable_model_dir)
        ckpt.close()
        MetricLogger().event("export", path=path)
        return path
    raise ValueError(
        f"task_type {task!r} unsupported for two_tower (train|eval|export)"
    )


def run_task(cfg: Config):
    """task_type dispatch (ps:501-551): train | eval | infer | export,
    plus ``serve`` — online scoring over the exported servable (the
    TF-Serving step of the reference's workflow, serve/server.py)."""
    task = cfg.run.task_type
    # arm the flight-recorder termination dump (obs/flight.py): the
    # train-family tasks below run under a PreemptionGuard, so a SIGTERM
    # or crash writes model_dir/flight.jsonl — the correlated incident
    # timeline — next to the checkpoint the guard was preserving.  The
    # serve task skips it here: serve processes have no guard and expose
    # the live ring at GET /v1/flight (plus --flight-dump on their CLIs).
    if cfg.run.model_dir and task != "serve":
        from ..obs import flight as obs_flight

        obs_flight.install(os.path.join(cfg.run.model_dir, "flight.jsonl"))
    if task in ("feedback-train", "feedback_train"):
        # the data flywheel's training leg (deepfm_tpu/flywheel): the
        # SAME online trainer (elastic path included), cursoring the
        # delayed-label join's output stream instead of a hand-fed event
        # log — config validation already required join_output_url
        cfg = cfg.with_overrides(
            data={"training_data_dir": cfg.flywheel.join_output_url},
            run={"task_type": "online-train"},
        )
        task = "online-train"
    if task in ("online-train", "online_train"):
        # continuous training from the event log at training_data_dir,
        # publishing versioned servables the serve task hot-reloads
        # (online/trainer.py; the online half of the train->serve loop).
        # With elastic.enabled the mesh shape becomes a runtime variable:
        # the controller reshards live on device loss/regain instead of
        # dying with the mesh (deepfm_tpu/elastic)
        if cfg.elastic.enabled:
            from ..elastic import run_elastic_train

            return run_elastic_train(cfg)
        from ..online.trainer import run_online_train

        return run_online_train(cfg)
    if task == "publish":
        # the MPMD publisher half of the elastic trainer/publisher split
        # (elastic/mpmd.py): tail committed payloads in model_dir and
        # publish versioned servables asynchronously — a publish-store
        # outage degrades freshness, never the trainer's hot loop
        from ..elastic.mpmd import run_publisher

        return run_publisher(cfg)
    if task in ("region-front", "region_front"):
        # cross-region control process (deepfm_tpu/region): the async
        # manifest replicator tailing cfg.regions.home_root into every
        # region store plus the front tier (home-region routing,
        # staleness-SLO drain, budgeted failover).  Host-only — the
        # per-region pools are their own `task_type=serve` processes.
        from ..region import run_region_front

        return run_region_front(cfg)
    if task == "serve":
        from ..serve.server import serve_forever, serve_pool

        if cfg.run.serve_groups > 0:
            # the router-fronted shard-group pool (serve/pool/): tables
            # row-sharded over each group's mesh, group-atomic hot swap,
            # supervised member processes
            from ..serve.pool.__main__ import main as pool_main

            argv = [
                "--servable", cfg.run.servable_model_dir, "--router",
                "--groups", str(cfg.run.serve_groups),
                "--group-dp", str(cfg.run.serve_group_data_parallel),
                "--group-mp", str(cfg.run.serve_group_model_parallel),
                "--port", str(cfg.run.serve_router_port),
                "--host", cfg.run.serve_host,
                "--buckets", cfg.run.serve_buckets,
                "--max-wait-ms", str(cfg.run.serve_max_wait_ms),
                "--retry-limit", str(cfg.run.serve_retry_limit),
                "--eject-after", str(cfg.run.serve_eject_after),
                "--health-interval",
                str(cfg.run.serve_health_interval_secs),
            ]
            if cfg.run.serve_reload_url:
                argv += ["--reload-url", cfg.run.serve_reload_url,
                         "--reload-interval",
                         str(cfg.run.serve_reload_interval_secs)]
            if cfg.fleet.tenants:
                # multi-tenant fleet (deepfm_tpu/fleet): members serve
                # every tenant from one executable set; the router
                # splits traffic and runs shadow challengers
                import json as _json

                argv += [
                    "--tenants", _json.dumps(list(cfg.fleet.tenants)),
                    "--shadow-sample",
                    str(cfg.fleet.shadow_sample_percent),
                    "--shadow-queue", str(cfg.fleet.shadow_queue_depth),
                ]
            if cfg.run.funnel_top_k:
                argv += ["--funnel-top-k", str(cfg.run.funnel_top_k)]
            if cfg.run.funnel_return_n:
                argv += ["--funnel-return-n", str(cfg.run.funnel_return_n)]
            if cfg.run.funnel_retrieval != "exact":
                argv += ["--funnel-retrieval", cfg.run.funnel_retrieval]
            if cfg.run.funnel_oversample != 4:
                argv += ["--funnel-oversample",
                         str(cfg.run.funnel_oversample)]
            if cfg.run.funnel_pallas != "auto":
                argv += ["--funnel-pallas", cfg.run.funnel_pallas]
            if cfg.flywheel.enabled:
                # data flywheel (deepfm_tpu/flywheel): the router logs
                # a hash-stable sample of scored impressions for the
                # delayed-label join
                fw = cfg.flywheel
                argv += [
                    "--flywheel-log", fw.impression_log_url,
                    "--flywheel-sample", str(fw.sample_rate),
                    "--flywheel-roll-bytes", str(fw.segment_roll_bytes),
                    "--flywheel-roll-age",
                    str(fw.segment_roll_age_secs),
                    "--flywheel-queue", str(fw.queue_depth),
                ]
                if fw.join_output_url:
                    argv += ["--flywheel-join-out", fw.join_output_url]
            pool_main(argv)
            return None
        if cfg.run.serve_workers > 1:
            serve_pool(
                cfg.run.servable_model_dir,
                workers=cfg.run.serve_workers,
                port=cfg.run.serve_port,
                host=cfg.run.serve_host,
                buckets=cfg.run.serve_buckets,
                max_wait_ms=cfg.run.serve_max_wait_ms,
                item_corpus=cfg.run.serve_item_corpus or None,
                reload_url=cfg.run.serve_reload_url or None,
                reload_interval_secs=cfg.run.serve_reload_interval_secs,
                funnel_top_k=cfg.run.funnel_top_k,
                funnel_return_n=cfg.run.funnel_return_n,
                funnel_retrieval=("" if cfg.run.funnel_retrieval == "exact"
                                  else cfg.run.funnel_retrieval),
                funnel_oversample=(0 if cfg.run.funnel_oversample == 4
                                   else cfg.run.funnel_oversample),
                funnel_pallas=("" if cfg.run.funnel_pallas == "auto"
                               else cfg.run.funnel_pallas),
            )
            return None
        serve_forever(
            cfg.run.servable_model_dir,
            port=cfg.run.serve_port,
            host=cfg.run.serve_host,
            buckets=cfg.run.serve_buckets,
            max_wait_ms=cfg.run.serve_max_wait_ms,
            item_corpus=cfg.run.serve_item_corpus or None,
            reload_url=cfg.run.serve_reload_url or None,
            reload_interval_secs=cfg.run.serve_reload_interval_secs,
            funnel_top_k=cfg.run.funnel_top_k,
            funnel_return_n=cfg.run.funnel_return_n,
            # config defaults defer to the servable's published retrieval
            # section (the funnel_top_k=0 convention); a non-default
            # value is an explicit operator override
            funnel_retrieval=("" if cfg.run.funnel_retrieval == "exact"
                              else cfg.run.funnel_retrieval),
            funnel_oversample=(0 if cfg.run.funnel_oversample == 4
                               else cfg.run.funnel_oversample),
            funnel_pallas=("" if cfg.run.funnel_pallas == "auto"
                           else cfg.run.funnel_pallas),
        )
        return None
    if cfg.model.model_name == "two_tower":
        return run_retrieval_task(cfg)
    if task == "train":
        return run_train(cfg)
    if task == "eval":
        ctx = setup(cfg)
        ckpt = make_checkpointer(cfg.run.model_dir)
        state = restore_latest(ckpt, ctx, create_spmd_state(ctx))
        result = run_eval(cfg, ctx, state, MetricLogger())
        ckpt.close()
        return result
    if task == "infer":
        return run_infer(cfg)
    if task == "export":
        return run_export(cfg)
    raise ValueError(
        f"unknown task_type {task!r} "
        f"(train|eval|infer|export|serve|online-train|feedback-train|"
        f"publish|region-front)"
    )
