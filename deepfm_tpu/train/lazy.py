"""Lazy (sparse) Adam for embedding tables — touched-rows-only updates.

Dense Adam reads and writes the full [V, K] table plus both moments every
step (~6·V·K·4 bytes of HBM traffic) even though a batch touches at most
B·F rows.  At the reference vocabulary (117,581×32) that is ~90 MB/step —
already the dominant step cost on one chip — and at the 100M-row north star
it is simply impossible.  TF1 solved this with ``sparse_apply_adam`` over
``IndexedSlices`` (what the reference's Adam does for its embedding gathers
when no dense term forces densification); this module is the JAX/TPU
equivalent:

    gather rows -> grad w.r.t. ROWS (never a dense table grad)
    sort ids -> segment-sum duplicate rows (Adam is nonlinear: one summed
    update per unique row, not per occurrence)
    gather m/v rows -> Adam math on [N, K] -> masked delta scatter-add

Everything is fixed-shape (N = B·F with zero-masked padding segments), so
it jits cleanly.  Semantics notes:

- Moment decay is lazy (untouched rows keep stale m/v — LazyAdam semantics,
  not bias-exact Adam).  Bias correction uses the global step.
- L2 regularization is applied as a gradient term ``l2·w`` on touched rows
  only, once per unique row (the reference's dense ``l2_loss`` term adds
  ``l2·w`` to every row every step — lazy trades that for sparsity, the
  standard lazy-regularization approximation).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core.config import OptimizerConfig


class LazyAdamState(NamedTuple):
    m: dict        # per-table first moment, full table shape
    v: dict        # per-table second moment, full table shape


def init_lazy_state(tables: dict) -> LazyAdamState:
    zeros = {k: jnp.zeros_like(t) for k, t in tables.items()}
    return LazyAdamState(m=zeros, v={k: jnp.zeros_like(t) for k, t in tables.items()})


def segment_rows(flat_ids: jnp.ndarray, flat_grads: jnp.ndarray,
                 id_bound: int | None = None):
    """Dedup row updates: (ids [N], grads [N, K]) ->
    (row_id [N], summed [N, K], valid [N]) where only the first U entries
    (U = unique count) are live; the rest are zero-masked padding.
    ``id_bound``: static exclusive upper bound on the (non-negative) ids,
    unlocking the packed single-key sort (ops/embedding.py)."""
    order, seg, row_id, valid = shared_segments(flat_ids, id_bound)
    summed = jax.ops.segment_sum(
        flat_grads[order], seg, num_segments=flat_ids.shape[0],
        indices_are_sorted=True,
    )
    return row_id, summed, valid


def adam_row_math(
    p_r: jnp.ndarray,
    m_r: jnp.ndarray,
    v_r: jnp.ndarray,
    gsum: jnp.ndarray,
    step: jnp.ndarray,
    cfg: OptimizerConfig,
    *,
    learning_rate: float,
    l2_reg: float = 0.0,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The per-row Adam arithmetic on gathered rows [N, W]: lazy-L2 fold,
    moment update, bias correction, parameter step.  ONE implementation
    shared by the dense-id update, the shard-local update, and the tiered
    hot-cache (slot-space) step — bit-parity between those paths
    (tests/test_tiered.py) holds because they run THIS function on the
    same values.  Returns (new_p, new_m, new_v) for the gathered rows."""
    if l2_reg:
        gsum = gsum + l2_reg * p_r
    b1, b2, eps = cfg.adam_b1, cfg.adam_b2, cfg.adam_eps
    m_n = b1 * m_r + (1.0 - b1) * gsum
    v_n = b2 * v_r + (1.0 - b2) * jnp.square(gsum)
    t = step.astype(jnp.float32)
    m_hat = m_n / (1.0 - jnp.power(b1, t))
    v_hat = v_n / (1.0 - jnp.power(b2, t))
    p_n = p_r - learning_rate * m_hat / (jnp.sqrt(v_hat) + eps)
    return p_n, m_n, v_n


def lazy_adam_update(
    table: jnp.ndarray,
    m: jnp.ndarray,
    v: jnp.ndarray,
    ids: jnp.ndarray,
    row_grads: jnp.ndarray,
    step: jnp.ndarray,
    cfg: OptimizerConfig,
    *,
    learning_rate: float,
    l2_reg: float = 0.0,
    segmented: tuple | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One lazy-Adam step on the rows of ``table`` touched by ``ids``.

    table [V, ...], ids [...] int, row_grads ids.shape + table.shape[1:],
    step: 1-based global step (for bias correction).  ``segmented`` lets the
    caller reuse one sort across tables sharing the same ids.
    Returns (new_table, new_m, new_v).
    """
    shape = table.shape
    width = 1
    for d in shape[1:]:
        width *= d
    t2 = table.reshape(shape[0], width)
    m2 = m.reshape(shape[0], width)
    v2 = v.reshape(shape[0], width)
    flat_ids = jnp.clip(ids.reshape(-1), 0, shape[0] - 1)
    g2 = row_grads.reshape(flat_ids.shape[0], width)

    if segmented is None:
        row_id, gsum, valid = segment_rows(flat_ids, g2, shape[0])
    else:
        order, seg, row_id, valid = segmented
        gsum = jax.ops.segment_sum(
            g2[order], seg, num_segments=flat_ids.shape[0],
            indices_are_sorted=True,
        )

    p_r = t2[row_id]
    m_r = m2[row_id]
    v_r = v2[row_id]
    # dense-L2 analog on touched rows, once per unique row (inside
    # adam_row_math); one shared implementation of the per-row arithmetic
    p_n, m_n, v_n = adam_row_math(
        p_r, m_r, v_r, gsum, step, cfg,
        learning_rate=learning_rate, l2_reg=l2_reg,
    )

    # padding segments get strictly-increasing OUT-OF-BOUNDS ids: XLA drops
    # them, and the index vector stays sorted and duplicate-free so the
    # scatters take the fast sorted/unique path instead of the serialized
    # conflict-safe one (the difference is ~50x on TPU)
    n = row_id.shape[0]
    scatter_id = jnp.where(
        valid, row_id, shape[0] + jnp.arange(n, dtype=row_id.dtype)
    )
    kw = dict(indices_are_sorted=True, unique_indices=True, mode="drop")
    new_t = t2.at[scatter_id].add(p_n - p_r, **kw)
    new_m = m2.at[scatter_id].add(m_n - m_r, **kw)
    new_v = v2.at[scatter_id].add(v_n - v_r, **kw)
    return new_t.reshape(shape), new_m.reshape(shape), new_v.reshape(shape)


def lazy_adam_update_shard(
    local_table: jnp.ndarray,
    m: jnp.ndarray,
    v: jnp.ndarray,
    row_id: jnp.ndarray,
    gsum: jnp.ndarray,
    valid: jnp.ndarray,
    row_offset: jnp.ndarray,
    step: jnp.ndarray,
    cfg: OptimizerConfig,
    *,
    learning_rate: float,
    l2_reg: float = 0.0,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Shard-local lazy Adam: apply pre-deduped global row updates to the
    rows this shard owns ([row_offset, row_offset + local_rows)).

    ``row_id``/``gsum``/``valid`` come from :func:`segment_rows` (or
    :func:`shared_segments` + segment_sum) over the GLOBAL id stream —
    identical on every shard — so replicas of the segment computation fold
    into one XLA program and only the scatter targets differ per shard.
    Out-of-range rows are dropped via out-of-bounds scatter indices.
    """
    shape = local_table.shape
    width = 1
    for d in shape[1:]:
        width *= d
    rows = shape[0]
    t2 = local_table.reshape(rows, width)
    m2 = m.reshape(rows, width)
    v2 = v.reshape(rows, width)
    g2 = gsum.reshape(row_id.shape[0], width)

    local_id = row_id - row_offset
    in_range = valid & (local_id >= 0) & (local_id < rows)
    safe = jnp.clip(local_id, 0, rows - 1)
    p_r = t2[safe]
    m_r = m2[safe]
    v_r = v2[safe]
    p_n, m_n, v_n = adam_row_math(
        p_r, m_r, v_r, g2, step, cfg,
        learning_rate=learning_rate, l2_reg=l2_reg,
    )

    n = row_id.shape[0]
    scatter_id = jnp.where(
        in_range, local_id, rows + jnp.arange(n, dtype=local_id.dtype)
    )
    # out-of-range rows interleave, so sortedness is NOT preservable here;
    # uniqueness is (padding ids are distinct and >= rows)
    kw = dict(unique_indices=True, mode="drop")
    new_t = t2.at[scatter_id].add(p_n - p_r, **kw)
    new_m = m2.at[scatter_id].add(m_n - m_r, **kw)
    new_v = v2.at[scatter_id].add(v_n - v_r, **kw)
    return new_t.reshape(shape), new_m.reshape(shape), new_v.reshape(shape)


def shared_segments(flat_ids: jnp.ndarray, id_bound: int | None = None):
    """Precompute the sort/segment structure once for tables sharing ids.

    Alias of ops/embedding.py ``sort_segments`` (also the segsum-backward
    building block AND the all-to-all shard exchange's routing plan,
    parallel/embedding.py ``exchange_plan``) — one implementation (packed
    single-key sort) to keep in sync.  The sharded lazy step feeds the
    SAME remapped id stream here and to the forward exchange so XLA CSE
    folds their sorts into one (parallel/spmd.py)."""
    from ..ops.embedding import sort_segments

    return sort_segments(flat_ids, id_bound)
