"""Dense (single-chip) train/eval steps for the two-tower retrieval family.

Same TrainState / optimizer plumbing as the CTR steps (train/step.py); the
loss couples examples across the batch (in-batch softmax), so this family
gets its own step builders instead of ModelDef dispatch.  The sharded
counterpart with the cross-chip all-gather lives in parallel/retrieval.py.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax

from ..core.config import Config
from ..models.two_tower import (
    apply_two_tower,
    in_batch_softmax_loss,
    init_two_tower,
    retrieval_metrics,
    two_tower_l2_penalty,
)
from .optimizer import build_optimizer
from .step import TrainState, _dp_size


def create_retrieval_state(cfg: Config, key: jax.Array | None = None) -> TrainState:
    key = jax.random.PRNGKey(cfg.run.seed) if key is None else key
    init_key, step_key = jax.random.split(key)
    params, model_state = init_two_tower(init_key, cfg.model)
    tx = build_optimizer(cfg.optimizer, data_parallel_size=_dp_size(cfg))
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        model_state=model_state,
        opt_state=tx.init(params),
        rng=step_key,
    )


def retrieval_loss(cfg: Config, params, batch) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Full-batch in-batch-softmax loss: positives on the diagonal."""
    towers = apply_two_tower(params, batch, cfg=cfg.model)
    b = towers.user.shape[0]
    labels = jnp.arange(b)
    ce, scores = in_batch_softmax_loss(
        towers.user, towers.item, labels, temperature=cfg.model.temperature
    )
    loss = jnp.mean(ce) + two_tower_l2_penalty(params, cfg.model.l2_reg)
    return loss, scores


def make_retrieval_train_step(cfg: Config) -> Callable:
    tx = build_optimizer(cfg.optimizer, data_parallel_size=_dp_size(cfg))

    def train_step(state: TrainState, batch: dict) -> tuple[TrainState, dict]:
        def loss_fn(params):
            return retrieval_loss(cfg, params, batch)

        (loss, scores), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss}
        metrics.update(retrieval_metrics(scores, jnp.arange(scores.shape[0])))
        return (
            TrainState(
                step=state.step + 1,
                params=new_params,
                model_state=state.model_state,
                opt_state=new_opt_state,
                rng=state.rng,
            ),
            metrics,
        )

    return train_step


def make_retrieval_eval_step(cfg: Config) -> Callable:
    def eval_step(state: TrainState, batch: dict) -> dict:
        loss, scores = retrieval_loss(cfg, state.params, batch)
        metrics = {"loss": loss, "count": jnp.asarray(scores.shape[0])}
        metrics.update(retrieval_metrics(scores, jnp.arange(scores.shape[0])))
        return metrics

    return eval_step
