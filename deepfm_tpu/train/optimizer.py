"""Optimizer construction — parity with the reference's selection block
(ps:292-305): Adam / Adagrad / Momentum / Ftrl with the exact TF1
hyperparameters, built on optax transforms (FTRL implemented here; optax has
no FTRL).  The Horovod path's lr×world_size scaling (hvd:171) is an explicit
config knob applied by the caller via ``data_parallel_size``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from ..core.config import OptimizerConfig


class FtrlState(NamedTuple):
    z: optax.Updates
    n: optax.Updates


def ftrl(
    learning_rate: float,
    *,
    learning_rate_power: float = -0.5,
    initial_accumulator_value: float = 0.1,
    l1: float = 0.0,
    l2: float = 0.0,
) -> optax.GradientTransformation:
    """FTRL-Proximal (McMahan et al.), matching ``tf.train.FtrlOptimizer``
    defaults (ps:304-305).  Note FTRL rewrites weights from its own state, so
    updates returned are ``w_new - w_old``."""

    def init_fn(params):
        return FtrlState(
            z=jax.tree_util.tree_map(jnp.zeros_like, params),
            n=jax.tree_util.tree_map(
                lambda p: jnp.full_like(p, initial_accumulator_value), params
            ),
        )

    def update_fn(grads, state, params):
        if params is None:
            raise ValueError("ftrl requires params")
        p = -learning_rate_power
        tm = jax.tree_util.tree_map
        n_new = tm(lambda g, n: n + jnp.square(g), grads, state.n)
        z_new = tm(
            lambda g, z, n2, n, w: z + g - (n2**p - n**p) / learning_rate * w,
            grads, state.z, n_new, state.n, params,
        )
        w_new = tm(
            lambda z2, n2, w: jnp.where(
                jnp.abs(z2) <= l1,
                jnp.zeros_like(w),
                -(z2 - jnp.sign(z2) * l1) / ((n2**p) / learning_rate + 2.0 * l2),
            ),
            z_new, n_new, params,
        )
        updates = tm(lambda wn, w: wn - w, w_new, params)
        return updates, FtrlState(z=z_new, n=n_new)

    return optax.GradientTransformation(init_fn, update_fn)


def build_lr_schedule(cfg: OptimizerConfig, *, data_parallel_size: int = 1):
    """Resolve the learning-rate schedule: a float for the reference's
    constant-lr behavior (ps:292-305 — the reference has no schedules), or
    an ``optax`` schedule (step -> lr) when warmup/decay is configured.

    The step count a schedule sees is the OPTIMIZER step (optax's update
    count for the dense path, ``state.step`` for the lazy path — the two
    advance in lockstep), so checkpoint resume continues the schedule at
    the right point.
    """
    peak = cfg.learning_rate
    if cfg.scale_lr_by_data_parallel:
        peak = peak * data_parallel_size  # hvd:171 semantics, now explicit
    name = cfg.lr_schedule.lower()
    warmup = cfg.warmup_steps
    if name == "constant":
        if warmup <= 0:
            return peak
        return optax.schedules.join_schedules(
            [optax.schedules.linear_schedule(0.0, peak, warmup),
             optax.schedules.constant_schedule(peak)],
            [warmup],
        )
    if cfg.decay_steps <= warmup:
        raise ValueError(
            f"lr_schedule={name!r} needs decay_steps > warmup_steps "
            f"(got {cfg.decay_steps} <= {warmup}); decay_steps is the TOTAL "
            f"schedule horizon including warmup"
        )
    end = peak * cfg.lr_end_fraction
    if name == "cosine":
        return optax.schedules.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=peak, warmup_steps=warmup,
            decay_steps=cfg.decay_steps, end_value=end,
        )
    if name == "linear":
        return optax.schedules.join_schedules(
            [optax.schedules.linear_schedule(0.0, peak, warmup),
             optax.schedules.linear_schedule(
                 peak, end, cfg.decay_steps - warmup)],
            [warmup],
        )
    raise ValueError(
        f"unknown lr_schedule {cfg.lr_schedule!r} (constant|cosine|linear)"
    )


def schedule_value(lr_sched, step):
    """Evaluate a ``build_lr_schedule`` result at an optimizer step: floats
    (and config-supplied ints) pass through, schedules are called.  The one
    place the constant-vs-schedule type dispatch lives — both lazy paths
    (train/step.py, parallel/spmd.py) use it inside their traced steps."""
    return lr_sched(step) if callable(lr_sched) else lr_sched


# params whose updates the embedding_lr_multiplier scales: the CTR tables
# the reference's parameter servers hosted (FM_W [V], FM_V [V,K] —
# ps:188-198) plus the two-tower retrieval tables.  Everything else
# (MLP/towers, bias) keeps the base lr.
EMBEDDING_PARAM_KEYS = ("fm_w", "fm_v", "user_embedding", "item_embedding")


def _scale_embedding_updates(multiplier: float) -> optax.GradientTransformation:
    """Post-scale fm_w/fm_v updates by ``multiplier`` — an exact per-group
    lr split for optimizers whose update is linear in lr (Adam/Adagrad/
    Momentum).  Stateless, so it does not change checkpoint structure
    beyond the chain wrapper itself."""

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params

        def scale(path, u):
            leaf = path[-1]
            name = getattr(leaf, "key", None) or str(leaf)
            return u * multiplier if name in EMBEDDING_PARAM_KEYS else u

        return jax.tree_util.tree_map_with_path(scale, updates), state

    return optax.GradientTransformation(init_fn, update_fn)


def build_optimizer(
    cfg: OptimizerConfig, *, data_parallel_size: int = 1
) -> optax.GradientTransformation:
    lr = build_lr_schedule(cfg, data_parallel_size=data_parallel_size)
    name = cfg.name.lower()
    if name == "adam":
        tx = optax.adam(lr, b1=cfg.adam_b1, b2=cfg.adam_b2, eps=cfg.adam_eps)
    elif name == "adagrad":
        # TF Adagrad has no epsilon term; the initial accumulator provides
        # numeric floor (ps:296-298)
        tx = optax.adagrad(
            lr, initial_accumulator_value=cfg.adagrad_init_accum, eps=0.0
        )
    elif name == "momentum":
        tx = optax.sgd(lr, momentum=cfg.momentum, nesterov=False)
    elif name == "ftrl":
        if callable(lr):
            raise ValueError(
                "Ftrl supports constant lr only (its z-state accumulates "
                "1/lr-weighted terms; a schedule would change past state)"
            )
        if cfg.embedding_lr_multiplier != 1.0:
            raise ValueError(
                "embedding_lr_multiplier: Ftrl updates are full weight "
                "rewrites, not lr-linear steps — the multiplier would not "
                "be an lr split; use Adam/Adagrad/Momentum"
            )
        tx = ftrl(lr)
    else:
        raise ValueError(
            f"unknown optimizer {cfg.name!r} (Adam|Adagrad|Momentum|Ftrl)"
        )
    if cfg.embedding_lr_multiplier != 1.0:
        # chained only when active, so the default config keeps the bare
        # optimizer's opt_state structure (checkpoint compatibility)
        tx = optax.chain(tx, _scale_embedding_updates(
            cfg.embedding_lr_multiplier))
    return tx
