"""Optimizer construction — parity with the reference's selection block
(ps:292-305): Adam / Adagrad / Momentum / Ftrl with the exact TF1
hyperparameters, built on optax transforms (FTRL implemented here; optax has
no FTRL).  The Horovod path's lr×world_size scaling (hvd:171) is an explicit
config knob applied by the caller via ``data_parallel_size``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from ..core.config import OptimizerConfig


class FtrlState(NamedTuple):
    z: optax.Updates
    n: optax.Updates


def ftrl(
    learning_rate: float,
    *,
    learning_rate_power: float = -0.5,
    initial_accumulator_value: float = 0.1,
    l1: float = 0.0,
    l2: float = 0.0,
) -> optax.GradientTransformation:
    """FTRL-Proximal (McMahan et al.), matching ``tf.train.FtrlOptimizer``
    defaults (ps:304-305).  Note FTRL rewrites weights from its own state, so
    updates returned are ``w_new - w_old``."""

    def init_fn(params):
        return FtrlState(
            z=jax.tree_util.tree_map(jnp.zeros_like, params),
            n=jax.tree_util.tree_map(
                lambda p: jnp.full_like(p, initial_accumulator_value), params
            ),
        )

    def update_fn(grads, state, params):
        if params is None:
            raise ValueError("ftrl requires params")
        p = -learning_rate_power
        tm = jax.tree_util.tree_map
        n_new = tm(lambda g, n: n + jnp.square(g), grads, state.n)
        z_new = tm(
            lambda g, z, n2, n, w: z + g - (n2**p - n**p) / learning_rate * w,
            grads, state.z, n_new, state.n, params,
        )
        w_new = tm(
            lambda z2, n2, w: jnp.where(
                jnp.abs(z2) <= l1,
                jnp.zeros_like(w),
                -(z2 - jnp.sign(z2) * l1) / ((n2**p) / learning_rate + 2.0 * l2),
            ),
            z_new, n_new, params,
        )
        updates = tm(lambda wn, w: wn - w, w_new, params)
        return updates, FtrlState(z=z_new, n=n_new)

    return optax.GradientTransformation(init_fn, update_fn)


def build_optimizer(
    cfg: OptimizerConfig, *, data_parallel_size: int = 1
) -> optax.GradientTransformation:
    lr = cfg.learning_rate
    if cfg.scale_lr_by_data_parallel:
        lr = lr * data_parallel_size  # hvd:171 semantics, now explicit
    name = cfg.name.lower()
    if name == "adam":
        return optax.adam(lr, b1=cfg.adam_b1, b2=cfg.adam_b2, eps=cfg.adam_eps)
    if name == "adagrad":
        # TF Adagrad has no epsilon term; the initial accumulator provides
        # numeric floor (ps:296-298)
        return optax.adagrad(
            lr, initial_accumulator_value=cfg.adagrad_init_accum, eps=0.0
        )
    if name == "momentum":
        return optax.sgd(lr, momentum=cfg.momentum, nesterov=False)
    if name == "ftrl":
        return ftrl(lr)
    raise ValueError(f"unknown optimizer {cfg.name!r} (Adam|Adagrad|Momentum|Ftrl)")
