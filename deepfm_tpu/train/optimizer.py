"""Optimizer construction — parity with the reference's selection block
(ps:292-305): Adam / Adagrad / Momentum / Ftrl with the exact TF1
hyperparameters, built on optax transforms (FTRL implemented here; optax has
no FTRL).  The Horovod path's lr×world_size scaling (hvd:171) is an explicit
config knob applied by the caller via ``data_parallel_size``.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import optax

from ..core.config import OptimizerConfig


class FtrlState(NamedTuple):
    z: optax.Updates
    n: optax.Updates


def ftrl(
    learning_rate: float,
    *,
    learning_rate_power: float = -0.5,
    initial_accumulator_value: float = 0.1,
    l1: float = 0.0,
    l2: float = 0.0,
) -> optax.GradientTransformation:
    """FTRL-Proximal (McMahan et al.), matching ``tf.train.FtrlOptimizer``
    defaults (ps:304-305).  Note FTRL rewrites weights from its own state, so
    updates returned are ``w_new - w_old``."""

    def init_fn(params):
        return FtrlState(
            z=jax.tree_util.tree_map(jnp.zeros_like, params),
            n=jax.tree_util.tree_map(
                lambda p: jnp.full_like(p, initial_accumulator_value), params
            ),
        )

    def update_fn(grads, state, params):
        if params is None:
            raise ValueError("ftrl requires params")
        p = -learning_rate_power
        tm = jax.tree_util.tree_map
        n_new = tm(lambda g, n: n + jnp.square(g), grads, state.n)
        z_new = tm(
            lambda g, z, n2, n, w: z + g - (n2**p - n**p) / learning_rate * w,
            grads, state.z, n_new, state.n, params,
        )
        w_new = tm(
            lambda z2, n2, w: jnp.where(
                jnp.abs(z2) <= l1,
                jnp.zeros_like(w),
                -(z2 - jnp.sign(z2) * l1) / ((n2**p) / learning_rate + 2.0 * l2),
            ),
            z_new, n_new, params,
        )
        updates = tm(lambda wn, w: wn - w, w_new, params)
        return updates, FtrlState(z=z_new, n=n_new)

    return optax.GradientTransformation(init_fn, update_fn)


def build_lr_schedule(cfg: OptimizerConfig, *, data_parallel_size: int = 1):
    """Resolve the learning-rate schedule: a float for the reference's
    constant-lr behavior (ps:292-305 — the reference has no schedules), or
    an ``optax`` schedule (step -> lr) when warmup/decay is configured.

    The step count a schedule sees is the OPTIMIZER step (optax's update
    count for the dense path, ``state.step`` for the lazy path — the two
    advance in lockstep), so checkpoint resume continues the schedule at
    the right point.
    """
    peak = cfg.learning_rate
    if cfg.scale_lr_by_data_parallel:
        peak = peak * data_parallel_size  # hvd:171 semantics, now explicit
    name = cfg.lr_schedule.lower()
    warmup = cfg.warmup_steps
    if name == "constant":
        if warmup <= 0:
            return peak
        return optax.schedules.join_schedules(
            [optax.schedules.linear_schedule(0.0, peak, warmup),
             optax.schedules.constant_schedule(peak)],
            [warmup],
        )
    if cfg.decay_steps <= warmup:
        raise ValueError(
            f"lr_schedule={name!r} needs decay_steps > warmup_steps "
            f"(got {cfg.decay_steps} <= {warmup}); decay_steps is the TOTAL "
            f"schedule horizon including warmup"
        )
    end = peak * cfg.lr_end_fraction
    if name == "cosine":
        return optax.schedules.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=peak, warmup_steps=warmup,
            decay_steps=cfg.decay_steps, end_value=end,
        )
    if name == "linear":
        return optax.schedules.join_schedules(
            [optax.schedules.linear_schedule(0.0, peak, warmup),
             optax.schedules.linear_schedule(
                 peak, end, cfg.decay_steps - warmup)],
            [warmup],
        )
    raise ValueError(
        f"unknown lr_schedule {cfg.lr_schedule!r} (constant|cosine|linear)"
    )


def schedule_value(lr_sched, step):
    """Evaluate a ``build_lr_schedule`` result at an optimizer step: floats
    (and config-supplied ints) pass through, schedules are called.  The one
    place the constant-vs-schedule type dispatch lives — both lazy paths
    (train/step.py, parallel/spmd.py) use it inside their traced steps."""
    return lr_sched(step) if callable(lr_sched) else lr_sched


# params whose updates the embedding_lr_multiplier scales: the CTR tables
# the reference's parameter servers hosted (FM_W [V], FM_V [V,K] —
# ps:188-198) plus the two-tower retrieval tables.  Everything else
# (MLP/towers, bias) keeps the base lr.
EMBEDDING_PARAM_KEYS = ("fm_w", "fm_v", "user_embedding", "item_embedding")


def _scale_embedding_updates(multiplier: float) -> optax.GradientTransformation:
    """Post-scale fm_w/fm_v updates by ``multiplier`` — an exact per-group
    lr split for optimizers whose update is linear in lr (Adam/Adagrad/
    Momentum).  Stateless, so it does not change checkpoint structure
    beyond the chain wrapper itself."""

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params

        def scale(path, u):
            leaf = path[-1]
            name = getattr(leaf, "key", None) or str(leaf)
            return u * multiplier if name in EMBEDDING_PARAM_KEYS else u

        return jax.tree_util.tree_map_with_path(scale, updates), state

    return optax.GradientTransformation(init_fn, update_fn)


# ---------------------------------------------------------------------------
# ZeRO-style dp-partitioned weight update (arxiv 2004.13336, "Automatic
# Cross-Replica Sharding of Weight Update in Data-Parallel Training")


class ZeroDpState(NamedTuple):
    """dp-partitioned optimizer state: the wrapped chain's state over the
    ZERO LAYOUT of the param tree — every eligible leaf flattened (and
    dp-padded), so each data shard owns a contiguous 1/dp window of the
    moments.  The ``zero_dp`` field name is the layout MARKER: sharding
    rules (``parallel/spmd._spec_for_leaf``) and the cross-topology
    restore (``checkpoint/reshard.py``) both key on it appearing in a
    leaf's tree path."""

    zero_dp: Any


def resolve_zero_sharding(cfg: OptimizerConfig, data_parallel_size: int) -> bool:
    """Whether the dp-sharded weight update is ACTIVE: 'off' never, 'on'
    and 'auto' exactly when the data axis has more than one shard (at
    dp == 1 there is nothing to shard — 'on' warns at config validation,
    ``core/config.py``)."""
    if cfg.zero_sharding == "off":
        return False
    return data_parallel_size > 1


def zero_chunk(n_local: int, dp: int) -> int:
    """Per-dp-shard window length for an ``n_local``-element flattened
    leaf: ``ceil(n_local / dp)`` — the last window carries the zero
    padding when ``n_local`` does not divide."""
    return -(-max(1, n_local) // max(1, dp))


def zero_layout_size(n_total: int, shards: int, dp: int) -> int | None:
    """Flattened GLOBAL length of a leaf's zero-layout moment, or ``None``
    when the leaf is ineligible and keeps the replicated update.

    The layout is CANONICAL: the global moment is exactly the row-major
    flatten of the global param (plus trailing zero padding for dense
    leaves), so a payload saved under any (dp, mp) restores onto any
    other by a dim0 slice/pad — the same machinery that adapts table row
    padding (``checkpoint/reshard.jit_row_adapter``).  Canonicality is
    what makes a row-sharded table leaf (``shards`` = model_parallel > 1)
    eligible only when its per-model-shard element count divides dp:
    interleaved per-shard padding would encode the topology into the
    bytes.  Dense leaves (``shards`` == 1) pad trailing and are always
    eligible."""
    n_local, rem = divmod(max(1, n_total), shards)
    if rem:
        return None
    if shards > 1:
        return n_total if n_local % dp == 0 else None
    return zero_chunk(n_local, dp) * dp


def _zero_plan_chunk(n_local: int, shards: int, dp: int) -> int:
    """Window length matching :func:`zero_layout_size`'s layout: exact
    ``n_local // dp`` for multi-shard (table) leaves — their layout is
    the unpadded canonical flatten — ceil for dense leaves (trailing
    zero padding)."""
    return n_local // dp if shards > 1 else zero_chunk(n_local, dp)


class ZeroShardedOptimizer(NamedTuple):
    """The dp-partitioned weight update's two entry points.  NOT a plain
    ``optax.GradientTransformation``: the apply must happen on the 1/dp
    window BEFORE the all-gather (``update_and_apply``), because the
    fresh params — not the updates — are what crosses the wire.  (Bit
    parity depends on this too: applying a gathered update would place
    the final ``p + u`` add behind a collective materialization, where
    XLA can no longer contract it into the same fused multiply-add the
    replicated path compiles — a 1-ulp drift per step.)"""

    init: Any                  # params -> ZeroDpState
    update_and_apply: Any      # (grads, state, params) -> (new_params, state)


def zero_sharded(
    tx: optax.GradientTransformation,
    *,
    dp: int,
    mp: int,
    vocab: int,
    data_axis: str,
    model_axis: str,
    table_keys: Sequence[str],
) -> ZeroShardedOptimizer:
    """Wrap an optax chain so the weight update is SHARDED across the
    ``data_axis`` instead of redundantly replicated (ZeRO / arxiv
    2004.13336, expressed through sharding annotations per GSPMD, arxiv
    2105.04663):

    * ``update_and_apply`` (which must run INSIDE ``shard_map`` over the
      [data × model] mesh) replaces the dense-grad ``pmean`` +
      full-width replicated ``tx.update`` with a per-leaf
      **reduce-scatter** (``lax.psum_scatter``) of the flattened grad —
      issued per leaf, so XLA can overlap each collective with the
      remaining backward compute — a windowed inner update + apply on
      the 1/dp of params and moments this shard owns, and an
      **all-gather** of the fresh 1/dp param windows back to full width;
    * ``init`` builds the inner state over the zero LAYOUT of the param
      tree (``zero_layout_size``), so every moment leaf is born
      flattened: per shard the moments are 1/dp-sized, and per step they
      are read and written once by one owner instead of dp times by
      everybody — the dominant train-hot-path HBM traffic term
      (bench.py roofline ``dense_state_bytes_per_step``).

    Row-sharded table leaves (path under ``table_keys`` with a
    ``vocab``-row leading dim) shard their per-model-shard flatten over
    dp on top of the existing model-axis row sharding; the rare
    ineligible leaf (per-model-shard size not divisible by dp, see
    ``zero_layout_size``) keeps the replicated pmean update, bit-exactly
    as before.  Bit-parity with the replicated path is pinned by
    tests/test_zero_sharding.py; the lowering contract (reduce-scatter,
    not all-reduce, on dense grads) by ``analysis.trace_audit.
    audit_zero_update``."""
    table_set = frozenset(table_keys)

    def _shards(path, shape, *, local: bool) -> int:
        # mirrors parallel/spmd._spec_for_leaf's row-sharding rule: only
        # leaves it row-shards over the model axis have mp-way shards
        # (local view: the per-shard leading dim is vocab // mp)
        keys = {getattr(p, "key", None) for p in path}
        rows = vocab // mp if local else vocab
        if keys & table_set and len(shape) >= 1 and shape[0] == rows:
            return mp
        return 1

    def _size(shape) -> int:
        n = 1
        for d in shape:
            n *= int(d)
        return n

    def _plan(path, shape, *, local: bool):
        """(n_local, chunk) for an eligible leaf, None for ineligible."""
        shards = _shards(path, shape, local=local)
        n = _size(shape)
        n_local = n if local else n // max(1, shards)
        if shards > 1 and n_local % dp != 0:
            return None
        return n_local, _zero_plan_chunk(n_local, shards, dp)

    def _dict_path(path) -> tuple:
        return tuple(
            k for k in (getattr(p, "key", None) for p in path)
            if k is not None
        )

    def init_fn(params):
        # dict-key path -> (layout_len, true_len) for padded leaves: optax
        # states mirror the param tree under their sub-states (mu/nu/
        # z/n/...), so the same dict-key sequence identifies the moment
        # leaves whose padding region must be zeroed below
        padded: dict = {}

        def lay(path, p):
            if p is None or not hasattr(p, "shape"):
                return p
            plan = _plan(path, p.shape, local=False)
            if plan is None:
                return p
            flat = p.reshape(-1)
            shards = _shards(path, p.shape, local=False)
            pad = shards * plan[1] * dp - flat.shape[0]
            if pad:
                padded[_dict_path(path)] = (flat.shape[0] + pad,
                                            flat.shape[0])
                flat = jnp.concatenate(
                    [flat, jnp.zeros((pad,), flat.dtype)]
                )
            return flat

        inner = tx.init(jax.tree_util.tree_map_with_path(lay, params))

        def zero_pad(path, s):
            # the padding tail must be ZERO whatever the optimizer's init
            # constant (Adagrad/FTRL fill accumulators with a nonzero
            # floor): the canonical layout's trailing region is what the
            # cross-topology restore verifies is droppable padding, and
            # it STAYS zero under the update (padded grads are zero)
            m = padded.get(_dict_path(path))
            if (m is None or not hasattr(s, "shape")
                    or tuple(s.shape) != (m[0],)):
                return s
            return jnp.where(jnp.arange(m[0]) < m[1], s, 0)

        return ZeroDpState(
            zero_dp=jax.tree_util.tree_map_with_path(zero_pad, inner)
        )

    def update_and_apply(grads, state, params):
        if params is None:
            raise ValueError("zero_sharded requires params (the windowed "
                             "inner update slices them)")
        from jax import lax

        d = lax.axis_index(data_axis)
        tm = jax.tree_util.tree_map_with_path

        def _pad_flat(a, chunk):
            flat = a.reshape(-1)
            pad = chunk * dp - flat.shape[0]
            if pad:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((pad,), flat.dtype)]
                )
            return flat

        def scatter(path, g):
            plan = _plan(path, g.shape, local=True)
            if plan is None:
                # ineligible: the replicated pmean update, unchanged
                return lax.pmean(g, data_axis)
            # reduce-scatter issued PER LEAF, as each grad becomes
            # available in the backward — independent collectives XLA can
            # overlap with the remaining backward compute
            win = lax.psum_scatter(
                _pad_flat(g, plan[1]), data_axis, scatter_dimension=0,
                tiled=True,
            ) / dp
            if _shards(path, g.shape, local=True) == 1:
                # replicated (non-table) leaf: pin bit-identity across
                # model replicas exactly like _pmean_grads does — on the
                # 1/dp window, where it costs 1/dp as much
                win = lax.pmean(win, model_axis)
            return win

        def window(path, p):
            plan = _plan(path, p.shape, local=True)
            if plan is None:
                return p
            return lax.dynamic_slice(
                _pad_flat(p, plan[1]), (d * plan[1],), (plan[1],)
            )

        g_win = tm(scatter, grads)
        p_win = tm(window, params)
        updates_win, new_inner = tx.update(g_win, state.zero_dp, p_win)
        # apply on the WINDOW, then gather the fresh params: the p + u add
        # stays adjacent to the update math (same fused pattern as the
        # replicated path — bit parity), and what crosses the wire is the
        # new 1/dp param windows, once
        new_win = optax.apply_updates(p_win, updates_win)

        def gather(path, w, p):
            plan = _plan(path, p.shape, local=True)
            if plan is None:
                return w  # ineligible: w is already the full new leaf
            full = lax.all_gather(w, data_axis, tiled=True)
            return full[: _size(p.shape)].reshape(p.shape)

        new_params = tm(gather, new_win, params)
        return new_params, ZeroDpState(zero_dp=new_inner)

    return ZeroShardedOptimizer(init_fn, update_and_apply)


def build_optimizer(
    cfg: OptimizerConfig, *, data_parallel_size: int = 1
) -> optax.GradientTransformation:
    """Build the configured optax chain (Adam/Adagrad/Momentum/Ftrl with
    the reference's TF1 hyperparameters, plus the lr-schedule and
    embedding-lr-split extensions).

    ``cfg.zero_sharding`` (off|on|auto) selects the ZeRO-style dp-sharded
    weight update: the SPMD step builders (``parallel/spmd.py``) wrap
    this chain with :func:`zero_sharded` when
    :func:`resolve_zero_sharding` says it is active — reduce-scatter of
    dense grads over the data axis, a 1/dp-windowed update on
    dp-partitioned moments, and an all-gather of the fresh windows —
    instead of the replicated pmean + full-width update.  The wrapper is
    applied at the shard_map layer, not here: this function stays
    axis-agnostic so the single-device step (``train/step.py``), the
    replay oracle and the benches keep the plain chain (at dp == 1 the
    knob is a structural no-op either way)."""
    lr = build_lr_schedule(cfg, data_parallel_size=data_parallel_size)
    name = cfg.name.lower()
    if name == "adam":
        tx = optax.adam(lr, b1=cfg.adam_b1, b2=cfg.adam_b2, eps=cfg.adam_eps)
    elif name == "adagrad":
        # TF Adagrad has no epsilon term; the initial accumulator provides
        # numeric floor (ps:296-298)
        tx = optax.adagrad(
            lr, initial_accumulator_value=cfg.adagrad_init_accum, eps=0.0
        )
    elif name == "momentum":
        tx = optax.sgd(lr, momentum=cfg.momentum, nesterov=False)
    elif name == "ftrl":
        if callable(lr):
            raise ValueError(
                "Ftrl supports constant lr only (its z-state accumulates "
                "1/lr-weighted terms; a schedule would change past state)"
            )
        if cfg.embedding_lr_multiplier != 1.0:
            raise ValueError(
                "embedding_lr_multiplier: Ftrl updates are full weight "
                "rewrites, not lr-linear steps — the multiplier would not "
                "be an lr split; use Adam/Adagrad/Momentum"
            )
        tx = ftrl(lr)
    else:
        raise ValueError(
            f"unknown optimizer {cfg.name!r} (Adam|Adagrad|Momentum|Ftrl)"
        )
    if cfg.embedding_lr_multiplier != 1.0:
        # chained only when active, so the default config keeps the bare
        # optimizer's opt_state structure (checkpoint compatibility)
        tx = optax.chain(tx, _scale_embedding_updates(
            cfg.embedding_lr_multiplier))
    return tx
