"""DeepFM: wide (1st-order) + FM (2nd-order) + deep MLP, TPU-first.

Reproduces the reference forward pass exactly (model_fn, ps:172-260):

    y = FM_B + Σ_f w_f·x_f + 0.5Σ_k((Σ_f e)²−Σ_f e²) + MLP(flatten(e))
    e_fk = V[id_f]_k · x_f
    pred = σ(y)

with the reference's initialization (zeros bias; glorot_normal FM_W/FM_V,
ps:186-198; glorot_uniform MLP kernels + zero biases — the
``contrib.layers.fully_connected`` defaults, ps:233-255), relu MLP with
optional post-activation batch-norm and dropout whose config value is the
TF1 *keep* probability (ps:240-246).

TPU mapping: the two gathers stay f32 (HBM-bound, precision-sensitive sums);
the MLP runs in ``cfg.compute_dtype`` (bf16 by default) so its matmuls hit
the MXU; XLA fuses the FM reductions into a single VPU pass.  Parameters are
kept f32 throughout for optimizer precision.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.config import ModelConfig
from ..ops.batch_norm import batch_norm, bn_init
from ..ops.embedding import (dense_lookup, narrow_ids, scaled_embedding,
                             segsum_lookup)
from ..ops.fm import fm_first_order, fm_second_order
from ..ops.initializers import glorot_normal, glorot_uniform
from ..ops.pallas_ctr import fused_ctr_interaction, resolve_fused
from .base import register_model


def init_mlp(key: jax.Array, in_dim: int, cfg: ModelConfig) -> dict:
    """MLP tower params: hidden layers + linear head (ps:230-255)."""
    params: dict = {}
    dims = [in_dim, *cfg.deep_layers]
    keys = jax.random.split(key, len(cfg.deep_layers) + 1)
    for i, (d_in, d_out) in enumerate(zip(dims[:-1], dims[1:])):
        params[f"layer_{i}"] = {
            "kernel": glorot_uniform(keys[i], (d_in, d_out)),
            "bias": jnp.zeros((d_out,), jnp.float32),
        }
    params["out"] = {
        "kernel": glorot_uniform(keys[-1], (dims[-1], 1)),
        "bias": jnp.zeros((1,), jnp.float32),
    }
    return params


def apply_mlp(
    params: dict,
    bn_params: dict | None,
    bn_state: dict | None,
    x: jnp.ndarray,
    *,
    cfg: ModelConfig,
    train: bool,
    rng: jax.Array | None,
) -> tuple[jnp.ndarray, dict]:
    """Shared deep tower: relu FCs (+BN, +dropout at train), linear head.

    Returns ([B] logits contribution, new bn_state).
    """
    compute_dtype = jnp.dtype(cfg.compute_dtype)
    h = x.astype(compute_dtype)
    new_bn_state: dict = {}
    n_layers = len(cfg.deep_layers)
    needs_dropout = train and any(k < 1.0 for k in cfg.dropout_keep[:n_layers])
    if needs_dropout:
        if rng is None:
            raise ValueError(
                "train=True with dropout_keep < 1.0 requires an rng key"
            )
        drop_keys = jax.random.split(rng, n_layers)
    for i in range(n_layers):
        layer = params[f"layer_{i}"]
        h = h @ layer["kernel"].astype(compute_dtype) + layer["bias"].astype(compute_dtype)
        h = jax.nn.relu(h)
        if cfg.batch_norm:
            hf, new_bn_state[f"layer_{i}"] = batch_norm(
                h.astype(jnp.float32),
                bn_params[f"layer_{i}"],
                bn_state[f"layer_{i}"],
                train=train,
                decay=cfg.batch_norm_decay,
            )
            h = hf.astype(compute_dtype)
        if needs_dropout and cfg.dropout_keep[i] < 1.0:
            keep = cfg.dropout_keep[i]
            mask = jax.random.bernoulli(drop_keys[i], keep, h.shape)
            h = jnp.where(mask, h / keep, 0.0).astype(compute_dtype)
    out = params["out"]
    y = h @ out["kernel"].astype(compute_dtype) + out["bias"].astype(compute_dtype)
    return y[:, 0].astype(jnp.float32), new_bn_state


def init_deepfm(key: jax.Array, cfg: ModelConfig) -> tuple[dict, dict]:
    k_w, k_v, k_mlp = jax.random.split(key, 3)
    fm_v = glorot_normal(k_v, (cfg.feature_size, cfg.embedding_size))  # ps:192-198
    if cfg.fused_kernel != "off" and 128 % cfg.embedding_size == 0:
        # pre-pad to an aligned-window multiple with zero rows so the Pallas
        # wrapper never re-pads the table inside the per-step forward; the
        # rows are never gathered (ids clip to feature_size-1) and stay zero
        # under training (zero grads -> zero Adam updates, zero L2).
        # Deliberately keyed on the config value, NOT resolve_fused(): the
        # checkpointed table shape must not depend on which backend happened
        # to run init ("auto" on TPU vs a later CPU export/infer restore)
        pad = (-cfg.feature_size) % (128 // cfg.embedding_size)
        if pad:
            fm_v = jnp.pad(fm_v, ((0, pad), (0, 0)))
    params = {
        "fm_b": jnp.zeros((1,), jnp.float32),                      # ps:186-188
        "fm_w": glorot_normal(k_w, (cfg.feature_size,)),           # ps:189-191
        "fm_v": fm_v,
        "mlp": init_mlp(k_mlp, cfg.field_size * cfg.embedding_size, cfg),
    }
    state: dict = {}
    if cfg.batch_norm:
        params["bn"] = {}
        state["bn"] = {}
        for i, width in enumerate(cfg.deep_layers):
            params["bn"][f"layer_{i}"], state["bn"][f"layer_{i}"] = bn_init(width)
    return params, state


def apply_deepfm(
    params: dict,
    model_state: dict,
    feat_ids: jnp.ndarray,
    feat_vals: jnp.ndarray,
    *,
    cfg: ModelConfig,
    train: bool = False,
    rng: jax.Array | None = None,
    lookup_fn=dense_lookup,
) -> tuple[jnp.ndarray, dict]:
    """Forward pass: [B, F] int ids + [B, F] f32 vals -> [B] logits."""
    feat_ids = narrow_ids(feat_ids.reshape(-1, cfg.field_size),
                          cfg.feature_size, cfg.narrow_ids)
    feat_vals = feat_vals.reshape(-1, cfg.field_size).astype(jnp.float32)

    if cfg.fused_kernel == "on" and lookup_fn is not dense_lookup:
        raise ValueError(
            "fused_kernel='on' requires the dense single-table lookup path; "
            "lazy_embedding_updates and sharded (SPMD) tables substitute "
            "their own row lookup, which cannot be fused — use "
            "fused_kernel='auto' (or 'off') with those configs"
        )
    use_fused = lookup_fn is dense_lookup and resolve_fused(cfg.fused_kernel)
    if use_fused and 128 % cfg.embedding_size != 0:
        if cfg.fused_kernel == "on":
            raise ValueError(
                f"fused_kernel='on' needs embedding_size dividing 128, "
                f"got {cfg.embedding_size}"
            )
        use_fused = False  # "auto": quietly keep the XLA gather path
    if use_fused:
        from ..core.platform import is_tpu_backend

        # one HBM pass: both gathers + scaling + FM sums (ops/pallas_ctr.py)
        emb, y_w, y_v = fused_ctr_interaction(
            params["fm_w"], params["fm_v"], feat_ids, feat_vals,
            not is_tpu_backend(),  # interpret on CPU (tests)
        )
    else:
        if lookup_fn is dense_lookup and cfg.table_grad == "segsum":
            lookup_fn = segsum_lookup  # sorted-unique-write backward
        # first order (ps:206-209)
        feat_w = lookup_fn(params["fm_w"], feat_ids)        # [B, F]
        y_w = fm_first_order(feat_w, feat_vals)

        # second order (ps:211-217): e = V[ids] * vals
        if lookup_fn is dense_lookup:
            emb = scaled_embedding(params["fm_v"], feat_ids, feat_vals)
        else:
            emb = lookup_fn(params["fm_v"], feat_ids) * feat_vals[..., None]
        y_v = fm_second_order(emb)

    # deep tower (ps:228-255)
    deep_in = emb.reshape(emb.shape[0], cfg.field_size * cfg.embedding_size)
    y_d, new_bn = apply_mlp(
        params["mlp"],
        params.get("bn"),
        model_state.get("bn"),
        deep_in,
        cfg=cfg,
        train=train,
        rng=rng,
    )

    logits = params["fm_b"][0] + y_w + y_v + y_d            # ps:257-259
    new_state = dict(model_state)
    if cfg.batch_norm and train:
        new_state["bn"] = new_bn
    return logits, new_state


def deepfm_l2_penalty(params: dict, l2_reg: float) -> jnp.ndarray:
    """``l2_reg·(l2_loss(FM_W)+l2_loss(FM_V))`` where l2_loss = ½Σx²
    (ps:275-279).  The MLP L2 in the reference went to a collection that was
    never added to the loss (SURVEY §2a) — intentionally not applied."""
    total = jnp.zeros(())
    for key in ("fm_w", "fm_v", "embedding"):  # sparse tables only, per reference
        if key in params:
            total = total + jnp.sum(jnp.square(params[key]))
    return l2_reg * 0.5 * total


register_model("deepfm", init_deepfm, apply_deepfm, deepfm_l2_penalty)
