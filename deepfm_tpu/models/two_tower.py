"""Two-tower retrieval: dual encoders + in-batch sampled-softmax negatives.

BASELINE.json config 5: "Two-tower retrieval (MovieLens-25M) with in-batch
negative all-gather over ICI".  The reference repo has no retrieval model —
this extends the framework's embedding/SPMD machinery (the capability the
reference's PS embedding tables provide, README.md:15,63) to the retrieval
family that commonly shares CTR infrastructure.

Architecture (dual encoder, Yi et al. RecSys'19 style):

    u = normalize(MLP_u(flatten(E_u[user_ids] · user_vals)))   [B, D]
    i = normalize(MLP_i(flatten(E_i[item_ids] · item_vals)))   [B, D]
    scores = u · iᵀ / τ     — every other in-batch item is a negative
    loss   = softmax CE against the diagonal

Batch schema: ``{"user_ids" [B,Fu] i64, "user_vals" [B,Fu] f32,
"item_ids" [B,Fi] i64, "item_vals" [B,Fi] f32}`` (vals of 1.0 for pure-id
features).  This family has its own train/eval steps (train/retrieval.py
dense, parallel/retrieval.py sharded) because the loss couples examples
across the batch — the sharded step all-gathers item encodings over the
``data`` axis so every chip scores its queries against the GLOBAL batch's
items, with the gather riding ICI.

Tables are row-shardable over the ``model`` axis exactly like FM_W/FM_V
(params keys "user_embedding"/"item_embedding" are in parallel.spmd
TABLE_KEYS).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core.config import ModelConfig
from ..ops.embedding import dense_lookup, narrow_ids, segsum_lookup
from ..ops.initializers import glorot_normal, glorot_uniform


class TowerOutputs(NamedTuple):
    user: jnp.ndarray   # [B, D], L2-normalized
    item: jnp.ndarray   # [B, D], L2-normalized


def user_vocab(cfg: ModelConfig) -> int:
    return cfg.user_vocab_size or cfg.feature_size


def item_vocab(cfg: ModelConfig) -> int:
    return cfg.item_vocab_size or cfg.feature_size


def _init_tower(key: jax.Array, in_dim: int, cfg: ModelConfig) -> dict:
    params: dict = {}
    dims = [in_dim, *cfg.tower_layers]
    keys = jax.random.split(key, len(cfg.tower_layers) + 1)
    for l, (d_in, d_out) in enumerate(zip(dims[:-1], dims[1:])):
        params[f"layer_{l}"] = {
            "kernel": glorot_uniform(keys[l], (d_in, d_out)),
            "bias": jnp.zeros((d_out,), jnp.float32),
        }
    params["proj"] = {
        "kernel": glorot_uniform(keys[-1], (dims[-1], cfg.tower_dim)),
        "bias": jnp.zeros((cfg.tower_dim,), jnp.float32),
    }
    return params


def _apply_tower(params: dict, x: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    compute_dtype = jnp.dtype(cfg.compute_dtype)
    h = x.astype(compute_dtype)
    for l in range(len(cfg.tower_layers)):
        layer = params[f"layer_{l}"]
        h = h @ layer["kernel"].astype(compute_dtype) + layer["bias"].astype(
            compute_dtype
        )
        h = jax.nn.relu(h)
    proj = params["proj"]
    out = h @ proj["kernel"].astype(compute_dtype) + proj["bias"].astype(compute_dtype)
    out = out.astype(jnp.float32)
    return out / jnp.maximum(jnp.linalg.norm(out, axis=-1, keepdims=True), 1e-12)


def init_two_tower(key: jax.Array, cfg: ModelConfig) -> tuple[dict, dict]:
    k_ue, k_ie, k_ut, k_it = jax.random.split(key, 4)
    params = {
        "user_embedding": glorot_normal(
            k_ue, (user_vocab(cfg), cfg.embedding_size)
        ),
        "item_embedding": glorot_normal(
            k_ie, (item_vocab(cfg), cfg.embedding_size)
        ),
        "user_tower": _init_tower(
            k_ut, cfg.user_field_size * cfg.embedding_size, cfg
        ),
        "item_tower": _init_tower(
            k_it, cfg.item_field_size * cfg.embedding_size, cfg
        ),
    }
    return params, {}


def encode_tower(
    params: dict,
    ids: jnp.ndarray,
    vals: jnp.ndarray,
    *,
    cfg: ModelConfig,
    side: str,
    lookup_fn=dense_lookup,
) -> jnp.ndarray:
    """Encode one side (``side`` in {"user", "item"}): lookup -> scale ->
    tower MLP -> L2-normalized [B, D].  The serving-time entry point for
    encoding query users or corpus items independently."""
    field = cfg.user_field_size if side == "user" else cfg.item_field_size
    ids = narrow_ids(ids.reshape(-1, field),
                     user_vocab(cfg) if side == "user" else item_vocab(cfg),
                     cfg.narrow_ids)
    vals = vals.reshape(-1, field).astype(jnp.float32)
    if lookup_fn is dense_lookup and cfg.table_grad == "segsum":
        lookup_fn = segsum_lookup  # sorted-unique-write backward
    emb = lookup_fn(params[f"{side}_embedding"], ids) * vals[..., None]
    return _apply_tower(
        params[f"{side}_tower"],
        emb.reshape(emb.shape[0], field * cfg.embedding_size),
        cfg,
    )


def apply_two_tower(
    params: dict,
    batch: dict,
    *,
    cfg: ModelConfig,
    lookup_fn=dense_lookup,
    user_lookup_fn=None,
    item_lookup_fn=None,
) -> TowerOutputs:
    """Encode the batch's users and items.  ``user_lookup_fn``/
    ``item_lookup_fn`` override ``lookup_fn`` per table (the sharded path
    passes per-table lookups since the two vocabs shard independently)."""
    u = encode_tower(
        params, batch["user_ids"], batch["user_vals"],
        cfg=cfg, side="user", lookup_fn=user_lookup_fn or lookup_fn,
    )
    i = encode_tower(
        params, batch["item_ids"], batch["item_vals"],
        cfg=cfg, side="item", lookup_fn=item_lookup_fn or lookup_fn,
    )
    return TowerOutputs(user=u, item=i)


def in_batch_softmax_loss(
    user: jnp.ndarray,
    items: jnp.ndarray,
    label_idx: jnp.ndarray,
    *,
    temperature: float,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Sampled-softmax over in-batch negatives.

    user [b, D] queries, items [N, D] candidate pool (N ≥ b; the sharded path
    passes the all-gathered GLOBAL item set), label_idx [b] — the index in
    ``items`` of each query's positive.  Returns (per-example CE [b],
    scores [b, N]).
    """
    scores = (user @ items.T) / temperature
    log_probs = jax.nn.log_softmax(scores, axis=-1)
    ce = -jnp.take_along_axis(log_probs, label_idx[:, None], axis=1)[:, 0]
    return ce, scores


def retrieval_metrics(
    scores: jnp.ndarray, label_idx: jnp.ndarray, k: int = 10
) -> dict[str, jnp.ndarray]:
    """top-1 accuracy and recall@k of the positives within the score matrix."""
    top1 = (jnp.argmax(scores, axis=-1) == label_idx).astype(jnp.float32)
    true_score = jnp.take_along_axis(scores, label_idx[:, None], axis=1)
    rank = jnp.sum((scores > true_score).astype(jnp.int32), axis=-1)
    return {
        "top1_acc": jnp.mean(top1),
        f"recall_at_{k}": jnp.mean((rank < k).astype(jnp.float32)),
    }


def two_tower_l2_penalty(params: dict, l2_reg: float) -> jnp.ndarray:
    """Reference-style sparse-table L2 (ps:275-279 semantics) over both
    embedding tables; tower dense weights excluded."""
    total = jnp.zeros(())
    for k in ("user_embedding", "item_embedding"):
        total = total + jnp.sum(jnp.square(params[k]))
    return l2_reg * 0.5 * total
