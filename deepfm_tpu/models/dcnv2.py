"""DCN-v2: deep & cross network (Wang et al., WWW'21), parallel structure.

Swap-in model family for the DeepFM slot (BASELINE.json config "xDeepFM /
DCN-v2 swap-in").  Keeps the reference scaffold — [B, F] ids/vals schema,
shared scaled-embedding input (ps:212-214), deep tower (ps:230-255), sparse
L2 (ps:275-279) — and replaces the FM second-order term with a stack of
full-rank cross layers over the flattened embedding vector x0 [B, D], D=F·K:

    x_{l+1} = x0 ⊙ (W_l x_l + b_l) + x_l        l = 0..cfg.cross_layers-1
    y_cross = w_out · x_L

Combination is logit-additive (parallel deep & cross), matching the DeepFM
head style: y = b + y_cross + y_deep.

TPU mapping: each cross layer is one [B, D] × [D, D] MXU matmul plus fused
elementwise ops; the stack unrolls at trace time (static ``cross_layers``).
Matmuls run in ``cfg.compute_dtype`` (bf16), params stay f32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.config import ModelConfig
from ..ops.batch_norm import bn_init
from ..ops.embedding import (dense_lookup, narrow_ids, scaled_embedding,
                             segsum_lookup)
from ..ops.initializers import glorot_normal, glorot_uniform
from .base import register_model
from .deepfm import apply_mlp, deepfm_l2_penalty, init_mlp


def init_cross(key: jax.Array, dim: int, num_layers: int) -> dict:
    params: dict = {}
    keys = jax.random.split(key, num_layers + 1)
    for l in range(num_layers):
        params[f"layer_{l}"] = {
            "kernel": glorot_uniform(keys[l], (dim, dim)),
            "bias": jnp.zeros((dim,), jnp.float32),
        }
    params["out"] = {
        "kernel": glorot_uniform(keys[-1], (dim, 1)),
        "bias": jnp.zeros((1,), jnp.float32),
    }
    return params


def apply_cross(params: dict, x0: jnp.ndarray, *, cfg: ModelConfig) -> jnp.ndarray:
    """x0 [B, D] -> y_cross [B]."""
    compute_dtype = jnp.dtype(cfg.compute_dtype)
    x0c = x0.astype(compute_dtype)
    x = x0c
    for l in range(cfg.cross_layers):
        layer = params[f"layer_{l}"]
        wx = x @ layer["kernel"].astype(compute_dtype) + layer["bias"].astype(
            compute_dtype
        )
        x = x0c * wx + x
    out = params["out"]
    y = x @ out["kernel"].astype(compute_dtype) + out["bias"].astype(compute_dtype)
    return y[:, 0].astype(jnp.float32)


def init_dcnv2(key: jax.Array, cfg: ModelConfig) -> tuple[dict, dict]:
    k_v, k_cross, k_mlp = jax.random.split(key, 3)
    dim = cfg.field_size * cfg.embedding_size
    params = {
        "fm_b": jnp.zeros((1,), jnp.float32),
        "fm_v": glorot_normal(k_v, (cfg.feature_size, cfg.embedding_size)),
        "cross": init_cross(k_cross, dim, cfg.cross_layers),
        "mlp": init_mlp(k_mlp, dim, cfg),
    }
    state: dict = {}
    if cfg.batch_norm:
        params["bn"] = {}
        state["bn"] = {}
        for i, width in enumerate(cfg.deep_layers):
            params["bn"][f"layer_{i}"], state["bn"][f"layer_{i}"] = bn_init(width)
    return params, state


def apply_dcnv2(
    params: dict,
    model_state: dict,
    feat_ids: jnp.ndarray,
    feat_vals: jnp.ndarray,
    *,
    cfg: ModelConfig,
    train: bool = False,
    rng: jax.Array | None = None,
    lookup_fn=dense_lookup,
) -> tuple[jnp.ndarray, dict]:
    feat_ids = narrow_ids(feat_ids.reshape(-1, cfg.field_size),
                          cfg.feature_size, cfg.narrow_ids)
    feat_vals = feat_vals.reshape(-1, cfg.field_size).astype(jnp.float32)
    if lookup_fn is dense_lookup and cfg.table_grad == "segsum":
        lookup_fn = segsum_lookup  # sorted-unique-write backward

    if lookup_fn is dense_lookup:
        emb = scaled_embedding(params["fm_v"], feat_ids, feat_vals)
    else:
        emb = lookup_fn(params["fm_v"], feat_ids) * feat_vals[..., None]

    x0 = emb.reshape(emb.shape[0], cfg.field_size * cfg.embedding_size)
    y_cross = apply_cross(params["cross"], x0, cfg=cfg)
    y_d, new_bn = apply_mlp(
        params["mlp"],
        params.get("bn"),
        model_state.get("bn"),
        x0,
        cfg=cfg,
        train=train,
        rng=rng,
    )

    logits = params["fm_b"][0] + y_cross + y_d
    new_state = dict(model_state)
    if cfg.batch_norm and train:
        new_state["bn"] = new_bn
    return logits, new_state


register_model("dcnv2", init_dcnv2, apply_dcnv2, deepfm_l2_penalty)
