"""xDeepFM: linear + Compressed Interaction Network (CIN) + deep MLP.

Swap-in model family for the DeepFM slot (BASELINE.json config "xDeepFM /
DCN-v2 swap-in ... exercises cross-network kernels").  The reference repo
trains only DeepFM (model_fn, 1-ps-cpu/DeepFM-...py:172-313); xDeepFM keeps
that scaffold — same feature schema [B, F] ids/vals, same first-order term
(ps:207-209), same deep tower (ps:230-255), same sparse-table L2 (ps:275-279)
— and replaces the FM second-order identity with a CIN (Lian et al., KDD'18).

CIN layer k (hidden sizes ``cfg.cin_layers``):

    Z^k   = outer(X^{k-1}, X^0) along fields       [B, H_{k-1}, F, K]
    X^k_h = Σ_{i,j} W^k_{h,i,j} · Z^k_{i,j}        [B, H_k, K]
    p^k   = Σ_K X^k                                 [B, H_k]
    y_cin = w_out · concat_k(p^k)

TPU mapping: each CIN layer is two einsums — a batched outer product and a
contraction against W^k — which XLA fuses into one MXU matmul of shape
[B·K, H·F] × [H·F, H']; everything runs in ``cfg.compute_dtype`` (bf16) like
the MLP tower.  No scalar loops, no dynamic shapes: the layer stack is
unrolled at trace time from the static config tuple.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.config import ModelConfig
from ..ops.batch_norm import bn_init
from ..ops.embedding import (dense_lookup, narrow_ids, scaled_embedding,
                             segsum_lookup)
from ..ops.fm import fm_first_order
from ..ops.initializers import glorot_normal, glorot_uniform
from .base import register_model
from .deepfm import apply_mlp, deepfm_l2_penalty, init_mlp


def init_cin(key: jax.Array, cfg: ModelConfig) -> dict:
    """CIN filter stack + output head.  W^k has shape [H_{k-1}, F, H_k]."""
    params: dict = {}
    f = cfg.field_size
    sizes = [f, *cfg.cin_layers]
    keys = jax.random.split(key, len(cfg.cin_layers) + 1)
    for k, (h_prev, h_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        params[f"filter_{k}"] = glorot_uniform(
            keys[k], (h_prev * f, h_out)
        ).reshape(h_prev, f, h_out)
    total_pooled = sum(cfg.cin_layers)
    params["out"] = {
        "kernel": glorot_uniform(keys[-1], (total_pooled, 1)),
        "bias": jnp.zeros((1,), jnp.float32),
    }
    return params


def apply_cin(params: dict, emb: jnp.ndarray, *, cfg: ModelConfig) -> jnp.ndarray:
    """emb [B, F, K] -> y_cin [B] via the compressed interaction stack."""
    compute_dtype = jnp.dtype(cfg.compute_dtype)
    x0 = emb.astype(compute_dtype)                       # [B, F, K]
    xk = x0
    pooled = []
    for k in range(len(cfg.cin_layers)):
        w = params[f"filter_{k}"].astype(compute_dtype)  # [H_prev, F, H_out]
        # outer product along fields then contract with the filter:
        # one fused MXU contraction over (h: H_prev, f: F)
        z = jnp.einsum("bhk,bfk->bhfk", xk, x0)
        xk = jnp.einsum("bhfk,hfo->bok", z, w)           # [B, H_out, K]
        pooled.append(jnp.sum(xk, axis=2))               # sum-pool over K
    p = jnp.concatenate(pooled, axis=1)                  # [B, ΣH]
    out = params["out"]
    y = p @ out["kernel"].astype(compute_dtype) + out["bias"].astype(compute_dtype)
    return y[:, 0].astype(jnp.float32)


def apply_cin_reference(params: dict, emb: jnp.ndarray, *, cfg: ModelConfig) -> jnp.ndarray:
    """O(F²) loop oracle for ``apply_cin`` — test use only (f32 throughout)."""
    x0 = emb.astype(jnp.float32)
    xk = x0
    pooled = []
    for k in range(len(cfg.cin_layers)):
        w = params[f"filter_{k}"].astype(jnp.float32)
        h_prev, f, h_out = w.shape
        outs = []
        for h in range(h_out):
            acc = jnp.zeros(emb.shape[::2])              # [B, K]
            for i in range(h_prev):
                for j in range(f):
                    acc = acc + w[i, j, h] * xk[:, i, :] * x0[:, j, :]
            outs.append(acc)
        xk = jnp.stack(outs, axis=1)
        pooled.append(jnp.sum(xk, axis=2))
    p = jnp.concatenate(pooled, axis=1)
    out = params["out"]
    return (p @ out["kernel"] + out["bias"])[:, 0]


def init_xdeepfm(key: jax.Array, cfg: ModelConfig) -> tuple[dict, dict]:
    k_w, k_v, k_cin, k_mlp = jax.random.split(key, 4)
    params = {
        "fm_b": jnp.zeros((1,), jnp.float32),
        "fm_w": glorot_normal(k_w, (cfg.feature_size,)),
        "fm_v": glorot_normal(k_v, (cfg.feature_size, cfg.embedding_size)),
        "cin": init_cin(k_cin, cfg),
        "mlp": init_mlp(k_mlp, cfg.field_size * cfg.embedding_size, cfg),
    }
    state: dict = {}
    if cfg.batch_norm:
        params["bn"] = {}
        state["bn"] = {}
        for i, width in enumerate(cfg.deep_layers):
            params["bn"][f"layer_{i}"], state["bn"][f"layer_{i}"] = bn_init(width)
    return params, state


def apply_xdeepfm(
    params: dict,
    model_state: dict,
    feat_ids: jnp.ndarray,
    feat_vals: jnp.ndarray,
    *,
    cfg: ModelConfig,
    train: bool = False,
    rng: jax.Array | None = None,
    lookup_fn=dense_lookup,
) -> tuple[jnp.ndarray, dict]:
    feat_ids = narrow_ids(feat_ids.reshape(-1, cfg.field_size),
                          cfg.feature_size, cfg.narrow_ids)
    feat_vals = feat_vals.reshape(-1, cfg.field_size).astype(jnp.float32)
    if lookup_fn is dense_lookup and cfg.table_grad == "segsum":
        lookup_fn = segsum_lookup  # sorted-unique-write backward

    feat_w = lookup_fn(params["fm_w"], feat_ids)
    y_w = fm_first_order(feat_w, feat_vals)

    if lookup_fn is dense_lookup:
        emb = scaled_embedding(params["fm_v"], feat_ids, feat_vals)
    else:
        emb = lookup_fn(params["fm_v"], feat_ids) * feat_vals[..., None]

    y_cin = apply_cin(params["cin"], emb, cfg=cfg)

    deep_in = emb.reshape(emb.shape[0], cfg.field_size * cfg.embedding_size)
    y_d, new_bn = apply_mlp(
        params["mlp"],
        params.get("bn"),
        model_state.get("bn"),
        deep_in,
        cfg=cfg,
        train=train,
        rng=rng,
    )

    logits = params["fm_b"][0] + y_w + y_cin + y_d
    new_state = dict(model_state)
    if cfg.batch_norm and train:
        new_state["bn"] = new_bn
    return logits, new_state


register_model("xdeepfm", init_xdeepfm, apply_xdeepfm, deepfm_l2_penalty)
