from .base import ModelDef, get_model, register_model, registered_models  # noqa: F401
from .deepfm import apply_deepfm, deepfm_l2_penalty, init_deepfm  # noqa: F401
