from .base import ModelDef, get_model, register_model, registered_models  # noqa: F401
from .deepfm import apply_deepfm, deepfm_l2_penalty, init_deepfm  # noqa: F401
from .dcnv2 import apply_dcnv2, init_dcnv2  # noqa: F401
from .xdeepfm import apply_xdeepfm, init_xdeepfm  # noqa: F401
