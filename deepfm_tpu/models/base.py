"""Model interface + registry.

Every model family is a pair of pure functions over explicit pytrees:

    init(key, cfg)  -> (params, model_state)
    apply(params, model_state, feat_ids, feat_vals, *, cfg, train, rng,
          lookup_fn) -> (logits, new_model_state)

``params`` are trainable; ``model_state`` is non-trainable (e.g. batch-norm
moving stats) — the functional replacement for the reference's TF graph
collections.  ``lookup_fn`` abstracts embedding gathers so the same model
runs with replicated tables (single chip) or row-sharded tables
(``deepfm_tpu/parallel``) without modification.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

from ..core.config import ModelConfig


class ModelDef(NamedTuple):
    name: str
    init: Callable
    apply: Callable
    # (params, l2_reg) -> scalar regularization penalty; each family declares
    # which of its tables the reference-style L2 applies to.
    l2_penalty: Callable


def _no_penalty(params, l2_reg):
    return 0.0


_REGISTRY: dict[str, ModelDef] = {}


def register_model(
    name: str, init: Callable, apply: Callable, l2_penalty: Callable = _no_penalty
) -> ModelDef:
    md = ModelDef(name, init, apply, l2_penalty)
    _REGISTRY[name] = md
    return md


def get_model(name_or_cfg: str | ModelConfig) -> ModelDef:
    name = name_or_cfg if isinstance(name_or_cfg, str) else name_or_cfg.model_name
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; registered: {sorted(_REGISTRY)}"
        ) from None


def registered_models() -> list[str]:
    return sorted(_REGISTRY)
