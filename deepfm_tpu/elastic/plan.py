"""Minimal-traffic N→M redistribution planning (arxiv 2112.01075's frame).

A reshard moves a sharded ``TrainState`` — dense params/opt state and
row-sharded embedding tables (plus their lazy-Adam moments) — from a mesh
over N devices to a mesh over M.  The naive plan (gather everything to the
host, re-place) moves every byte twice through the slowest link in the
system; the minimal plan moves only the rows a device will own but does
not already hold, device-to-device:

* **tables** — each model shard owns a contiguous row window; after the
  topology change a device fetches only ``new_window − held_rows`` (a
  shrink that keeps the row-shard width moves ZERO table bytes — the
  surviving shards already own their windows; pad-row growth is zero-fill,
  never traffic);
* **dense leaves** — replicated; only devices that newly JOINED the mesh
  need a replica.

:func:`plan_reshard` computes this plan from two SPMD contexts by shape
inference alone (nothing materializes); :func:`reshard_state` applies it
to a live state with ``jit_row_adapter`` executables (checkpoint/
reshard.py) whose output shardings make XLA emit the device-to-device
collective — the ``audit_elastic`` trace contract lowers the same
executables under ``transfer_guard('disallow')`` to prove no table row
ever stages on the host.

:func:`choose_mesh` is the topology policy: keep the row-shard width as
stable as the device count allows, because a stable ``model_parallel``
keeps the padded vocabulary — and therefore every published artifact
shape — identical across the reshard (the serving pool's swap stays a jit
cache hit; see ElasticConfig.prefer_model_parallel).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

# the authoritative row-sharded-table key list (parallel/spmd.py drives
# every sharding rule from it; a copy here would silently miss new tables)
from ..parallel.spmd import TABLE_KEYS


def choose_mesh(
    n_devices: int, *, prefer_model_parallel: int = 1
) -> tuple[int, int]:
    """``(data_parallel, model_parallel)`` for ``n_devices``: the largest
    divisor of the device count not exceeding the preferred row-shard
    width.  [8 devices, prefer 4] -> (2, 4); [4, prefer 4] -> (1, 4);
    [6, prefer 4] -> (2, 3); [3, prefer 4] -> (1, 3)."""
    if n_devices < 1:
        raise ValueError(f"need at least one device, got {n_devices}")
    prefer = max(1, prefer_model_parallel)
    mp = max(d for d in range(1, min(prefer, n_devices) + 1)
             if n_devices % d == 0)
    return n_devices // mp, mp


def _windows(rows: int, mp: int) -> list[tuple[int, int]]:
    """Contiguous per-model-shard row windows (rows % mp == 0 by the
    padded-vocab construction)."""
    per = rows // mp
    return [(m * per, (m + 1) * per) for m in range(mp)]


def _overlap(a: tuple[int, int], b: tuple[int, int]) -> int:
    return max(0, min(a[1], b[1]) - max(a[0], b[0]))


@dataclass(frozen=True)
class ReshardPlan:
    """The planned N→M redistribution, bytes-accounted per leaf class.

    ``moved_bytes`` is the device-to-device traffic of the minimal plan;
    ``kept_bytes`` the rows that stay put; ``naive_bytes`` what the
    gather-to-host round trip would have moved (every byte down AND back
    up) — the number the plan exists to beat.  ``host_round_trip`` is
    structurally False: there is no code path in this planner that stages
    a table row on the host, and ``audit_elastic`` holds the executables
    to it at lowering time."""

    from_shape: tuple[int, int]
    to_shape: tuple[int, int]
    from_padded_vocab: int
    to_padded_vocab: int
    tables: dict[str, dict] = field(default_factory=dict)
    moved_bytes: int = 0
    kept_bytes: int = 0
    dense_bytes: int = 0
    joined_devices: int = 0
    naive_bytes: int = 0
    host_round_trip: bool = False

    def validate_target(self, ctx) -> None:
        """Fail before any bytes move if ``ctx`` is not the topology this
        plan was drawn for."""
        from ..parallel.mesh import mesh_shape

        got = mesh_shape(ctx.mesh)
        if tuple(got) != tuple(self.to_shape):
            raise ValueError(
                f"reshard plan targets mesh {list(self.to_shape)} but the "
                f"restore context is {list(got)}"
            )
        if ctx.cfg.model.feature_size != self.to_padded_vocab:
            raise ValueError(
                f"reshard plan targets padded vocab {self.to_padded_vocab} "
                f"but the restore context pads to "
                f"{ctx.cfg.model.feature_size}"
            )

    def summary(self) -> dict:
        return {
            "from_mesh": list(self.from_shape),
            "to_mesh": list(self.to_shape),
            "from_padded_vocab": self.from_padded_vocab,
            "to_padded_vocab": self.to_padded_vocab,
            "moved_bytes": self.moved_bytes,
            "kept_bytes": self.kept_bytes,
            "dense_bytes": self.dense_bytes,
            "joined_devices": self.joined_devices,
            "naive_bytes": self.naive_bytes,
            "host_round_trip": self.host_round_trip,
            "tables": self.tables,
        }


def _is_table_path(path) -> bool:
    keys = {getattr(p, "key", None) for p in path}
    return bool(keys & set(TABLE_KEYS))


def plan_reshard(old_ctx, new_ctx) -> ReshardPlan:
    """Draw the minimal-traffic plan between two SPMD contexts.

    Shape inference only: table leaves are identified by path (the
    TABLE_KEYS discipline of ``parallel/spmd._spec_for_leaf``), their
    per-device row windows intersected between topologies, and the
    residual — window rows that existed in the old table but were not
    held by the device that now owns them — is the plan's traffic.  Rows
    in the padding gap are zero-fill, never traffic."""
    import jax

    from ..parallel.spmd import abstract_spmd_state

    old_dp, old_mp = old_ctx.mesh.shape["data"], old_ctx.mesh.shape["model"]
    new_dp, new_mp = new_ctx.mesh.shape["data"], new_ctx.mesh.shape["model"]
    pv_old = old_ctx.cfg.model.feature_size
    pv_new = new_ctx.cfg.model.feature_size
    old_devs = list(old_ctx.mesh.devices.flat)
    new_devs = list(new_ctx.mesh.devices.flat)

    # rows each surviving device held before the reshard (its model-shard
    # window, identical across the data axis it sat on)
    held: dict[Any, tuple[int, int]] = {}
    old_wins = _windows(pv_old, old_mp)
    for flat_idx, d in enumerate(old_devs):
        held[d] = old_wins[flat_idx % old_mp]

    new_wins = _windows(pv_new, new_mp)
    joined = sum(1 for d in new_devs if d not in held)

    leaves = jax.tree_util.tree_flatten_with_path(
        abstract_spmd_state(old_ctx)
    )[0]
    tables: dict[str, dict] = {}
    moved = kept = dense = naive = 0
    for path, leaf in leaves:
        if not hasattr(leaf, "shape") or not leaf.shape:
            continue
        nbytes_per_row = leaf.dtype.itemsize
        for dim in leaf.shape[1:]:
            nbytes_per_row *= dim
        if _is_table_path(path) and leaf.shape[0] == pv_old:
            t_moved = t_kept = 0
            for flat_idx, d in enumerate(new_devs):
                lo, hi = new_wins[flat_idx % new_mp]
                want = _overlap((lo, hi), (0, pv_old))  # real rows only
                have = (_overlap((lo, hi), held[d]) if d in held else 0)
                have = min(have, want)
                t_moved += want - have
                t_kept += have
            key = jax.tree_util.keystr(path)
            tables[key] = {
                "rows_from": pv_old,
                "rows_to": pv_new,
                "row_bytes": nbytes_per_row,
                "moved_bytes": t_moved * nbytes_per_row,
                "kept_bytes": t_kept * nbytes_per_row,
            }
            moved += t_moved * nbytes_per_row
            kept += t_kept * nbytes_per_row
            # naive: one full gather down + one full scatter back up
            naive += 2 * pv_old * nbytes_per_row
        else:
            b = leaf.shape[0] * nbytes_per_row
            dense += b * joined  # replicas only for devices that joined
            naive += 2 * b
    return ReshardPlan(
        from_shape=(old_dp, old_mp),
        to_shape=(new_dp, new_mp),
        from_padded_vocab=pv_old,
        to_padded_vocab=pv_new,
        tables=tables,
        moved_bytes=moved,
        kept_bytes=kept,
        dense_bytes=dense,
        joined_devices=joined,
        naive_bytes=naive,
        host_round_trip=False,
    )


def reshard_state(state, new_ctx):
    """Apply a reshard to a LIVE state: every table leaf's rows adapt
    on-device to the new padded vocab under the new sharding
    (``jit_row_adapter`` — XLA emits the device-to-device plan), every
    other leaf re-places with ``device_put``.  The elastic controller's
    resume path restores from the committed Orbax payload instead
    (exactly-once needs the durable snapshot); this is the in-memory fast
    path for planned topology changes where no replay is required.

    Zero-sharded optimizer state (``train/optimizer.ZeroDpState``) moves
    too: a dp change re-windows the flat moment leaves on-device exactly
    like table rows (their layout is the canonical flatten), and a move
    across the dp==1 boundary — where the sharded update switches on or
    off and the opt_state STRUCTURE changes — relays through
    ``checkpoint.reshard.relayout_state``."""
    import jax

    from ..checkpoint.reshard import (
        _is_zero_leaf,
        _reshape_under_sharding_ok,
        jit_row_adapter,
        relayout_state,
    )
    from ..parallel.spmd import abstract_spmd_state

    target_shapes = abstract_spmd_state(new_ctx)
    if (jax.tree_util.tree_structure(state)
            != jax.tree_util.tree_structure(target_shapes)):
        # opt-state layout flips across the dp==1 boundary: leaves pair
        # by flatten order and relayout through the canonical flat form
        return relayout_state(
            state, target_shapes, new_ctx.state_shardings
        )
    target_by_path = {
        jax.tree_util.keystr(p): l
        for p, l in jax.tree_util.tree_flatten_with_path(target_shapes)[0]
    }
    pv_new = new_ctx.cfg.model.feature_size

    def _dim0_partitions(sharding) -> int:
        spec = sharding.spec
        if not spec or spec[0] is None:
            return 1
        names = spec[0] if isinstance(spec[0], tuple) else (spec[0],)
        p = 1
        for nm in names:
            p *= sharding.mesh.shape[nm]
        return p

    def adapt(path, leaf, sharding):
        if _is_zero_leaf(path) and hasattr(leaf, "shape"):
            # zero-layout opt-state leaf: TERMINAL branch.  The canonical
            # flat form adapts through relayout_state's reform (handles
            # dp re-windowing AND the rare eligibility flip where a leaf
            # changes rank between topologies); an unchanged shape (the
            # flat length is dp-independent) re-places as-is — it must
            # NOT fall through to the table row-adapter, whose pv_new
            # target would slice a (pv*dim,) flat moment down to (pv,)
            tgt = target_by_path.get(jax.tree_util.keystr(path))
            if tgt is not None and tuple(leaf.shape) != tuple(tgt.shape):
                return jax.tree_util.tree_leaves(relayout_state(
                    [leaf], [tgt], [sharding]
                ))[0]
            return jax.device_put(leaf, sharding)
        if (
            _is_table_path(path)
            and hasattr(leaf, "shape")
            and leaf.ndim >= 1
            and leaf.shape[0] != pv_new
        ):
            rows_to = pv_new
            # the SAVED row count must divide the target's dim0 partitions
            # for the staged device_put (device_put requires divisibility);
            # odd paddings (e.g. 117,582 rows onto mp=4) take the
            # host-staged fallback — the same condition
            # _restore_resharded_tree guards with make_abstract
            if (
                _reshape_under_sharding_ok(sharding)
                and leaf.shape[0] % _dim0_partitions(sharding) == 0
            ):
                # stage the saved-shape rows onto the NEW mesh first
                # (device_put moves shards directly; one jitted
                # executable cannot span two device sets), then
                # re-window entirely on the new topology
                from jax.sharding import NamedSharding

                staged = jax.device_put(
                    leaf, NamedSharding(sharding.mesh, sharding.spec)
                )
                return jit_row_adapter(sharding, rows_to)(staged)
            import numpy as np

            host = np.asarray(jax.device_get(leaf))
            if host.shape[0] >= rows_to:
                host = host[:rows_to]
            else:
                pad = rows_to - host.shape[0]
                host = np.concatenate(
                    [host, np.zeros((pad, *host.shape[1:]), host.dtype)]
                )
            return jax.device_put(host, sharding)
        return jax.device_put(leaf, sharding)

    return jax.tree_util.tree_map_with_path(
        adapt, state, new_ctx.state_shardings
    )
