"""Multi-host elastic coordination: TTL leases, registry-view consensus,
a two-phase reshard barrier, and monotone fencing tokens.

PR 9's :class:`~deepfm_tpu.elastic.controller.ElasticTrainer` made mesh
shape a runtime variable for ONE process.  A pod is many processes, and a
synchronous SPMD program cannot let any of them reshard alone: every
process must agree on *which* membership epoch it is training in, drain
together, and rebuild the same mesh from the same device set.  This module
is that agreement, in the house style — a small stdlib HTTP service (like
``utils/dev_object_store.py``), clients under the PR 3
``RetryPolicy``/``CircuitBreaker``, faults scriptable through the same
:class:`~deepfm_tpu.utils.dev_object_store.FaultPlan`.

Protocol (one coordinator process, N participants):

* **lease** — each participant (``role="train"`` or ``"publish"``) holds a
  TTL lease it refreshes by heartbeating its local registry view (the
  device ids it can currently address).  A process that stops heartbeating
  is expired and drops out of consensus — crash detection without any
  platform integration.
* **consensus** — the coordinator merges the live trainers' views into ONE
  device set (:func:`merge_views`: the intersection — a device anyone lost
  is out for everyone) and names each agreed set with a monotone **epoch**.
* **two-phase barrier** — when the merged set changes the coordinator opens
  a *transition*: phase ``drain`` (every trainer admitted to the old epoch
  finishes its in-flight step and commits), then — only once ALL of them
  acked — phase ``reshard`` (the new epoch + device set become visible and
  every trainer rebuilds its mesh), then ``steady`` once all acked again.
  No process can observe the new device set while another is still
  stepping on the old one.
* **fencing token** — the monotone token is issued per COHORT, not per
  member: every trainer admitted to an epoch holds the SAME token (they
  are co-writers of one checkpoint root — replicas of one synchronous
  program — and must be able to advance one fence without refusing each
  other), and any trainer membership change (join, expiry, release,
  eviction) forces an epoch flip that re-issues a strictly newer shared
  token to the survivors.  Publishers are single writers of their own
  root, so each publisher *incarnation* gets its own strictly-newer
  token at acquire.  The token is threaded through ``commit_payload``
  and ``ModelPublisher.publish`` and recorded durably next to the data
  (:class:`Fence`); a write bearing a token older than the recorded
  high-water mark is REFUSED.  A zombie process that missed an epoch
  (expired lease, long GC pause, network partition) can therefore not
  corrupt the checkpoint lineage or the publish root — the "single
  logical writer" contract becomes an enforced invariant instead of a
  ValueError at construction time.

Graceful degradation (the client side, :class:`CoordinatedRegistry`):

* coordinator unreachable → **frozen topology**: the trainer keeps
  training on its current mesh under a circuit breaker (one probe per
  cooldown, not a retry storm), flight-recorded; commits continue and stay
  safe because the fence refuses them the moment another process was
  admitted in its place.
* lease expired (the coordinator outlived a partition) → **self-fence**:
  the process stops committing and drains until it is re-admitted with a
  fresh lease + token, then reshards onto the live consensus and replays
  the uncommitted tail exactly-once from its last durable commit.

Run standalone:  python -m deepfm_tpu.elastic.coord --port 8600
In tests:        serve_coordinator(Coordinator(...)) -> (server, url)
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Sequence

from ..obs import flight as obs_flight
from ..obs.metrics import MetricsRegistry
from ..utils.retry import CircuitBreaker, RetryPolicy

FENCE_NAME = "_FENCE.json"


# ---------------------------------------------------------------------------
# fencing: a durable monotone high-water mark next to the data


class StaleFencingTokenError(RuntimeError):
    """A write carried a fencing token older than the root's recorded
    high-water mark — the writer missed an epoch and must not touch this
    root again until re-admitted."""


def _fence_path(root: str) -> str:
    from ..data.object_store import is_url, join_url

    return join_url(root, FENCE_NAME) if is_url(root) else os.path.join(
        root, FENCE_NAME)


def read_fence(root: str) -> int:
    """The root's recorded token high-water mark (0 = never fenced)."""
    from ..data.object_store import get_store, is_url

    path = _fence_path(root)
    try:
        if is_url(root):
            raw = get_store().get(path)
        else:
            with open(path, "rb") as f:
                raw = f.read()
    except FileNotFoundError:
        return 0
    except Exception as e:
        from ..data.object_store import ObjectStoreError

        if isinstance(e, ObjectStoreError) and e.status == 404:
            return 0
        raise
    return int(json.loads(raw.decode()).get("token", 0))


def write_fence(root: str, token: int, *, holder: str = "") -> None:
    from ..data.object_store import get_store, is_url

    doc = json.dumps({"token": int(token), "holder": holder,
                      "written_unix": time.time()}).encode()
    path = _fence_path(root)
    if is_url(root):
        get_store().put(path, doc)
        return
    os.makedirs(root, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(doc)
    os.replace(tmp, path)


class Fence:
    """One writer's claim on one root: ``check()`` refuses when a newer
    holder already advanced the mark, ``advance()`` records this token as
    the new high-water mark (never lowers it).

    The check-then-write window is NOT atomic; the coordinator closes it
    upstream (a higher token is only issued after the old epoch drained or
    its lease expired), so the fence is the storage-level backstop that
    turns the residual zombie window into a refused write instead of a
    corrupted lineage."""

    def __init__(self, root: str, token: int, *, holder: str = ""):
        self.root = root
        self.token = int(token)
        self.holder = holder

    def check(self) -> int:
        """Refuse if a newer holder advanced the mark; returns the stored
        token so callers don't re-read it."""
        stored = read_fence(self.root)
        if stored > self.token:
            raise StaleFencingTokenError(
                f"fencing token {self.token} is stale for {self.root!r}: "
                f"recorded high-water mark is {stored} — a newer holder "
                f"was admitted; refusing the write"
            )
        return stored

    def advance(self) -> None:
        if self.check() < self.token:
            write_fence(self.root, self.token, holder=self.holder)


# ---------------------------------------------------------------------------
# consensus: the registry-view merge


def merge_views(views: dict[str, Sequence]) -> tuple:
    """Merge per-process registry views into the consensus device set:
    the INTERSECTION of every live trainer's view — a device any process
    lost is out for everyone (a synchronous program cannot address a
    device one participant cannot), and a lost device only returns once
    every process sees it again.  Order follows the view of the smallest
    participant id (all processes of one job report the same global
    order, so this is a deterministic tie-break, not a preference);
    merge is therefore order-independent across participants."""
    if not views:
        return ()
    common = None
    for ids in views.values():
        s = set(ids)
        common = s if common is None else (common & s)
    anchor = views[min(views)]
    return tuple(i for i in anchor if i in common)


# ---------------------------------------------------------------------------
# the coordinator (pure logic; HTTP layer below)


class _Member:
    __slots__ = ("pid", "role", "lease_id", "token", "expires", "ttl",
                 "view", "acked_drain", "acked_reshard", "admitted_epoch")

    def __init__(self, pid, role, lease_id, token, expires, ttl, view):
        self.pid = pid
        self.role = role
        self.lease_id = lease_id
        self.token = token
        self.expires = expires
        self.ttl = ttl
        self.view = tuple(view)
        self.acked_drain = -1
        self.acked_reshard = -1
        self.admitted_epoch = None  # set on reshard ack: built a topology


class LeaseExpired(Exception):
    """Server-side: the heartbeating lease is gone — the caller must
    self-fence and re-acquire."""


class Coordinator:
    """Lease + consensus + barrier state machine.  All public methods are
    thread-safe; ``clock`` is injectable so expiry tests run on a fake
    clock with zero real sleeps."""

    def __init__(
        self,
        *,
        lease_ttl_secs: float = 10.0,
        barrier_timeout_secs: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
        metrics: MetricsRegistry | None = None,
    ):
        if lease_ttl_secs <= 0:
            raise ValueError(
                f"lease_ttl_secs must be > 0, got {lease_ttl_secs}")
        self._ttl = float(lease_ttl_secs)
        # drain barriers evict live-but-stuck members after this long (a
        # DEAD member is reclaimed by the lease TTL; the timeout is the
        # backstop for one wedged process stalling the whole pod).  0
        # disables eviction.
        self._barrier_timeout = float(barrier_timeout_secs)
        self._clock = clock
        self._lock = threading.Lock()
        self._members: dict[str, _Member] = {}
        self._fence_counter = 0
        self._cohort_token = 0  # the shared train-cohort token (per epoch)
        self._lease_seq = 0
        self.epoch = 0
        self.devices: tuple = ()
        self.phase = "steady"          # steady | drain | reshard
        self.transition = 0
        self._pending_devices: tuple | None = None
        self._pending_epoch: int | None = None
        self._transition_started: float | None = None
        m = metrics or MetricsRegistry()
        self.metrics = m
        self._m_epoch = m.gauge(
            "deepfm_coord_epoch", "consensus membership epoch")
        self._m_members = m.gauge(
            "deepfm_coord_members", "live leases", labels=("role",))
        self._m_transitions = m.counter(
            "deepfm_coord_transitions_total", "barrier transitions opened")
        self._m_expired = m.counter(
            "deepfm_coord_leases_expired_total", "leases dropped on TTL")
        self._m_evicted = m.counter(
            "deepfm_coord_barrier_evictions_total",
            "members evicted for stalling a barrier past its timeout")

    # -- state machine (call with _lock held) -------------------------------
    def _trainers(self) -> list[_Member]:
        return [m for m in self._members.values() if m.role == "train"]

    def _sweep(self) -> None:
        now = self._clock()
        expired = [m for m in self._members.values() if m.expires <= now]
        for m in expired:
            del self._members[m.pid]
            self._m_expired.inc()
            obs_flight.record("coord_lease_expired", subsystem="coord",
                              pid=m.pid, role=m.role)
        if self._barrier_timeout > 0 \
                and self._transition_started is not None \
                and now - self._transition_started >= self._barrier_timeout:
            # BOTH barriers get the backstop (the timer restarts at the
            # flip): a wedged member that drain-acked but never reshard-
            # acks would otherwise pin the reshard phase forever
            if self.phase == "drain":
                stalled = [m for m in self._trainers()
                           if m.admitted_epoch is not None
                           and m.acked_drain != self.transition]
            elif self.phase == "reshard":
                stalled = [m for m in self._trainers()
                           if m.acked_reshard != self.transition]
            else:
                stalled = []
            for m in stalled:
                del self._members[m.pid]
                self._m_evicted.inc()
                obs_flight.record("coord_barrier_evicted",
                                  subsystem="coord", pid=m.pid,
                                  phase=self.phase,
                                  transition=self.transition)
            expired.extend(stalled)
        if any(m.role == "train" for m in expired):
            # a trainer LEFT: membership changed even if the merged device
            # set did not, and the flip must re-issue the cohort token so
            # the departed process's copy goes stale
            self._recompute(force=True)
        self._refresh_gauges()

    def _recompute(self, *, force: bool = False) -> None:
        """Re-derive consensus from the live trainer views.  ``force``
        opens a transition even when the merged device set is unchanged —
        trainer membership changes (join / expiry / release / eviction)
        must flip the epoch so the new shared cohort token stales every
        token held outside the new cohort.  Only a transition still in
        its DRAIN phase needs no restart for that: its flip is ahead and
        re-issues anyway.  In the reshard phase the flip already
        happened, so a membership change there must restart the
        transition or the departed process would keep a token equal to
        the live cohort's forever."""
        merged = merge_views({m.pid: m.view for m in self._trainers()})
        target = (self.devices if self.phase == "steady"
                  else self._pending_devices)
        if merged == target and not (force and self.phase != "drain"):
            self._advance_barrier()
            return
        # the merged set moved: open (or restart) a transition.  Restart
        # invalidates stale acks — ack payloads carry the transition id.
        self.transition += 1
        self.phase = "drain"
        self._pending_devices = merged
        self._pending_epoch = self.epoch + 1
        self._transition_started = self._clock()
        self._m_transitions.inc()
        obs_flight.record("coord_transition", subsystem="coord",
                          transition=self.transition,
                          pending_epoch=self._pending_epoch,
                          devices=len(merged))
        self._advance_barrier()

    def _advance_barrier(self) -> None:
        if self.phase == "drain":
            need = [m for m in self._trainers()
                    if m.admitted_epoch is not None]
            if all(m.acked_drain == self.transition for m in need):
                # every old-epoch trainer drained+committed: flip the
                # epoch, expose the new set, and issue ONE new cohort
                # token shared by every live trainer — co-writers of the
                # same checkpoint root must hold EQUAL tokens (distinct
                # values would make each cohort member's advance fence
                # out its peers), while anything that missed this flip
                # holds a strictly older token the fences refuse.
                # Publishers keep their per-incarnation acquire tokens.
                self.epoch = self._pending_epoch
                self.devices = tuple(self._pending_devices or ())
                self.phase = "reshard"
                # the reshard barrier gets its own full timeout window —
                # a restore is legitimately slower than a drain
                self._transition_started = self._clock()
                self._fence_counter += 1
                self._cohort_token = self._fence_counter
                for m in self._members.values():
                    if m.role == "train":
                        m.token = self._cohort_token
                obs_flight.record("coord_epoch", subsystem="coord",
                                  epoch=self.epoch,
                                  devices=len(self.devices))
        if self.phase == "reshard":
            if all(m.acked_reshard == self.transition
                   for m in self._trainers()):
                self.phase = "steady"
                self._pending_devices = None
                self._pending_epoch = None
                self._transition_started = None
        self._refresh_gauges()

    def _refresh_gauges(self) -> None:
        self._m_epoch.set(self.epoch)
        for role in ("train", "publish"):
            self._m_members.labels(role).set(
                sum(1 for m in self._members.values() if m.role == role))

    def _consensus(self) -> dict:
        return {
            "epoch": self.epoch,
            "devices": list(self.devices),
            "phase": self.phase,
            "transition": self.transition,
            "pending_epoch": self._pending_epoch,
            "pending_devices": (None if self._pending_devices is None
                                else list(self._pending_devices)),
        }

    def _lease_doc(self, m: _Member) -> dict:
        return {"lease_id": m.lease_id, "token": m.token,
                "ttl_secs": m.ttl}

    def _grant_ttl(self, requested) -> float:
        """The participant requests a TTL at acquire; the coordinator's
        own ``lease_ttl_secs`` is both the default and the CEILING (a
        shorter lease is honored, a longer one is clamped — expiry must
        stay coordinator-bounded)."""
        import math

        if requested is None:
            return self._ttl
        try:
            req = float(requested)
        except (TypeError, ValueError):
            # non-numeric JSON must answer 400, not tear the connection
            raise ValueError(
                f"ttl_secs must be a number, got {requested!r}") from None
        # NaN passes every <=/min comparison and would mint a lease that
        # can never TTL-expire (expires=NaN fails `expires <= now`
        # forever), pinning its stale view in consensus — refuse anything
        # non-finite alongside non-positive
        if not (req > 0 and math.isfinite(req)):
            raise ValueError(f"ttl_secs must be finite and > 0, "
                             f"got {requested}")
        return min(req, self._ttl)

    def _validate(self, pid: str, lease_id: str) -> _Member:
        m = self._members.get(pid)
        if m is None or m.lease_id != lease_id:
            raise LeaseExpired(pid)
        return m

    # -- participant API ----------------------------------------------------
    def acquire(self, pid: str, role: str = "train",
                view: Sequence = (), ttl_secs: float | None = None) -> dict:
        if role not in ("train", "publish"):
            raise ValueError(f"unknown role {role!r} (train|publish)")
        with self._lock:
            self._sweep()
            self._lease_seq += 1
            ttl = self._grant_ttl(ttl_secs)
            if role == "publish":
                # one publisher per publish root: each INCARNATION gets a
                # strictly newer token, so a replaced publisher's first
                # advance fences its predecessor out
                self._fence_counter += 1
                token = self._fence_counter
            else:
                # trainers share the cohort token; the forced transition
                # below re-issues a strictly newer one at its flip, which
                # is what stales this pid's previous incarnation
                token = self._cohort_token
            m = _Member(
                pid=pid, role=role,
                lease_id=f"L{self._lease_seq}-{pid}",
                token=token,
                expires=self._clock() + ttl,
                ttl=ttl,
                view=view if role == "train" else (),
            )
            self._members[pid] = m  # rejoin replaces: old lease_id dies
            obs_flight.record("coord_lease_acquired", subsystem="coord",
                              pid=pid, role=role, token=m.token)
            if role == "train":
                self._recompute(force=True)
            else:
                self._refresh_gauges()
            return {"lease": self._lease_doc(m),
                    "consensus": self._consensus()}

    def heartbeat(self, pid: str, lease_id: str,
                  view: Sequence | None = None,
                  on_epoch: int | None = None) -> dict:
        with self._lock:
            self._sweep()
            m = self._validate(pid, lease_id)
            m.expires = self._clock() + m.ttl
            if m.role == "train" and on_epoch is not None:
                # the epoch this member is TRAINING ON: a member that
                # joined an already-steady consensus registers here, so
                # the next drain barrier waits for it too
                m.admitted_epoch = int(on_epoch)
            if m.role == "train" and view is not None \
                    and tuple(view) != m.view:
                m.view = tuple(view)
                self._recompute()
            return {"lease": self._lease_doc(m),
                    "consensus": self._consensus()}

    def ack(self, pid: str, lease_id: str, phase: str,
            transition: int) -> dict:
        with self._lock:
            self._sweep()
            m = self._validate(pid, lease_id)
            m.expires = self._clock() + m.ttl
            if transition == self.transition:
                if phase == "drain":
                    m.acked_drain = transition
                elif phase == "reshard":
                    m.acked_reshard = transition
                    m.admitted_epoch = self.epoch
                else:
                    raise ValueError(f"unknown barrier phase {phase!r}")
                self._advance_barrier()
            return {"lease": self._lease_doc(m),
                    "consensus": self._consensus()}

    def release(self, pid: str, lease_id: str) -> dict:
        with self._lock:
            m = self._members.get(pid)
            if m is not None and m.lease_id == lease_id:
                del self._members[pid]
                obs_flight.record("coord_lease_released",
                                  subsystem="coord", pid=pid, role=m.role)
                if m.role == "train":
                    self._recompute(force=True)
                self._refresh_gauges()
            return {"consensus": self._consensus()}

    def status(self) -> dict:
        with self._lock:
            self._sweep()
            return {
                "consensus": self._consensus(),
                "fence_counter": self._fence_counter,
                "cohort_token": self._cohort_token,
                "members": {
                    pid: {
                        "role": m.role, "token": m.token,
                        "ttl_secs": m.ttl,
                        "view": list(m.view),
                        "expires_in_secs": round(
                            m.expires - self._clock(), 3),
                        "acked_drain": m.acked_drain,
                        "acked_reshard": m.acked_reshard,
                        "admitted_epoch": m.admitted_epoch,
                    }
                    for pid, m in sorted(self._members.items())
                },
            }


# ---------------------------------------------------------------------------
# HTTP layer


def _make_handler(coord: Coordinator, plan):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        disable_nagle_algorithm = True

        def log_message(self, *a):  # quiet
            pass

        def _send(self, code: int, doc: dict) -> None:
            body = json.dumps(doc).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_text(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _fault(self, verb: str, key: str) -> bool:
            """Consult the shared FaultPlan (verbs: ACQUIRE / HEARTBEAT /
            ACK / RELEASE / STATUS, key = participant pid); True when the
            fault already answered (error status or dropped connection)."""
            if plan is None:
                return False
            rule = plan.match(verb, key)
            if rule is None:
                return False
            if rule.delay_secs > 0:
                time.sleep(rule.delay_secs)
            if rule.drop:
                self.close_connection = True
                try:
                    self.connection.close()
                except OSError:
                    pass
                return True
            if rule.status:
                self._send(rule.status, {"error": "injected fault"})
                return True
            return False

        def do_GET(self) -> None:
            if self.path == "/__faults__" and plan is not None:
                return self._send(200, plan.to_dict())
            if self.path == "/metrics":
                return self._send_text(
                    200, coord.metrics.render_prometheus().encode(),
                    "text/plain; version=0.0.4")
            if self.path in ("/v1/status", "/v1/metrics"):
                if self._fault("STATUS", ""):
                    return
                doc = coord.status()
                if self.path == "/v1/metrics":
                    doc = {"coord": doc}
                return self._send(200, doc)
            self._send(404, {"error": "no such endpoint"})

        def do_POST(self) -> None:
            length = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(length)
            if self.path == "/__faults__" and plan is not None:
                try:
                    doc = json.loads(raw or b"{}")
                    plan.set_rules(doc.get("rules", []),
                                   seed=doc.get("seed"))
                except (ValueError, TypeError) as e:
                    return self._send(400, {"error": f"bad fault plan: {e}"})
                return self._send(200, {"ok": True})
            try:
                req = json.loads(raw or b"{}")
            except ValueError as e:
                return self._send(400, {"error": f"bad json: {e}"})
            pid = str(req.get("pid", ""))
            try:
                if self.path == "/v1/lease/acquire":
                    if self._fault("ACQUIRE", pid):
                        return
                    return self._send(200, coord.acquire(
                        pid, role=req.get("role", "train"),
                        view=req.get("view", ()),
                        ttl_secs=req.get("ttl_secs")))
                if self.path == "/v1/lease/heartbeat":
                    if self._fault("HEARTBEAT", pid):
                        return
                    return self._send(200, coord.heartbeat(
                        pid, str(req.get("lease_id", "")),
                        view=req.get("view"),
                        on_epoch=req.get("on_epoch")))
                if self.path == "/v1/barrier/ack":
                    if self._fault("ACK", pid):
                        return
                    return self._send(200, coord.ack(
                        pid, str(req.get("lease_id", "")),
                        str(req.get("phase", "")),
                        int(req.get("transition", -1))))
                if self.path == "/v1/lease/release":
                    if self._fault("RELEASE", pid):
                        return
                    return self._send(200, coord.release(
                        pid, str(req.get("lease_id", ""))))
            except LeaseExpired:
                return self._send(410, {"error": "lease_expired"})
            except ValueError as e:
                return self._send(400, {"error": str(e)})
            self._send(404, {"error": "no such endpoint"})

        def do_DELETE(self) -> None:
            if self.path == "/__faults__" and plan is not None:
                plan.clear()
                return self._send(200, {"ok": True})
            self._send(404, {"error": "no such endpoint"})

    return Handler


def serve_coordinator(
    coord: Coordinator | None = None,
    *,
    host: str = "127.0.0.1",
    port: int = 0,
    fault_plan=None,
    **coord_kw,
) -> tuple[ThreadingHTTPServer, str, Coordinator]:
    """Start a daemon-thread coordinator; returns (server, url, coord).
    Callers own shutdown (``server.shutdown(); server.server_close()``).
    ``fault_plan`` (a dev_object_store.FaultPlan) scripts coordinator
    outages exactly like store outages — also over ``/__faults__``."""
    from ..utils.dev_object_store import FaultPlan

    coord = coord if coord is not None else Coordinator(**coord_kw)
    plan = fault_plan if fault_plan is not None else FaultPlan()
    server = ThreadingHTTPServer((host, port), _make_handler(coord, plan))
    server.daemon_threads = True
    server.fault_plan = plan  # type: ignore[attr-defined]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, f"http://{host}:{server.server_address[1]}", coord


# ---------------------------------------------------------------------------
# client


class CoordUnreachableError(RuntimeError):
    """The coordinator could not be reached (connection/5xx after retries,
    or the circuit breaker is open) — degrade to frozen topology."""


class CoordClient:
    """Thin JSON client for one participant: bounded retries per call
    (``RetryPolicy``), a circuit breaker across calls so a dead
    coordinator costs one probe per cooldown, and the 410 lease-expired
    signal surfaced as :class:`LeaseExpired`."""

    def __init__(
        self,
        url: str,
        pid: str,
        *,
        role: str = "train",
        lease_ttl_secs: float | None = None,
        timeout_secs: float = 5.0,
        retry: RetryPolicy | None = None,
        breaker: CircuitBreaker | None = None,
    ):
        self.url = url.rstrip("/")
        self.pid = pid
        self.role = role
        # requested at acquire; the coordinator grants it clamped to its
        # own --lease-ttl ceiling, and granted_ttl records the answer
        self.lease_ttl_secs = lease_ttl_secs
        self.granted_ttl: float | None = None
        self._timeout = timeout_secs
        self._retry = retry or RetryPolicy(
            max_attempts=2, base_delay_secs=0.05, max_delay_secs=0.5)
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=0.5, window=4, min_calls=2,
            cooldown_secs=2.0, name=f"coord:{pid}")
        self.lease_id: str | None = None
        self.token: int | None = None

    def _post(self, path: str, doc: dict) -> dict:
        def attempt() -> dict:
            req = urllib.request.Request(
                self.url + path, data=json.dumps(doc).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(
                        req, timeout=self._timeout) as r:
                    return json.load(r)
            except urllib.error.HTTPError as e:
                if e.code == 410:
                    raise LeaseExpired(self.pid) from None
                raise CoordUnreachableError(
                    f"{path} -> HTTP {e.code}") from e
            except OSError as e:
                raise CoordUnreachableError(f"{path}: {e}") from e

        if not self.breaker.allow():
            raise CoordUnreachableError(
                f"coordinator breaker open "
                f"({self.breaker.cooldown_remaining():.1f}s cooldown left)")
        try:
            out = self._retry.call(
                attempt,
                classify=lambda e: isinstance(e, CoordUnreachableError))
        except LeaseExpired:
            self.breaker.record_success()  # the SERVICE answered
            raise
        except BaseException:
            self.breaker.record_failure()
            raise
        self.breaker.record_success()
        return out

    def clamp_interval(self, interval: float, *, event: str) -> float:
        """Shrink a heartbeat cadence to fit the GRANTED lease TTL.  The
        config validated the cadence against the *requested* TTL, but the
        coordinator may clamp the grant below it — left alone, every
        lease would expire before its next heartbeat (a silent perpetual
        expire/self-fence/re-acquire livelock).  Flight-records ``event``
        once per shrink."""
        granted = self.granted_ttl
        if granted is None or interval < granted / 2:
            return interval
        clamped = granted / 4
        obs_flight.record(event, subsystem="elastic", pid=self.pid,
                          granted_ttl=granted, interval=interval,
                          clamped_to=clamped)
        return clamped

    def _adopt(self, resp: dict) -> dict:
        lease = resp.get("lease") or {}
        self.lease_id = lease.get("lease_id", self.lease_id)
        if lease.get("token") is not None:
            self.token = int(lease["token"])
        if lease.get("ttl_secs") is not None:
            self.granted_ttl = float(lease["ttl_secs"])
        return resp

    def acquire(self, view: Sequence = ()) -> dict:
        doc = {"pid": self.pid, "role": self.role, "view": list(view)}
        if self.lease_ttl_secs is not None:
            doc["ttl_secs"] = float(self.lease_ttl_secs)
        return self._adopt(self._post("/v1/lease/acquire", doc))

    def heartbeat(self, view: Sequence | None = None,
                  on_epoch: int | None = None) -> dict:
        doc = {"pid": self.pid, "lease_id": self.lease_id}
        if view is not None:
            doc["view"] = list(view)
        if on_epoch is not None:
            doc["on_epoch"] = int(on_epoch)
        return self._adopt(self._post("/v1/lease/heartbeat", doc))

    def ack(self, phase: str, transition: int) -> dict:
        return self._adopt(self._post("/v1/barrier/ack", {
            "pid": self.pid, "lease_id": self.lease_id,
            "phase": phase, "transition": transition}))

    def release(self) -> None:
        if self.lease_id is None:
            return
        try:
            self._post("/v1/lease/release",
                       {"pid": self.pid, "lease_id": self.lease_id})
        # da:allow[swallowed-exception] release is best-effort teardown; the TTL reclaims the lease anyway
        except Exception:
            pass
        self.lease_id = None


class CoordinatedRegistry:
    """The multi-host registry: wraps a LOCAL registry (virtual or live)
    and speaks the controller's epoch/devices protocol from the
    coordinator's CONSENSUS instead of the local view.

    * ``poll()`` — polls the local registry, heartbeats the local view
      (throttled to ``heartbeat_interval_secs``; immediate when the view
      changed or a transition is in flight), and returns the epoch the
      trainer should be on: the settled consensus epoch, or the pending
      epoch while a transition drains (which is what trips the
      controller's detect→drain path).
    * ``snapshot()`` — ``(epoch, devices)``.  During the drain phase the
      device tuple is EMPTY: the controller's capacity wait keeps polling
      and no process can build the new mesh before the barrier opens.
    * ``ack_drain()`` / ``ack_topology(epoch)`` — the controller's
      barrier hooks (absent on plain registries, so the single-process
      path is unchanged).  A barrier restarted while this process was
      already drained re-acks automatically on the next heartbeat.
    * degradation — ``frozen`` (coordinator unreachable: keep the cached
      consensus, train on) and ``fenced`` (lease expired: report a
      sentinel epoch so the controller drains commit-free and waits for
      re-admission).
    """

    def __init__(
        self,
        local,
        client: CoordClient,
        *,
        heartbeat_interval_secs: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._local = local
        self._client = client
        self._interval = float(heartbeat_interval_secs)
        self._clock = clock
        self._lock = threading.Lock()
        base = getattr(local, "_base", None) or local.devices()
        self._by_id = {d.id: d for d in base}
        self._unmappable: tuple = ()  # consensus ids we cannot address
        self._epoch = 0
        self._devices: tuple = ()
        self._phase = "steady"
        self._transition = 0
        self._pending_epoch: int | None = None
        self._last_hb = -float("inf")
        self._last_view: tuple | None = None
        self._drained = False                 # controller has drained
        self._drained_for: int | None = None  # transition the ack LANDED on
        self._on_epoch: int | None = None     # epoch we built a topology on
        self.frozen = False
        self.fenced = False
        self.fence_token: int | None = None
        self.frozen_polls = 0

    # -- wire helpers -------------------------------------------------------
    def _view(self) -> tuple[int, ...]:
        poll = getattr(self._local, "poll", None)
        if poll is not None:
            poll()
        devs = self._local.devices()
        # refresh the id->object map from the LIVE view every poll: a
        # runtime reinit (e.g. on grow) can mint device ids that did not
        # exist at construction, and a died device's stale object must
        # not be handed to a mesh build
        self._by_id = {d.id: d for d in devs}
        return tuple(d.id for d in devs)

    def _to_devices(self, ids: Sequence) -> tuple:
        missing = tuple(i for i in ids if i not in self._by_id)
        if missing:
            # the consensus names a device this process cannot address
            # (it lost one while frozen, or the runtime re-inventoried).
            # Building a SMALLER mesh than the consensus — and than the
            # peers — would silently diverge the pod; report NOTHING so
            # the controller sits in its capacity wait until the view is
            # heard and a new consensus forms.
            if missing != self._unmappable:
                self._unmappable = missing
                obs_flight.record(
                    "elastic_consensus_unmappable", subsystem="elastic",
                    pid=self._client.pid, missing=list(missing),
                    epoch=self._epoch)
            return ()
        self._unmappable = ()
        return tuple(self._by_id[i] for i in ids)

    def _adopt_consensus(self, resp: dict) -> None:
        self._interval = self._client.clamp_interval(
            self._interval, event="elastic_heartbeat_clamped")
        while True:
            c = resp["consensus"]
            self._epoch = int(c["epoch"])
            self._devices = tuple(c["devices"])
            self._phase = c["phase"]
            self._transition = int(c["transition"])
            self._pending_epoch = c.get("pending_epoch")
            self.fence_token = self._client.token
            if self.frozen:
                self.frozen = False
                obs_flight.record("elastic_thawed", subsystem="elastic",
                                  pid=self._client.pid, epoch=self._epoch)
            # we have drained but the coordinator has not recorded it for
            # the CURRENT transition — either the barrier restarted while
            # we sat in the capacity wait, or our ack RPC failed and this
            # is the first call to get through since.  Re-ack; only a
            # SUCCESSFUL ack records _drained_for, so a transient ack
            # failure is retried by every later heartbeat instead of
            # stalling the whole pod's barrier.
            if (self._phase == "drain" and self._drained
                    and self._drained_for != self._transition):
                t = self._transition
                try:
                    resp = self._client.ack("drain", t)
                except (CoordUnreachableError, LeaseExpired):
                    return  # the normal poll paths will retry / self-fence
                self._drained_for = t
                continue
            return

    def _heartbeat(self, *, force: bool = False) -> None:
        now = self._clock()
        view = self._view()
        due = (force
               or view != self._last_view
               or self._phase != "steady"
               or now - self._last_hb >= self._interval)
        if not due:
            return
        try:
            if self.fenced or self._client.lease_id is None:
                # re-admission abandons the old topology: it must NOT
                # re-register as admitted to an epoch it will never drain
                # from (the drain barrier would wait on this process
                # forever) — ack_topology re-registers after the rebuild
                self._on_epoch = None
                resp = self._client.acquire(view)
                if self.fenced:
                    self.fenced = False
                    obs_flight.record(
                        "elastic_readmitted", subsystem="elastic",
                        pid=self._client.pid,
                        token=self._client.token)
                self._drained = False
                self._drained_for = None
            else:
                # on_epoch registers the epoch this process TRAINS ON —
                # without it, a member that joined an already-steady
                # consensus would be invisible to the next drain barrier
                resp = self._client.heartbeat(view,
                                              on_epoch=self._on_epoch)
            self._last_hb = now
            self._last_view = view
            self._adopt_consensus(resp)
        except LeaseExpired:
            self._last_hb = now
            if not self.fenced:
                self.fenced = True
                obs_flight.record("elastic_self_fenced",
                                  subsystem="elastic",
                                  pid=self._client.pid)
        except CoordUnreachableError:
            self._last_hb = now
            self.frozen_polls += 1
            if not self.frozen:
                self.frozen = True
                obs_flight.record(
                    "elastic_frozen", subsystem="elastic",
                    pid=self._client.pid, epoch=self._epoch,
                    breaker=self._client.breaker.state)

    # -- registry protocol --------------------------------------------------
    @property
    def epoch(self) -> int:
        with self._lock:
            return self._effective_epoch()

    def _effective_epoch(self) -> int:
        if self.fenced:
            return -1  # sentinel: never equals a built topology's epoch
        if self._phase == "drain" and self._pending_epoch is not None:
            return int(self._pending_epoch)
        return self._epoch

    def poll(self) -> int:
        with self._lock:
            self._heartbeat()
            return self._effective_epoch()

    def devices(self) -> tuple:
        with self._lock:
            return self._to_devices(self._devices)

    def snapshot(self) -> tuple[int, tuple]:
        with self._lock:
            self._heartbeat()
            if self.fenced or self._phase == "drain":
                return self._effective_epoch(), ()
            return self._epoch, self._to_devices(self._devices)

    # -- controller barrier hooks -------------------------------------------
    def ack_drain(self) -> None:
        with self._lock:
            # _drained marks the LOCAL fact (the controller finished its
            # in-flight step); _drained_for is only set once the ack RPC
            # SUCCEEDS — if it fails here, every later successful
            # heartbeat re-acks (_adopt_consensus), so one transient
            # network failure cannot leave the coordinator waiting on an
            # ack that will never be resent
            self._drained = True
            t = self._transition
            try:
                resp = self._client.ack("drain", t)
            except (CoordUnreachableError, LeaseExpired):
                # frozen/fenced paths pick this up on the next poll; the
                # barrier cannot open without us, so no one reshards early
                self._heartbeat(force=True)
                return
            self._drained_for = t
            self._adopt_consensus(resp)

    def ack_topology(self, epoch: int) -> None:
        """The controller built (or rebuilt) a topology for ``epoch`` —
        complete the reshard barrier if one is pending for it."""
        with self._lock:
            self._drained = False
            self._drained_for = None
            self._on_epoch = int(epoch)
            if self._phase != "reshard" or epoch != self._epoch:
                return
            try:
                self._adopt_consensus(
                    self._client.ack("reshard", self._transition))
            except (CoordUnreachableError, LeaseExpired):
                self._heartbeat(force=True)

    def release(self) -> None:
        self._client.release()


# ---------------------------------------------------------------------------
# standalone entrypoint


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8600)
    ap.add_argument(
        "--lease-ttl", type=float, default=10.0,
        help="default AND ceiling for participant lease TTLs (an acquire "
             "may request a shorter one)")
    ap.add_argument(
        "--barrier-timeout", type=float, default=60.0,
        help="evict a live member that stalls a drain barrier this long "
             "(0 disables; dead members are reclaimed by the TTL)")
    args = ap.parse_args()
    server, url, _coord = serve_coordinator(
        Coordinator(lease_ttl_secs=args.lease_ttl,
                    barrier_timeout_secs=args.barrier_timeout),
        host=args.host, port=args.port,
    )
    print(f"elastic coordinator on {url}")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.shutdown()


if __name__ == "__main__":
    main()
