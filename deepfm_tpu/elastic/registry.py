"""Device availability: the signal that makes mesh shape a runtime variable.

Production TPU pods lose and regain slices mid-run (maintenance events,
spot reclaims); the reference's async-PS answer was workers that merely
tolerate stragglers.  A synchronous SPMD program instead needs an explicit
availability signal it can *act* on: drain, reshard, resume (the elastic
controller, ``elastic/controller.py``).

Two registries behind one tiny protocol — ``devices()`` (the live device
list, stable order) and ``epoch`` (bumped on every membership change, the
cheap "did anything move?" poll the train loop makes once per step):

* :class:`VirtualDeviceRegistry` — a scriptable registry over a fixed
  device list (the 8-device virtual CPU mesh in CI): ``fail(...)`` /
  ``restore(...)`` simulate a slice loss / regain deterministically.  The
  chaos drills kill and revive devices mid-run through exactly this seam.
* :class:`LiveDeviceRegistry` — polls ``jax.devices()`` liveness in
  production.  The JAX runtime surfaces a lost slice as a changed (or
  erroring) device list after the distributed runtime reinitializes; the
  registry reduces that to the same epoch/devices protocol, so the
  controller code is identical under test and on hardware.
"""

from __future__ import annotations

import threading
from typing import Sequence


class VirtualDeviceRegistry:
    """Deterministic, scriptable device availability over a fixed list.

    ``fail``/``restore`` take device *indices into the base list* (stable
    across calls — a restored device returns to its original position, so
    a shrink-then-grow round trip rebuilds the identical mesh layout).
    Thread-safe: chaos drills flip availability from a scripting thread
    while the trainer polls from the step loop.
    """

    def __init__(self, devices: Sequence | None = None):
        if devices is None:
            import jax

            devices = jax.devices()
        self._base = tuple(devices)
        if not self._base:
            raise ValueError("registry needs at least one device")
        self._failed: set[int] = set()
        self._lock = threading.Lock()
        self._epoch = 0

    @property
    def epoch(self) -> int:
        """Monotone membership-change counter — equality with a cached
        value means the device set is unchanged since the cache."""
        with self._lock:
            return self._epoch

    def devices(self) -> tuple:
        """Live devices in base-list order."""
        with self._lock:
            return tuple(
                d for i, d in enumerate(self._base) if i not in self._failed
            )

    def fail(self, *indices: int) -> int:
        """Mark devices (by base-list index) unavailable; returns the new
        epoch.  Failing an already-failed device is a no-op (no epoch
        bump — spurious duplicate events must not trigger a reshard)."""
        with self._lock:
            before = set(self._failed)
            for i in indices:
                if not 0 <= i < len(self._base):
                    raise IndexError(
                        f"device index {i} out of range "
                        f"[0, {len(self._base)})"
                    )
                self._failed.add(i)
            if self._failed != before:
                self._epoch += 1
            return self._epoch

    def restore(self, *indices: int) -> int:
        """Return devices to availability; no-op (no epoch bump) for
        devices that were never failed."""
        with self._lock:
            before = set(self._failed)
            for i in indices:
                self._failed.discard(i)
            if self._failed != before:
                self._epoch += 1
            return self._epoch

    def snapshot(self) -> tuple[int, tuple]:
        """Atomic (epoch, devices) pair: the controller caches the epoch
        of the snapshot it BUILT a mesh from, so a membership flip between
        reading the epoch and reading the device list can never pair a new
        epoch with a stale device set."""
        with self._lock:
            return self._epoch, tuple(
                d for i, d in enumerate(self._base) if i not in self._failed
            )


class LiveDeviceRegistry:
    """Production registry: ``jax.devices()`` liveness, reduced to the
    epoch/devices protocol.

    Each ``poll()`` re-reads the backend device list; a change (different
    ids, or the query itself failing — a collapsed slice can make the
    runtime raise until reinitialized) bumps the epoch.  ``devices()``
    returns the last successful read, so the controller can still drain
    and commit on surviving state while the runtime churns.

    **Debounce**: a single anomalous poll does NOT bump the epoch — the
    same changed reading must repeat ``debounce_polls`` consecutive times
    (default 2, ``elastic.registry_debounce_polls``).  A transient
    device-query hiccup (runtime briefly raising, a one-poll id blip)
    would otherwise cost a full drain/commit/reshard/publish cycle for a
    topology that never actually changed; a real slice loss is still
    detected one poll later, which is noise next to the reshard itself.
    A reading that reverts before confirming resets the count.
    """

    def __init__(self, *, debounce_polls: int = 2):
        import jax

        if debounce_polls < 1:
            raise ValueError(
                f"debounce_polls must be >= 1, got {debounce_polls}")
        self._jax = jax
        self._debounce = int(debounce_polls)
        self._lock = threading.Lock()
        self._epoch = 0
        self._last = tuple(jax.devices())
        self._last_ids = tuple(d.id for d in self._last)
        self._pending_ids: tuple | None = None
        self._pending_count = 0

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def devices(self) -> tuple:
        with self._lock:
            return self._last

    def poll(self) -> int:
        """Re-read backend liveness; bump the epoch once the SAME changed
        reading has held for ``debounce_polls`` consecutive polls."""
        try:
            live = tuple(self._jax.devices())
            ids = tuple(d.id for d in live)
        # da:allow[swallowed-exception] a collapsed slice makes the device query raise; that IS the signal
        except Exception:
            live, ids = (), ()
        with self._lock:
            if ids == self._last_ids:
                # back to the committed reading: the anomaly was transient
                self._pending_ids = None
                self._pending_count = 0
                return self._epoch
            if ids == self._pending_ids:
                self._pending_count += 1
            else:
                self._pending_ids = ids
                self._pending_count = 1
            if self._pending_count >= self._debounce:
                self._epoch += 1
                if live:  # keep the last good list while the runtime churns
                    self._last = live
                self._last_ids = ids
                self._pending_ids = None
                self._pending_count = 0
            return self._epoch

    def snapshot(self) -> tuple[int, tuple]:
        self.poll()
        with self._lock:
            return self._epoch, self._last
