"""Elastic preemption-tolerant training: live N→M mesh resharding with
exactly-once resume and uninterrupted serving.

* ``registry``   — device availability (virtual for tests/chaos drills,
  ``jax.devices()`` liveness in production)
* ``plan``       — mesh choice policy + minimal-traffic redistribution
  planning (no gather-to-host; arxiv 2112.01075's frame)
* ``controller`` — the ElasticTrainer lifecycle: detect → drain →
  commit → replan → reshard → resume → publish
"""

from .controller import ElasticTrainer, run_elastic_train  # noqa: F401
from .plan import (  # noqa: F401
    ReshardPlan,
    choose_mesh,
    plan_reshard,
    reshard_state,
)
from .registry import (  # noqa: F401
    LiveDeviceRegistry,
    VirtualDeviceRegistry,
)
