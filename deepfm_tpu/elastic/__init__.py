"""Elastic preemption-tolerant training: live N→M mesh resharding with
exactly-once resume and uninterrupted serving.

* ``registry``   — device availability (virtual for tests/chaos drills,
  ``jax.devices()`` liveness in production — debounced)
* ``plan``       — mesh choice policy + minimal-traffic redistribution
  planning (no gather-to-host; arxiv 2112.01075's frame)
* ``controller`` — the ElasticTrainer lifecycle: detect → drain →
  commit → replan → reshard → resume → publish
* ``coord``      — multi-host composition: TTL leases, registry-view
  consensus, the two-phase reshard barrier, fencing tokens
* ``mpmd``       — the trainer/publisher MPMD split: the publisher
  program that tails committed payloads (``--task_type publish``)
"""

from .controller import ElasticTrainer, run_elastic_train  # noqa: F401
from .coord import (  # noqa: F401
    CoordClient,
    CoordinatedRegistry,
    Coordinator,
    Fence,
    StaleFencingTokenError,
    merge_views,
    serve_coordinator,
)
from .mpmd import PayloadPublisher, run_publisher  # noqa: F401
from .plan import (  # noqa: F401
    ReshardPlan,
    choose_mesh,
    plan_reshard,
    reshard_state,
)
from .registry import (  # noqa: F401
    LiveDeviceRegistry,
    VirtualDeviceRegistry,
)
