"""Elastic preemption-tolerant training: detect → drain → replan →
reshard → resume → publish, with the mesh shape a runtime variable.

The fixed-mesh stack handles preemption stop-the-world: SIGTERM →
checkpoint → exit → restart on the SAME topology (``launch/preemption.py``),
with ``checkpoint/reshard.py`` adapting only between *runs*.  Production
pods lose and regain slices mid-run; dying with the mesh costs the whole
restart latency and a serving freshness gap.  :class:`ElasticTrainer`
instead keeps ONE process alive across topology changes:

1. **detect** — a device registry (``elastic/registry.py``) reports
   membership epochs; the step loop polls between batches, so detection
   adds zero cost to the step itself.
2. **drain** — the in-flight step completes (synchronous SPMD: reading
   the step's outputs IS the drain barrier).
3. **commit** — {weights, optimizer state, stream cursor} persist as ONE
   Orbax payload (``online/trainer.py`` commit semantics).  If the old
   mesh can no longer execute (devices truly gone), the last periodic
   commit is the resume point instead — the uncommitted tail replays.
4. **replan** — ``elastic/plan.py`` chooses the new mesh (row-shard width
   stable when the device count allows — keeps published artifact shapes
   constant) and draws the minimal-traffic redistribution.
5. **reshard** — ``restore_resharded_payload`` streams the committed
   payload INTO the new mesh's shardings; table rows adapt on-device
   (``jit_row_adapter``), never through the host (``audit_elastic``).
6. **resume** — the stream cursor restored from the SAME atomic payload
   as the weights: every event either is in the committed weights or gets
   replayed onto them — applied exactly once along the surviving lineage,
   by the same argument as the fixed-mesh online trainer's crash-resume.
7. **publish** — a manifest is emitted immediately after the reshard (and
   on the normal cadence throughout).  Artifacts are published at the
   TRUE vocabulary (pad rows sliced off), so every version has identical
   shapes regardless of the training mesh — the serving pool's
   generation-pinned group swap stays a jit cache hit and ingests the
   post-shrink publish without a 409 storm.  Serving never observes the
   topology change.

**Multi-host composition** (``elastic/coord.py``): with
``elastic.coordinator_url`` set, the registry is wrapped in a
:class:`~deepfm_tpu.elastic.coord.CoordinatedRegistry` — epochs and device
sets come from the coordinator's CONSENSUS over every process's local
view, the drain→reshard transition runs as a two-phase barrier (no
process reshards alone), and every commit/publish carries the lease's
monotone fencing token, which the checkpoint and publish roots enforce
(a zombie's stale-token write raises ``StaleFencingTokenError`` instead
of corrupting the lineage).  With ``elastic.publisher_split`` the trainer
only commits; a separate ``--task_type publish`` process (MPMD,
``elastic/mpmd.py``) tails the committed payloads and publishes
asynchronously, so a publish-store outage degrades freshness instead of
stalling the train step.  Degradation is graceful in both directions:
coordinator unreachable → frozen-topology training under a breaker
(flight-recorded); lease expired → commit-free draining until
re-admission.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, NamedTuple

import jax
import numpy as np

from ..checkpoint import make_checkpointer, restore_resharded_payload
from ..core.config import Config, MeshConfig
from ..online.publisher import ModelPublisher
from ..online.stream import EventLogReader, StreamCursor, open_tail
from ..online.trainer import OnlinePayload, commit_payload
from ..parallel import (
    build_mesh,
    create_spmd_state,
    make_context,
    make_spmd_train_step,
    shard_batch,
)
from ..obs import flight as obs_flight
from ..obs.metrics import MetricsRegistry
from ..parallel.spmd import TABLE_KEYS
from ..train.step import TrainState
from ..utils import MetricLogger
from .coord import Fence, StaleFencingTokenError
from .plan import ReshardPlan, choose_mesh, plan_reshard
from .registry import VirtualDeviceRegistry


class Topology(NamedTuple):
    """One compiled generation of the trainer: mesh, context, step."""

    epoch: int
    ctx: object
    step: Callable
    shape: tuple[int, int]


class ElasticTrainer:
    """Continuous SPMD training over an event log with live N→M mesh
    resharding.

    Layout contract mirrors :class:`~deepfm_tpu.online.trainer.
    OnlineTrainer` (event log at ``data.training_data_dir``, checkpoints
    at ``run.model_dir``, versioned publishes at
    ``run.servable_model_dir``); the differences are the mesh (sharded
    step over the registry's live devices instead of the single-device
    jitted step) and the reshard lifecycle above.

    Observability: ``reshards`` records one dict per topology change
    (plan summary + wall time + steps replayed); ``lifecycle`` records
    every detect/drain/commit/reshard/resume/publish transition;
    ``cursor_lineage`` is the batch-end cursor of every event batch
    applied along the SURVIVING lineage — strictly increasing by
    construction, which is the machine-checkable zero-double-apply
    statement the chaos drill audits.
    """

    def __init__(
        self,
        cfg: Config,
        *,
        registry=None,
        stream_root: str | None = None,
        publish_root: str | None = None,
        metrics: MetricsRegistry | None = None,
    ):
        if jax.process_count() > 1 and not cfg.elastic.coordinator_url:
            raise ValueError(
                "multi-process elastic training needs "
                "elastic.coordinator_url: without the coordinator's epoch "
                "consensus + lease fencing there is no single enforced "
                "logical writer over the event log (elastic/coord.py)"
            )
        if cfg.model.model_name == "two_tower":
            raise ValueError(
                "elastic training covers the CTR families (the event-log "
                "schema; online/trainer.py has the same boundary)"
            )
        self.cfg = cfg
        self.registry = registry if registry is not None \
            else VirtualDeviceRegistry()
        if cfg.elastic.coordinator_url \
                and not hasattr(self.registry, "ack_drain"):
            # wrap the local registry in the consensus client: epochs and
            # device sets now come from the coordinator's merged view, and
            # commits/publishes carry the lease's fencing token
            import os as _os

            from .coord import CoordClient, CoordinatedRegistry

            pid = f"p{jax.process_index()}-{_os.getpid()}"
            self.registry = CoordinatedRegistry(
                self.registry,
                CoordClient(cfg.elastic.coordinator_url, pid, role="train",
                            lease_ttl_secs=cfg.elastic.lease_ttl_secs),
                heartbeat_interval_secs=cfg.elastic.heartbeat_interval_secs,
            )
        self._stream_root = stream_root or cfg.data.training_data_dir
        self._publish_root = publish_root or cfg.run.servable_model_dir
        if not self._stream_root:
            raise ValueError("elastic training needs data.training_data_dir "
                             "(the event-log directory or URL)")
        if not self._publish_root:
            raise ValueError("elastic training needs run.servable_model_dir "
                             "(the versioned publish root)")
        self.reader = EventLogReader(
            open_tail(self._stream_root),
            field_size=cfg.model.field_size,
            batch_size=cfg.data.batch_size,
        )
        self.publisher = ModelPublisher(
            self._publish_root, keep=max(2, cfg.run.keep_checkpoints),
            keep_window=cfg.regions.publish_keep_window,
        )
        self._log = MetricLogger(log_steps=cfg.run.log_steps)
        self._cpu_serial = jax.default_backend() == "cpu"
        self.reshards: list[dict] = []
        self.lifecycle: list[dict] = []
        self.cursor_lineage: list[StreamCursor] = []
        # elastic lifecycle on the obs registry (deepfm_elastic_*): the
        # flight recorder gives the incident TIMELINE, these give the
        # alertable AGGREGATES (a drain_commit_failed was previously
        # invisible to Prometheus)
        m = metrics or MetricsRegistry()
        self.metrics = m
        self._m_epoch = m.gauge(
            "deepfm_elastic_epoch", "membership epoch the trainer is on")
        self._m_reshard = m.histogram(
            "deepfm_elastic_reshard_seconds",
            "detect->drain->commit->replan->restore wall time", window=256)
        self._m_drain_failed = m.counter(
            "deepfm_elastic_drain_commit_failed_total",
            "drain commits that failed (resume falls back to the last "
            "periodic commit)")
        self._m_reshards = m.counter(
            "deepfm_elastic_reshards_total", "completed topology changes")
        self._m_replayed = m.counter(
            "deepfm_elastic_steps_replayed_total",
            "optimizer steps replayed from the resume commit")
        self._m_frozen = m.gauge(
            "deepfm_elastic_frozen",
            "1 while training on a frozen topology (coordinator "
            "unreachable)")
        self._m_fence_refused = m.counter(
            "deepfm_elastic_fence_refused_total",
            "writes refused by a stale fencing token")
        self._m_lifecycle = m.counter(
            "deepfm_elastic_lifecycle_total",
            "lifecycle transitions by kind", labels=("kind",))

    # -- lifecycle bookkeeping ----------------------------------------------
    def _event(self, kind: str, **fields) -> None:
        self.lifecycle.append({"kind": kind, **fields})
        self._log.event(f"elastic_{kind}", **fields)
        self._m_lifecycle.labels(kind).inc()
        # the same lifecycle feeds the crash flight recorder (obs/flight):
        # a chaos drill's drain/reshard/resume lands in one correlated
        # timeline with swaps, breaker trips and ejections
        obs_flight.record(f"elastic_{kind}", subsystem="elastic", **fields)

    def metrics_snapshot(self) -> dict:
        """The ``elastic`` metrics section, rendered FROM the registry
        (the ``/v1/metrics`` discipline: JSON sections re-derive from the
        same families Prometheus scrapes, so the two can never drift)."""
        return {
            "epoch": int(self._m_epoch.value),
            "reshards": self._m_reshard.snapshot(include_max=True),
            "reshards_total": int(self._m_reshards.value),
            "drain_commit_failed": int(self._m_drain_failed.value),
            "steps_replayed": int(self._m_replayed.value),
            "frozen": bool(self._m_frozen.value),
            "fence_refused": int(self._m_fence_refused.value),
            "lifecycle": {
                kind: int(child.value)
                for (kind,), child in sorted(
                    self._m_lifecycle.children().items())
            },
        }

    def _fence_for(self, root: str) -> Fence | None:
        """A Fence bound to the registry's CURRENT lease token, or None
        when uncoordinated (single-process: the constructor refusal is the
        writer guarantee, as before)."""
        token = getattr(self.registry, "fence_token", None)
        if not token:
            return None
        return Fence(root, token, holder=getattr(
            getattr(self.registry, "_client", None), "pid", ""))

    def _current_epoch(self) -> int:
        """The registry's live membership epoch.  A polling registry
        (LiveDeviceRegistry) re-reads backend liveness here — this is the
        once-per-batch detection probe; push-style registries (the
        virtual one) just report their counter."""
        poll = getattr(self.registry, "poll", None)
        epoch = poll() if poll is not None else self.registry.epoch
        self._m_frozen.set(
            1.0 if getattr(self.registry, "frozen", False) else 0.0)
        return epoch

    # -- topology -----------------------------------------------------------
    def _topology(self, epoch: int, devices) -> Topology:
        prefer = (self.cfg.elastic.prefer_model_parallel
                  or self.cfg.mesh.model_parallel)
        dp, mp = choose_mesh(len(devices),
                             prefer_model_parallel=prefer)
        mesh = build_mesh(
            MeshConfig(data_parallel=dp, model_parallel=mp),
            devices=list(devices),
        )
        ctx = make_context(self.cfg, mesh)
        step = make_spmd_train_step(ctx)
        return Topology(epoch=epoch, ctx=ctx, step=step, shape=(dp, mp))

    def _admit(self, topo: Topology) -> None:
        """A topology is built and restored: complete the coordinator's
        reshard barrier (absent on plain registries) and take WRITE
        ownership of the roots by advancing their fences to this lease's
        token — from here on, any older token's commit or publish is
        refused at the storage layer."""
        self._m_epoch.set(topo.epoch)
        ack = getattr(self.registry, "ack_topology", None)
        if ack is not None:
            ack(topo.epoch)
        fence = self._fence_for(self.cfg.run.model_dir)
        if fence is not None:
            fence.advance()
        if not self.cfg.elastic.publisher_split:
            pub_fence = self._fence_for(self._publish_root)
            if pub_fence is not None:
                pub_fence.advance()

    def _wait_for_capacity(
        self, stop: threading.Event | None
    ) -> tuple[int, tuple]:
        """Block until the registry offers at least ``min_devices``."""
        el = self.cfg.elastic
        deadline = (time.time() + el.wait_for_capacity_secs
                    if el.wait_for_capacity_secs > 0 else None)
        while True:
            poll = getattr(self.registry, "poll", None)
            if poll is not None:
                poll()
            epoch, devices = self.registry.snapshot()
            if len(devices) >= el.min_devices:
                return epoch, devices
            if stop is not None and stop.is_set():
                raise RuntimeError(
                    f"stopped while waiting for capacity "
                    f"({len(devices)}/{el.min_devices} devices)"
                )
            if deadline is not None and time.time() >= deadline:
                raise RuntimeError(
                    f"no capacity after {el.wait_for_capacity_secs}s: "
                    f"{len(devices)} devices available, "
                    f"elastic.min_devices={el.min_devices}"
                )
            time.sleep(el.poll_interval_secs)

    # -- durability ---------------------------------------------------------
    def _commit(self, ckpt, state: TrainState, cursor: StreamCursor) -> None:
        try:
            commit_payload(ckpt, state, cursor,
                           fence=self._fence_for(self.cfg.run.model_dir))
        except StaleFencingTokenError:
            self._m_fence_refused.inc()
            self._event("fence_refused", root="model_dir",
                        step=int(state.step))
            raise

    def _publish(self, topo: Topology, state: TrainState,
                 cursor: StreamCursor):
        """Publish a topology-INVARIANT artifact: table leaves sliced to
        the true vocabulary (pad rows are zeros by invariant), config at
        the true vocab.  Every version therefore has identical shapes no
        matter which mesh trained it — the serving members' staged
        payloads keep hitting the same compiled executables across a
        shrink/grow, which is what keeps the pool swap 409-free."""
        if self.cfg.elastic.publisher_split:
            # MPMD: the `--task_type publish` process owns the publish
            # root (its own lease + fencing token); the trainer's commits
            # are the hand-off, and the hot loop never touches the
            # publish store
            return None
        true_vocab = topo.ctx.true_feature_size
        params = {}
        for k, v in state.params.items():
            if k in TABLE_KEYS and hasattr(v, "shape") and v.ndim >= 1 \
                    and v.shape[0] != true_vocab:
                params[k] = np.asarray(jax.device_get(v))[:true_vocab]
            else:
                params[k] = v
        pub_state = TrainState(
            step=state.step,
            params=params,
            model_state=state.model_state,
            opt_state=None,
            rng=state.rng,
        )
        try:
            manifest = self.publisher.publish(
                self.cfg, pub_state,
                cursor={"segment": cursor.segment, "record": cursor.record},
                watermark=self.reader.watermark(),
                extra={"elastic": {"mesh": list(topo.shape),
                                   "epoch": topo.epoch}},
                fence=self._fence_for(self._publish_root),
            )
        except StaleFencingTokenError:
            self._m_fence_refused.inc()
            self._event("fence_refused", root="publish",
                        step=int(state.step))
            raise
        self._event("publish", version=manifest.version,
                    step=manifest.step, mesh=list(topo.shape))
        return manifest

    # -- the reshard --------------------------------------------------------
    def _reshard(
        self,
        ckpt,
        topo: Topology,
        state: TrainState,
        cursor: StreamCursor,
        stop: threading.Event | None,
    ) -> tuple[Topology, TrainState, StreamCursor, ReshardPlan]:
        """The detect→drain→commit→replan→reshard→resume sequence.  On
        return, training continues from the restored payload's cursor on
        the new topology."""
        t0 = time.perf_counter()
        step_before = int(state.step)
        self._event("detect", epoch=self.registry.epoch,
                    from_mesh=list(topo.shape))
        # drain: block on the state the last dispatched step produced —
        # synchronous SPMD means no other work can be in flight
        fenced = bool(getattr(self.registry, "fenced", False))
        if self.cfg.elastic.drain_commit and not fenced:
            try:
                jax.block_until_ready(state)
                self._commit(ckpt, state, cursor)
                self._event("drain_commit", step=step_before,
                            segment=cursor.segment, record=cursor.record)
            except Exception as e:
                self._m_drain_failed.inc()
                self._event("drain_commit_failed",
                            error=f"{type(e).__name__}: {e}"[:200])
        elif fenced:
            # lease expired: this process's token is stale by construction,
            # so it drains COMMIT-FREE — the last fenced commit is the
            # resume point and the tail replays after re-admission
            self._event("self_fenced", step=step_before)
        # two-phase barrier (coordinated registries): report "drained" and
        # wait — the consensus device set only becomes visible once every
        # old-epoch process drained, so no process reshards alone
        ack_drain = getattr(self.registry, "ack_drain", None)
        if ack_drain is not None and not fenced:
            ack_drain()
        epoch, devices = self._wait_for_capacity(stop)
        new_topo = self._topology(epoch, devices)
        plan = plan_reshard(topo.ctx, new_topo.ctx)
        self._event("replan", to_mesh=list(new_topo.shape),
                    moved_bytes=plan.moved_bytes,
                    naive_bytes=plan.naive_bytes)
        payload: OnlinePayload = restore_resharded_payload(
            ckpt, new_topo.ctx, plan=plan
        )
        state = payload.train
        cursor = payload.cursor()
        self._admit(new_topo)
        # truncate the lineage to the committed resume point: batches
        # past the cursor were applied only to the DISCARDED state and
        # will replay — along the surviving lineage each event counts once
        while self.cursor_lineage and self.cursor_lineage[-1] > cursor:
            self.cursor_lineage.pop()
        wall = time.perf_counter() - t0
        record = {
            **plan.summary(),
            "wall_secs": round(wall, 4),
            "steps_replayed": step_before - int(state.step),
            "resume_step": int(state.step),
        }
        self.reshards.append(record)
        self._m_reshard.observe(wall)
        self._m_reshards.inc()
        self._m_replayed.inc(max(0, record["steps_replayed"]))
        self._event("reshard", **{k: record[k] for k in
                                  ("from_mesh", "to_mesh", "wall_secs",
                                   "steps_replayed", "moved_bytes")})
        return new_topo, state, cursor, plan

    def _apply_reshard(
        self, ckpt, topo, state, cursor, stop, applied: int
    ):
        """One reshard plus the loop bookkeeping both detection sites
        share: resume step, distinct-event accounting (replayed batches
        must not double-count toward max_batches), and the post-reshard
        publish that keeps serving fresh."""
        topo, state, cursor, _ = self._reshard(
            ckpt, topo, state, cursor, stop
        )
        step = int(state.step)
        applied = max(0, applied - self.reshards[-1]["steps_replayed"])
        self._publish(topo, state, cursor)
        return topo, state, cursor, step, applied

    # -- main loop ----------------------------------------------------------
    def run(
        self,
        *,
        follow: bool = True,
        max_batches: int = 0,
        stop: threading.Event | None = None,
        idle_timeout_secs: float = 0.0,
        publish_every_steps: int | None = None,
        on_commit: Callable[[TrainState, StreamCursor], None] | None = None,
    ) -> TrainState:
        """Consume the stream with the same termination contract as
        ``OnlineTrainer.run``, resharding live whenever the registry's
        membership epoch moves.  Returns the final TrainState (committed
        and published)."""
        cfg = self.cfg
        publish_every = (
            cfg.run.online_publish_every_steps
            if publish_every_steps is None else publish_every_steps
        )
        ckpt_every = max(1, cfg.run.checkpoint_every_steps)
        ckpt = make_checkpointer(
            cfg.run.model_dir, max_to_keep=cfg.run.keep_checkpoints
        )
        epoch, devices = self._wait_for_capacity(stop)
        topo = self._topology(epoch, devices)
        self._admit(topo)
        cursor = StreamCursor()
        if ckpt.latest_step() is None:
            state = create_spmd_state(topo.ctx)
            # a durable step-0 payload BEFORE the first event applies: a
            # shrink during the very first batches then has a resume
            # point, with the whole prefix replayed (exactly-once holds
            # vacuously — nothing was committed beyond the init)
            self._commit(ckpt, state, cursor)
        else:
            payload = restore_resharded_payload(ckpt, topo.ctx)
            state = payload.train
            cursor = payload.cursor()
            self._event("resume", step=int(state.step),
                        segment=cursor.segment, record=cursor.record,
                        mesh=list(topo.shape))
        step = int(state.step)
        self._log.seed_step(step)
        applied = 0
        last_committed = step
        last_published = -1
        try:
            while True:
                resharded = False
                remaining = (max_batches - applied) if max_batches else 0
                if max_batches and remaining <= 0:
                    break
                for batch, batch_cursor in self.reader.batches(
                    cursor,
                    follow=follow,
                    stop=stop,
                    idle_timeout_secs=idle_timeout_secs,
                    max_batches=remaining,
                ):
                    if self._current_epoch() != topo.epoch:
                        # the drain point: the previous step's state is
                        # final and THIS batch has not been applied — it
                        # replays from the committed cursor after the
                        # reshard, on whichever lineage survives
                        topo, state, cursor, step, applied = (
                            self._apply_reshard(
                                ckpt, topo, state, cursor, stop, applied
                            )
                        )
                        last_committed = step
                        last_published = step
                        resharded = True
                        break
                    state, metrics = topo.step(
                        state, shard_batch(topo.ctx, batch)
                    )
                    if self._cpu_serial:
                        # XLA:CPU virtual meshes deadlock with >1 sharded
                        # program in flight (train/loop.py rationale)
                        jax.block_until_ready(metrics)
                    cursor = batch_cursor
                    self.cursor_lineage.append(cursor)
                    step += 1
                    applied += 1
                    self._log.step(
                        step, int(batch["label"].shape[0]),
                        {k: v for k, v in metrics.items()
                         if k != "loss_per_shard"},
                    )
                    if step % ckpt_every == 0 or (
                        publish_every and step % publish_every == 0
                    ):
                        self._commit(ckpt, state, cursor)
                        last_committed = step
                        if on_commit is not None:
                            on_commit(state, cursor)
                    if publish_every and step % publish_every == 0:
                        self._publish(topo, state, cursor)
                        last_published = step
                if resharded:
                    continue
                if (stop is None or not stop.is_set()) \
                        and self._current_epoch() != topo.epoch:
                    # membership moved while the tail drained (idle/EOS):
                    # reshard so the final commit/publish land on a mesh
                    # that matches live capacity, then UNCONDITIONALLY
                    # re-enter the stream — a failed drain commit rolls
                    # the cursor back past events the generator already
                    # delivered, and ending here would drop that tail
                    # forever (the exactly-once violation), in follow
                    # mode just as in one-shot mode
                    topo, state, cursor, step, applied = (
                        self._apply_reshard(
                            ckpt, topo, state, cursor, stop, applied
                        )
                    )
                    last_committed = step
                    last_published = step
                    continue  # re-read the tail the rollback re-exposed
                break
            if step != last_committed:
                self._commit(ckpt, state, cursor)
                if on_commit is not None:
                    on_commit(state, cursor)
            if applied and step != last_published:
                self._publish(topo, state, cursor)
            self._event("done", step=step, applied=applied,
                        reshards=len(self.reshards),
                        mesh=list(topo.shape))
        finally:
            ckpt.close()
        return state


def run_elastic_train(cfg: Config) -> TrainState:
    """CLI entry: ``--task_type online-train`` with ``elastic.enabled``
    (launch/cli.py dispatch) — tail the event log under the live device
    registry until SIGTERM/SIGINT, ``online_max_batches``, or
    ``online_idle_timeout_secs``."""
    from .registry import LiveDeviceRegistry

    trainer = ElasticTrainer(cfg, registry=LiveDeviceRegistry(
        debounce_polls=cfg.elastic.registry_debounce_polls))
    stop = threading.Event()
    restore: list[tuple] = []
    if threading.current_thread() is threading.main_thread():
        import signal

        def _stop(*_):
            stop.set()

        for sig in (signal.SIGTERM, signal.SIGINT):
            restore.append((sig, signal.signal(sig, _stop)))
    try:
        return trainer.run(
            follow=True,
            stop=stop,
            max_batches=cfg.run.online_max_batches,
            idle_timeout_secs=cfg.run.online_idle_timeout_secs,
        )
    finally:
        release = getattr(trainer.registry, "release", None)
        if release is not None:
            release()  # clean lease hand-back; the TTL covers crashes
        if restore:
            import signal

            for sig, prev in restore:
                signal.signal(sig, prev)
