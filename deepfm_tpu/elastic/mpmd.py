"""MPMD trainer/publisher split: the publisher half (``--task_type
publish``).

"Scaling Deep Learning Training with MPMD Pipeline Parallelism"
(PAPERS.md, arxiv 2412.14374) runs *different programs* on different
process groups; applied to the online loop, the insight is that publishing
is not part of the training program at all — it only consumes COMMITTED
payloads.  :class:`PayloadPublisher` is that second program: a process
that tails the checkpoint root the (elastic) trainer commits to, restores
each newly committed payload host-side, and publishes the versioned
servable asynchronously.  Consequences:

* a publish-store outage degrades **freshness**, never the train step —
  the trainer's hot loop has no publish I/O left in it
  (``ElasticTrainer._publish`` short-circuits under
  ``elastic.publisher_split``);
* the publisher carries its own lease + fencing token
  (``elastic/coord.py``, role ``publish``), so a zombie publisher from a
  previous incarnation cannot clobber the root: its stale token is
  refused by the root's fence;
* a publisher killed between artifact write and manifest write leaves an
  orphaned ``versions/<v>/`` prefix that is *invisible* to readers
  (manifest-first resolution) — the next incarnation deletes it at
  startup (``ModelPublisher.clean_orphans``), extending the PR 3 orphan
  guarantees across the process boundary.

The payload restore is host-side and topology-free: leaf shapes come from
the checkpoint's own metadata, so the publisher needs NO mesh and no
agreement with the trainer about padding — it slices table rows to the
true vocabulary exactly like the trainer's inline publish did, producing
bit-identical artifacts (same ``param_hash``) for the same committed step.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from ..core.config import Config
from ..obs import flight as obs_flight
from ..obs.metrics import MetricsRegistry
from ..online.publisher import ModelPublisher, latest_manifest
from ..online.trainer import cursor_from_arrays
from ..parallel.spmd import TABLE_KEYS
from ..utils import MetricLogger
from .coord import (
    CoordClient,
    CoordUnreachableError,
    Fence,
    LeaseExpired,
    StaleFencingTokenError,
)


def read_payload_tree(model_dir: str, step: int | None = None):
    """Host-side restore of one committed :class:`OnlinePayload` in dict
    form — ``(step, tree)`` — with no mesh, no template, no transfer: the
    abstract target is built from the checkpoint's OWN metadata, so the
    publisher works against any topology's commit.  ``step=None`` takes
    the newest step, falling back across torn ones (the
    ``restore_latest_payload`` discipline)."""
    import jax
    import orbax.checkpoint as ocp

    # every leaf restores onto THIS process's local device, whatever the
    # saving mesh was: without an explicit sharding, Orbax falls back to
    # the sharding file persisted by the trainer and refuses on any other
    # device inventory — the publisher must not care what it was
    local = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    with ocp.CheckpointManager(
        os.path.abspath(model_dir),
        item_handlers=ocp.StandardCheckpointHandler(),
    ) as mngr:
        steps = ([step] if step is not None
                 else sorted(mngr.all_steps(), reverse=True))
        if not steps:
            raise FileNotFoundError(f"no committed payload in {model_dir}")
        last_err: Exception | None = None
        for s in steps:
            try:
                meta = mngr.item_metadata(s)
                leaves, treedef = jax.tree_util.tree_flatten(meta)
                abstract = treedef.unflatten(
                    jax.ShapeDtypeStruct(m.shape, m.dtype, sharding=local)
                    if hasattr(m, "shape") else m
                    for m in leaves
                )
                return s, mngr.restore(
                    s, args=ocp.args.StandardRestore(abstract))
            except Exception as e:
                last_err = e
        raise RuntimeError(
            f"no readable payload among steps {steps}; last error: "
            f"{type(last_err).__name__}: {last_err}"
        ) from last_err


def servable_from_payload(cfg: Config, tree: dict):
    """``(TrainState, cursor_dict)`` for publishing: table leaves sliced
    to the TRUE vocabulary (identical to the trainer's inline publish —
    same leaves, same ``param_hash``), optimizer state dropped."""
    from ..train.step import TrainState

    train = tree["train"]
    params = dict(train["params"])
    true_vocab = cfg.model.feature_size
    for k in TABLE_KEYS:
        v = params.get(k)
        if v is not None and hasattr(v, "shape") and v.ndim >= 1 \
                and v.shape[0] != true_vocab:
            params[k] = np.asarray(v)[:true_vocab]
    state = TrainState(
        step=train["step"],
        params=params,
        model_state=train["model_state"],
        opt_state=None,
        rng=train["rng"],
    )
    cursor = cursor_from_arrays(
        tree["cursor_segment"], tree["cursor_len"], tree["cursor_record"])
    return state, {"segment": cursor.segment, "record": cursor.record}


class PayloadPublisher:
    """The publisher program: tail ``run.model_dir`` for newly committed
    payloads, publish each newest one to ``run.servable_model_dir``.

    Degradation table:

    * publish store down       → bounded retries inside
      ``ModelPublisher.publish``; a failed round is counted, the payload
      is retried next poll — freshness lags, nothing stalls or crashes.
    * coordinator unreachable  → keep publishing under the LAST issued
      token (breaker-guarded probes; the fence still protects the root if
      a successor was admitted meanwhile).
    * lease expired            → re-acquire; until re-admitted the stale
      token means publishes are refused, which is self-fencing.
    * stale fencing token      → the root belongs to a newer incarnation:
      record, STOP (a fenced-out publisher must not spin against the
      refusal forever).
    """

    def __init__(self, cfg: Config, *,
                 metrics: MetricsRegistry | None = None):
        from ..data.object_store import is_url

        if not cfg.run.model_dir:
            raise ValueError("publisher needs run.model_dir "
                             "(the checkpoint root it tails)")
        if is_url(cfg.run.model_dir):
            # os.listdir/CheckpointManager cannot tail a URL — silently
            # publishing nothing forever would be the failure mode.  The
            # remote mirror (checkpoint/remote.py) is an upload target,
            # not a restore source; run the publisher next to the
            # trainer's LOCAL model_dir.
            raise ValueError(
                f"publisher cannot tail a remote model_dir "
                f"({cfg.run.model_dir!r}): run the `--task_type publish` "
                f"process on the trainer's host against the local "
                f"checkpoint root (the publish root may be remote)"
            )
        if not cfg.run.servable_model_dir:
            raise ValueError("publisher needs run.servable_model_dir "
                             "(the versioned publish root)")
        self.cfg = cfg
        self.publisher = ModelPublisher(
            cfg.run.servable_model_dir,
            keep=max(2, cfg.run.keep_checkpoints),
            keep_window=cfg.regions.publish_keep_window,
        )
        self._log = MetricLogger(log_steps=cfg.run.log_steps)
        self._client: CoordClient | None = None
        if cfg.elastic.coordinator_url:
            self._client = CoordClient(
                cfg.elastic.coordinator_url,
                f"pub-{os.getpid()}", role="publish",
                lease_ttl_secs=cfg.elastic.lease_ttl_secs)
        m = metrics or MetricsRegistry()
        self.metrics = m
        self._m_published = m.counter(
            "deepfm_publisher_published_total", "versions published")
        self._m_failures = m.counter(
            "deepfm_publisher_failures_total",
            "publish rounds that failed after retries")
        self._m_fence_refused = m.counter(
            "deepfm_publisher_fence_refused_total",
            "publishes refused by a stale fencing token")
        self._m_orphans = m.counter(
            "deepfm_publisher_orphans_cleaned_total",
            "orphaned version prefixes removed at startup")
        self._m_lag = m.gauge(
            "deepfm_publisher_lag_steps",
            "newest committed step minus newest published step")
        self._hb_interval = cfg.elastic.heartbeat_interval_secs
        self._last_hb = -float("inf")

    def metrics_snapshot(self) -> dict:
        """The ``publisher`` metrics section, rendered from the registry."""
        return {
            "published": int(self._m_published.value),
            "failures": int(self._m_failures.value),
            "fence_refused": int(self._m_fence_refused.value),
            "orphans_cleaned": int(self._m_orphans.value),
            "lag_steps": int(self._m_lag.value),
        }

    # -- lease --------------------------------------------------------------
    def _fence(self) -> Fence | None:
        if self._client is None or not self._client.token:
            return None
        return Fence(self.cfg.run.servable_model_dir, self._client.token,
                     holder=self._client.pid)

    def _lease_tick(self) -> None:
        """Acquire/refresh the publish lease; adopt re-issued tokens and
        take ownership of the root's fence.  Unreachable coordinator →
        keep the last token (breaker-paced probes)."""
        if self._client is None:
            return
        now = time.monotonic()
        if now - self._last_hb < self._hb_interval:
            return
        self._last_hb = now
        prev = self._client.token
        try:
            if self._client.lease_id is None:
                self._client.acquire()
            else:
                self._client.heartbeat()
            self._hb_interval = self._client.clamp_interval(
                self._hb_interval, event="publisher_heartbeat_clamped")
        except LeaseExpired:
            self._client.lease_id = None
            obs_flight.record("publisher_self_fenced",
                              subsystem="elastic", pid=self._client.pid)
            return
        except CoordUnreachableError:
            return
        if self._client.token != prev:
            fence = self._fence()
            if fence is not None:
                try:
                    fence.advance()
                except StaleFencingTokenError:
                    # a NEWER publisher owns the root; publish_once will
                    # hit the same refusal and exit the loop loudly
                    self._m_fence_refused.inc()

    # -- the loop -----------------------------------------------------------
    @staticmethod
    def committed_steps(model_dir: str) -> list[int]:
        """Committed payload steps by directory listing — Orbax renames a
        step directory into its bare numeric name only on completion, so
        an int-parseable entry IS a committed step (tmp-suffixed torn
        writes never parse).  Cheap enough to poll every round without
        spinning up a CheckpointManager."""
        try:
            names = os.listdir(model_dir)
        except FileNotFoundError:
            return []
        steps = []
        for n in names:
            try:
                steps.append(int(n))
            except ValueError:
                continue
        return sorted(steps)

    def publish_once(self) -> int | None:
        """Publish the newest committed payload if it is newer than the
        newest published version; returns the published step or None."""
        steps = self.committed_steps(self.cfg.run.model_dir)
        if not steps:
            return None
        newest = max(steps)
        manifest = latest_manifest(self.cfg.run.servable_model_dir)
        published = manifest.step if manifest is not None else -1
        self._m_lag.set(max(0, newest - published))
        if newest <= published:
            return None
        step, tree = read_payload_tree(self.cfg.run.model_dir)
        if step <= published:
            return None
        state, cursor = servable_from_payload(self.cfg, tree)
        manifest = self.publisher.publish(
            self.cfg, state, cursor=cursor,
            extra={"mpmd": {"publisher_pid": os.getpid(),
                            "payload_fence_token":
                                int(np.asarray(
                                    tree.get("fence_token", 0)))}},
            fence=self._fence(),
        )
        self._m_published.inc()
        self._m_lag.set(0)
        self._log.event("publish", version=manifest.version,
                        step=manifest.step,
                        param_hash=manifest.param_hash[:12])
        return step

    def run(
        self,
        *,
        stop: threading.Event | None = None,
        idle_timeout_secs: float = 0.0,
        max_publishes: int = 0,
    ) -> int:
        """Tail-and-publish until ``stop``, ``idle_timeout_secs`` without
        a new commit, or ``max_publishes``.  Returns versions published."""
        removed = self.publisher.clean_orphans()
        if removed:
            self._m_orphans.inc(len(removed))
            self._log.event("orphans_cleaned", versions=removed)
            obs_flight.record("publisher_orphans_cleaned",
                              subsystem="elastic", versions=removed)
        published = 0
        # the idle clock only engages once the FIRST commit exists: the
        # trainer's initial compile can take arbitrarily long, and an
        # idle-exit before it ever committed would be a publisher that
        # never publishes
        last_progress: float | None = None
        poll = self.cfg.elastic.publish_poll_secs
        while stop is None or not stop.is_set():
            self._lease_tick()
            if last_progress is None and self.committed_steps(
                    self.cfg.run.model_dir):
                last_progress = time.monotonic()
            try:
                step = self.publish_once()
            except StaleFencingTokenError:
                self._m_fence_refused.inc()
                self._log.event("fenced_out")
                obs_flight.record("publisher_fenced_out",
                                  subsystem="elastic")
                break
            except Exception as e:
                self._m_failures.inc()
                obs_flight.record(
                    "publisher_round_failed", subsystem="elastic",
                    error=f"{type(e).__name__}: {e}"[:200])
                step = None
            if step is not None:
                published += 1
                last_progress = time.monotonic()
                if max_publishes and published >= max_publishes:
                    break
            elif idle_timeout_secs > 0 and last_progress is not None and (
                    time.monotonic() - last_progress >= idle_timeout_secs):
                break
            # the wait must honor the (possibly clamped) heartbeat
            # cadence, not just the publish poll: a slow tailing cadence
            # would otherwise space heartbeats past the granted TTL and
            # re-create the expire/re-acquire livelock the clamp prevents
            wait = poll if self._client is None \
                else min(poll, self._hb_interval)
            if stop is not None:
                stop.wait(wait)
            else:
                time.sleep(wait)
        if self._client is not None:
            self._client.release()
        self._log.event("publisher_done", published=published)
        return published


def run_publisher(cfg: Config) -> int:
    """CLI entry (``--task_type publish``, launch/cli.py): the MPMD
    publisher process.  Stops on SIGTERM/SIGINT or after
    ``run.online_idle_timeout_secs`` without a new commit (0 = tail
    forever)."""
    pub = PayloadPublisher(cfg)
    stop = threading.Event()
    restore: list[tuple] = []
    if threading.current_thread() is threading.main_thread():
        import signal

        def _stop(*_):
            stop.set()

        for sig in (signal.SIGTERM, signal.SIGINT):
            restore.append((sig, signal.signal(sig, _stop)))
    try:
        return pub.run(
            stop=stop,
            idle_timeout_secs=cfg.run.online_idle_timeout_secs,
        )
    finally:
        if restore:
            import signal

            for sig, prev in restore:
                signal.signal(sig, prev)


__all__ = [
    "PayloadPublisher",
    "read_payload_tree",
    "run_publisher",
    "servable_from_payload",
]
