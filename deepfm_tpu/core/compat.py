"""jax version compatibility shims.

The framework targets current jax, where ``shard_map`` is a top-level
export and its replication-checking knob is ``check_vma``.  Older jaxlibs
(0.4.x) keep ``shard_map`` under ``jax.experimental.shard_map`` and call
the same knob ``check_rep``.  Importing from here gives every caller one
spelling that works on both:

    from deepfm_tpu.core.compat import shard_map
"""

from __future__ import annotations

import functools
import inspect

try:
    from jax import shard_map as _shard_map
except ImportError:  # jax < 0.6 keeps it in the experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map

if "check_vma" in inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:

    @functools.wraps(_shard_map)
    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)
