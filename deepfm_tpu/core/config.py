"""Typed configuration for the TPU-native DeepFM framework.

Capability parity with the reference's three-layer flag system
(reference: 1-ps-cpu/DeepFM-dist-ps-for-multipleCPU-multiInstance.py:37-107 and
2-hvd-gpu/DeepFM-hvd-tfrecord-vectorized-map.py:36-98) collapsed into one typed
dataclass hierarchy with explicit CLI/env/dict override hooks — no import-time
environment coupling, no string-encoded topology except at the parse boundary.

Dead reference flags intentionally not replicated: ``num_threads`` / ``log_steps``
were never read (ps:49, ps:55), ``loss_type`` never branched (ps:58, ps:275),
``perform_shuffle`` had no flag definition.  ``log_steps`` IS honored here
(the reference defined-but-ignored it; we wire it to the metrics logger).
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Sequence


def _strip_list_wrappers(s: str) -> str:
    # accept "(8,4)" / "[8,4]" alongside the canonical "8,4" — users paste
    # python tuples into --set and the bare int() error was baffling
    return s.strip().removeprefix("(").removeprefix("[") \
            .removesuffix(")").removesuffix("]")


def _parse_int_list(s: str | Sequence[int]) -> tuple[int, ...]:
    if isinstance(s, str):
        return tuple(
            int(x) for x in _strip_list_wrappers(s).split(",") if x.strip()
        )
    return tuple(int(x) for x in s)


def _parse_float_list(s: str | Sequence[float]) -> tuple[float, ...]:
    if isinstance(s, str):
        return tuple(
            float(x) for x in _strip_list_wrappers(s).split(",") if x.strip()
        )
    return tuple(float(x) for x in s)


# ---- multi-tenant fleet (deepfm_tpu/fleet) --------------------------------

# ModelConfig fields that determine the serving EXECUTABLES — the payload
# avals and the lowered bucket modules.  Two tenants may share one
# precompiled executable set iff they agree on ALL of these (the
# audit_multitenant trace contract proves the sharing at lowering level);
# everything else (learning rate, l2, dropout — training-time knobs) is
# tenant-local and free to differ.
EXECUTABLE_SPEC_FIELDS = (
    "model_name", "feature_size", "field_size", "embedding_size",
    "deep_layers", "cin_layers", "cross_layers", "batch_norm",
    "tower_layers", "tower_dim", "user_vocab_size", "item_vocab_size",
    "user_field_size", "item_field_size", "compute_dtype", "narrow_ids",
    "table_grad", "fused_kernel", "shard_exchange",
    "shard_exchange_capacity", "tiered_embeddings",
)

# keys a fleet tenant entry may carry (core/config.py and fleet/registry.py
# share ONE schema; a typo'd key raises instead of silently doing nothing)
TENANT_ENTRY_KEYS = ("name", "source", "split_percent", "shadow_of",
                     "model")


def _spec_norm(v: Any) -> Any:
    return tuple(v) if isinstance(v, list) else v


def tenant_spec_divergence(base_model: dict, overrides: dict) -> list[str]:
    """Executable-spec fields where a tenant's ``model`` overrides diverge
    from the pool's base model section.  Non-empty means the tenant CANNOT
    share the pool's precompiled executables (its payload would lower to a
    different module) — the fleet refuses it at config load instead of
    recompiling mid-traffic."""
    return sorted(
        k for k in overrides
        if k in EXECUTABLE_SPEC_FIELDS
        and _spec_norm(overrides[k]) != _spec_norm(base_model.get(k))
    )


def validate_tenant_entries(entries) -> tuple:
    """Normalize + validate a fleet tenant list (dicts or JSON text):
    duplicate names raise, split percentages of the serving (non-shadow)
    arms must sum to 100 when any is set, shadow entries must reference an
    existing non-shadow incumbent and take no split.  Returns the
    normalized tuple of entry dicts.  Spec-compatibility against the base
    model section is the cross-section half, checked in
    ``Config.__post_init__`` (and re-checked with manifests by
    ``fleet/registry.py``)."""
    if isinstance(entries, str):
        entries = json.loads(entries) if entries.strip() else []
    norm = []
    for i, e in enumerate(entries):
        if not isinstance(e, dict):
            raise ValueError(
                f"fleet.tenants[{i}] must be an object, got {type(e).__name__}"
            )
        unknown = sorted(set(e) - set(TENANT_ENTRY_KEYS))
        if unknown:
            raise ValueError(
                f"fleet.tenants[{i}] has unknown key(s) {unknown} "
                f"(known: {list(TENANT_ENTRY_KEYS)})"
            )
        name = str(e.get("name", "")).strip()
        if not name:
            raise ValueError(f"fleet.tenants[{i}] is missing a name")
        norm.append({
            "name": name,
            "source": str(e.get("source", "")),
            "split_percent": float(e.get("split_percent", 0.0)),
            "shadow_of": str(e.get("shadow_of", "")),
            "model": dict(e.get("model") or {}),
        })
    names = [e["name"] for e in norm]
    dups = sorted({n for n in names if names.count(n) > 1})
    if dups:
        raise ValueError(f"duplicate fleet tenant name(s): {dups}")
    by_name = {e["name"]: e for e in norm}
    serving = [e for e in norm if not e["shadow_of"]]
    for e in norm:
        if e["split_percent"] < 0:
            raise ValueError(
                f"tenant {e['name']!r}: split_percent must be >= 0, got "
                f"{e['split_percent']}"
            )
        if e["shadow_of"]:
            ref = by_name.get(e["shadow_of"])
            if ref is None or ref["shadow_of"]:
                raise ValueError(
                    f"shadow tenant {e['name']!r} references "
                    f"{e['shadow_of']!r}, which is not a serving (non-"
                    f"shadow) tenant"
                )
            if e["split_percent"]:
                raise ValueError(
                    f"shadow tenant {e['name']!r} cannot take live split "
                    f"traffic (split_percent="
                    f"{e['split_percent']}); it scores the sampled stream "
                    f"off the response path"
                )
    total = sum(e["split_percent"] for e in serving)
    if any(e["split_percent"] for e in serving) and abs(total - 100.0) > 1e-6:
        raise ValueError(
            f"fleet split percentages must sum to 100, got {total:g} over "
            f"{[e['name'] for e in serving]} — every key must land on "
            f"exactly one arm"
        )
    return tuple(norm)


def packed_sort_id_bound(n: int) -> int:
    """Largest EXCLUSIVE id bound the packed single-key sort accepts for an
    ``n``-id stream (``ops/embedding.py sort_segments``): the (id,
    position) pair must fit one uint32 key, so ``bits(bound) +
    ceil(log2 n) <= 32``.  Lives here (pure int math, no jax import) so
    config-time validation and the sort share ONE definition."""
    shift = max(1, int(n - 1).bit_length()) if n > 1 else 1
    return 1 << (32 - shift)


@dataclass(frozen=True)
class ModelConfig:
    """DeepFM model hyperparameters (reference ps:50-69, notebook overrides cell 4)."""

    feature_size: int = 117_581       # vocabulary size (ps notebook cell 4)
    field_size: int = 39              # 13 numeric + 26 categorical fields
    embedding_size: int = 32          # K (ps:52)
    deep_layers: tuple[int, ...] = (256, 128, 64)   # ps:62 default; notebooks use 128,64,32
    # NOTE: the reference passes these to tf.nn.dropout as *keep_prob* (ps:245),
    # so 0.5 means "keep 50%".  We store keep probabilities to match.
    dropout_keep: tuple[float, ...] = (0.5, 0.5, 0.5)
    batch_norm: bool = False          # ps:64-66
    batch_norm_decay: float = 0.9     # ps:67-69
    l2_reg: float = 0.0001            # ps:57; applied to FM_W/FM_V only (ps:275-279)
    model_name: str = "deepfm"        # deepfm | xdeepfm | dcnv2 | two_tower
    # xDeepFM CIN layer sizes / DCN-v2 cross depth (ignored by plain deepfm)
    cin_layers: tuple[int, ...] = (128, 128)
    cross_layers: int = 3
    # two-tower retrieval (model_name="two_tower"; ignored by CTR families):
    # separate user/item vocabularies and field counts, tower MLP widths,
    # output dim, and softmax temperature for in-batch negatives
    user_vocab_size: int = 0          # 0 -> feature_size
    item_vocab_size: int = 0          # 0 -> feature_size
    user_field_size: int = 1
    item_field_size: int = 1
    tower_layers: tuple[int, ...] = (64, 32)
    tower_dim: int = 16
    temperature: float = 0.05
    # compute dtype for the MLP/FM math (params stay f32; bf16 feeds the MXU)
    compute_dtype: str = "bfloat16"
    # int64->int32 id narrowing when the vocab is int32-addressable (TPU has
    # no native 64-bit integer datapath).  On by default; the switch exists
    # for the id-dtype cost ablation (benchmarks/attribution.py)
    narrow_ids: bool = True
    # embedding-table gradient strategy: "scatter" = the gather's default
    # VJP (one scatter-add update per lookup; XLA:TPU serializes colliding
    # rows) | "segsum" = sort + segment-sum + one sorted-unique write per
    # distinct row (ops/embedding.py segsum_lookup).  Default stays
    # "scatter" until the TPU attribution bench decides
    # (benchmarks/attribution.py; round-5 finding in docs/TPU_REPORT.md)
    table_grad: str = "scatter"
    # Pallas fused gather+FM kernel (ops/pallas_ctr.py): "off" | "auto" | "on".
    # "auto" uses it on TPU backends; "on" forces it (interpret mode on CPU).
    fused_kernel: str = "off"
    # row-sharded lookup collective strategy (parallel/embedding.py):
    # "psum" = every shard contributes a mostly-zeros [B, F, K] dense tensor,
    # assembled by lax.psum over the model axis (the original path) |
    # "alltoall" = dedup the batch ids on-device, route only UNIQUE owner-rows
    # requests/responses through lax.all_to_all (owned-rows-only traffic;
    # capacity-bounded with a jit-stable psum fallback on overflow) |
    # "auto" = alltoall where a real interconnect exists AND the mesh
    # actually exchanges rows (model_parallel > 1, or lazy updates with
    # data_parallel > 1); psum on the CPU backend, whose shared-memory
    # virtual mesh makes the dense assembly a memcpy that the exchange's
    # sort work cannot beat (measured; parallel/embedding.py
    # resolve_shard_exchange).
    shard_exchange: str = "auto"
    # per-destination-shard request capacity for the alltoall exchange, as a
    # fraction of the flattened local id stream (B_local*F).  0 = auto:
    # ceil(N/M) per model shard for the forward exchange, 0.5*N for the lazy
    # path's per-data-shard unique pack.  Overflow falls back to the dense
    # path inside the same executable (lax.cond), so any value is safe —
    # smaller capacity = less ICI traffic but more frequent fallback.
    shard_exchange_capacity: float = 0.0
    # tiered giant-vocab embedding store (deepfm_tpu/tiered): page rows +
    # lazy-Adam moments through HBM hot cache <- pinned-host backing <-
    # object-store cold tier instead of holding the table resident.
    tiered_embeddings: bool = False
    # device-resident hot-cache slots (0 = auto: next pow2 >= 2*B*F); must
    # hold at least one batch's flattened id stream
    tiered_hot_slots: int = 0
    # staged rows per step, the miss pack's fixed shape (0 = auto: B*F)
    tiered_stage_rows: int = 0
    # pinned host-memory backing rows (0 = auto: 8*hot slots)
    tiered_host_rows: int = 0
    # rows per cold-tier page (one ranged read / one overlay write)
    tiered_page_rows: int = 1024
    # cold-tier root: object-store prefix URL or local directory
    tiered_cold_url: str = ""

    def __post_init__(self):
        object.__setattr__(self, "deep_layers", _parse_int_list(self.deep_layers))
        object.__setattr__(self, "dropout_keep", _parse_float_list(self.dropout_keep))
        object.__setattr__(self, "cin_layers", _parse_int_list(self.cin_layers))
        object.__setattr__(self, "tower_layers", _parse_int_list(self.tower_layers))
        if len(self.dropout_keep) < len(self.deep_layers):
            raise ValueError(
                f"dropout_keep has {len(self.dropout_keep)} entries for "
                f"{len(self.deep_layers)} deep layers"
            )
        if self.fused_kernel not in ("off", "auto", "on"):
            raise ValueError(
                f"fused_kernel must be 'off', 'auto' or 'on', "
                f"got {self.fused_kernel!r}"
            )
        if self.table_grad not in ("scatter", "segsum"):
            raise ValueError(
                f"table_grad must be 'scatter' or 'segsum', "
                f"got {self.table_grad!r}"
            )
        if self.shard_exchange not in ("psum", "alltoall", "auto"):
            raise ValueError(
                f"shard_exchange must be 'psum', 'alltoall' or 'auto', "
                f"got {self.shard_exchange!r}"
            )
        if not 0.0 <= self.shard_exchange_capacity <= 1.0:
            raise ValueError(
                f"shard_exchange_capacity must be in [0, 1] (a fraction of "
                f"the local id stream), got {self.shard_exchange_capacity!r}"
            )
        # the fused Pallas kernel owns both gathers AND their backward, so
        # table_grad='segsum' never takes effect on the fused path — reject
        # the certain conflict, warn on the backend-dependent one
        # (round-5 advisor finding: 'auto' resolving to fused on TPU
        # silently dropped the segsum backward under test)
        if self.table_grad == "segsum" and self.fused_kernel == "on":
            raise ValueError(
                "table_grad='segsum' has no effect with fused_kernel='on': "
                "the fused kernel supplies its own dedup'd backward — use "
                "fused_kernel='off' (or 'auto' on non-TPU) with segsum, or "
                "table_grad='scatter' with the fused kernel"
            )
        if self.table_grad == "segsum" and self.fused_kernel == "auto":
            import warnings

            warnings.warn(
                "table_grad='segsum' is ignored whenever "
                "fused_kernel='auto' resolves to the fused path (TPU "
                "backends): the fused kernel supplies its own backward. "
                "Set fused_kernel='off' to guarantee the segsum backward.",
                stacklevel=2,
            )
        if self.tiered_page_rows < 1:
            raise ValueError(
                f"tiered_page_rows must be >= 1, got {self.tiered_page_rows}"
            )
        for name in ("tiered_hot_slots", "tiered_stage_rows",
                     "tiered_host_rows"):
            if getattr(self, name) < 0:
                raise ValueError(
                    f"{name} must be >= 0 (0 = auto), got "
                    f"{getattr(self, name)}"
                )
        if self.tiered_embeddings and self.fused_kernel != "off":
            raise ValueError(
                "tiered_embeddings pages rows through a slot-space cache; "
                "the fused kernel gathers a RESIDENT table — use "
                "fused_kernel='off' with tiered embeddings"
            )


@dataclass(frozen=True)
class OptimizerConfig:
    """Optimizer selection, parity with reference ps:292-305."""

    name: str = "Adam"                # Adam | Adagrad | Momentum | Ftrl
    learning_rate: float = 0.0005     # ps:56
    # Horovod path scales lr by world size (hvd:171). Explicit knob here.
    scale_lr_by_data_parallel: bool = False
    # Beyond-reference (the reference is constant-lr only, ps:292-305):
    # warmup + decay schedules over OPTIMIZER steps.  constant|cosine|linear;
    # cosine/linear need decay_steps (TOTAL horizon incl. warmup) and end at
    # learning_rate * lr_end_fraction.  Resume-safe: the schedule reads the
    # restored step count.
    lr_schedule: str = "constant"
    warmup_steps: int = 0
    decay_steps: int = 0
    lr_end_fraction: float = 0.0
    # lr split: fm_w/fm_v (the tables the reference's PS hosted) train at
    # learning_rate * this; the MLP/bias keep the base lr.  Exact lr-split
    # semantics for Adam/Adagrad/Momentum; rejected for Ftrl.
    embedding_lr_multiplier: float = 1.0
    # touched-rows-only Adam for the embedding tables (train/lazy.py): the
    # TF1 sparse_apply_adam capability; Adam-only, single-controller path
    lazy_embedding_updates: bool = False
    # ZeRO-style dp-sharded weight update (train/optimizer.zero_sharded,
    # arxiv 2004.13336): reduce-scatter grads over the data axis, each dp
    # shard owns 1/dp of the flattened params and their optimizer moments,
    # all-gather the fresh windows.  "off" = replicated moments + pmean
    # (the original path) | "on" = shard whenever data_parallel > 1 (a
    # no-op at dp == 1 — warned in Config.__post_init__) | "auto" = on
    # exactly when data_parallel > 1.  Bit-identical to the replicated
    # path (tests/test_zero_sharding.py); applies to the SPMD train steps
    # (parallel/spmd.py) — the single-device step has no data axis.
    # NOT an EXECUTABLE_SPEC_FIELD: serving executables never touch
    # opt_state, so the knob cannot change any lowered serving shape.
    zero_sharding: str = "auto"
    adam_b1: float = 0.9
    adam_b2: float = 0.999
    adam_eps: float = 1e-8
    adagrad_init_accum: float = 1e-8  # ps:297 initial_accumulator_value
    momentum: float = 0.95            # ps:301

    def __post_init__(self):
        if self.zero_sharding not in ("off", "on", "auto"):
            raise ValueError(
                f"optimizer.zero_sharding must be 'off', 'on' or 'auto', "
                f"got {self.zero_sharding!r}"
            )


@dataclass(frozen=True)
class DataConfig:
    """Input-pipeline config: file/stream modes + the 4-way shard matrix.

    Shard matrix parity: README.md:87-92 and hvd:127-149 of the reference.
    ``s3_shard`` ≡ enable_s3_shard (platform pre-sharded files per host);
    ``multi_path`` ≡ enable_data_multi_path (one stream channel per local worker).
    """

    training_data_dir: str = ""
    val_data_dir: str = ""
    test_data_dir: str = ""
    batch_size: int = 1024            # notebook cell 4 (script default was 64, ps:54)
    num_epochs: int = 10
    shuffle_files: bool = True        # reference shuffles the *file list* (ps:422)
    shuffle_buffer: int = 0           # 0 = no record-level shuffle (reference has none)
    drop_remainder: bool = True       # ps:158 batch(..., drop_remainder=True)
    stream_mode: bool = False         # pipe_mode analog: streaming reader vs file mode
    s3_shard: bool = False            # platform pre-sharded the files per host
    multi_path: bool = False          # one stream path per local worker
    training_channel_name: str = "training"
    evaluation_channel_name: str = "evaluation"
    # stream-mode eval reads the evaluation channel until EOF, or until this
    # many batches when > 0 (a live channel may never close — bound the read)
    eval_max_batches: int = 0
    prefetch_batches: int = 2         # double-buffered host->device feed
    file_patterns: tuple[str, ...] = ("tr", "train")
    # concurrent per-source C++ readers for multi-shard ingest (the
    # multi-channel/multi-shard feed capability, hvd nb cell 8); 1 =
    # sequential.  Only takes effect with the native reader and >1 source.
    parallel_readers: int = 4
    # spread Zipf-hot ids across embedding shards with a fixed bijective
    # permutation (host-side, parallel/embedding.permute_ids)
    permute_ids: bool = False


@dataclass(frozen=True)
class MeshConfig:
    """Device mesh topology.  Replaces PS topology flags (ps:38-48) and
    Horovod rank plumbing (hvd:333-350) with named mesh axes.  The axis
    NAMES are fixed framework-wide ("data"/"model",
    parallel/mesh.DATA_AXIS/MODEL_AXIS) — they appear in every sharding
    rule, so they are constants, not configuration."""

    # -1 = all remaining devices on that axis
    data_parallel: int = -1
    model_parallel: int = 1           # row-shard factor for embedding tables
    # multi-host wiring (jax.distributed). 0 processes = single-process.
    coordinator_address: str = ""
    num_processes: int = 1
    process_id: int = 0


@dataclass(frozen=True)
class ElasticConfig:
    """Elastic preemption-tolerant training (``deepfm_tpu/elastic``): mesh
    shape as a RUNTIME variable.  A device registry watches availability;
    on a shrink/grow the trainer drains the in-flight step, commits
    {weights, optimizer state, stream cursor} as one Orbax payload, plans
    a minimal-traffic N→M redistribution, rebuilds mesh/shardings/compiled
    steps for the new topology, and resumes the stream cursor exactly-once
    (online/trainer.py commit semantics).  Publishing continues across the
    reshard, so serving never observes the topology change."""

    # run the elastic controller instead of the fixed-mesh online trainer
    # (task_type=online-train only; batch training keeps the stop-the-world
    # restart path in launch/preemption.py + checkpoint/reshard.py)
    enabled: bool = False
    # preferred embedding row-shard width: the planner picks the LARGEST
    # divisor of the live device count <= this (0 = mesh.model_parallel).
    # Keeping mp stable across a shrink keeps the padded vocab — and so the
    # published artifact shapes — identical, which keeps every post-reshard
    # group swap at the serving pool a jit cache hit.
    prefer_model_parallel: int = 0
    # refuse to rebuild on fewer devices than this; wait for capacity
    min_devices: int = 1
    # registry poll cadence while waiting for capacity to return
    poll_interval_secs: float = 0.25
    # max seconds to wait for min_devices after a shrink below it
    # (0 = wait forever — the platform owns the reschedule)
    wait_for_capacity_secs: float = 0.0
    # attempt a drain+commit on the OLD mesh before resharding (virtual
    # registries and advance-notice preemptions); when the commit itself
    # fails (devices already gone) the last periodic commit is the resume
    # point — exactly-once either way, the failed window just replays
    drain_commit: bool = True
    # -- multi-host composition (elastic/coord.py) --------------------------
    # coordination service URL ("" = single-process: no leases, no
    # consensus, PR-9 behavior).  With a coordinator, every training
    # process holds a TTL lease, heartbeats its local registry view, and
    # reshards only through the coordinator's two-phase drain barrier;
    # commits and publishes carry the lease's fencing token, which the
    # checkpoint root and publish root ENFORCE (a stale-token write is
    # refused, not just discouraged).
    coordinator_url: str = ""
    # lease TTL: a process that misses heartbeats for this long is expired
    # from consensus (its devices drop out, its fencing token goes stale).
    # Sent in the acquire request; the coordinator honors it clamped to
    # its own --lease-ttl ceiling, and the granted value drives expiry.
    lease_ttl_secs: float = 10.0
    # heartbeat cadence (must leave headroom under the TTL; transitions
    # and view changes heartbeat immediately regardless)
    heartbeat_interval_secs: float = 1.0
    # LiveDeviceRegistry debounce: consecutive anomalous polls required
    # before a device-set change bumps the epoch (one transient device-
    # query hiccup must not cost a full drain/commit/reshard cycle)
    registry_debounce_polls: int = 2
    # MPMD trainer/publisher split: the trainer only COMMITS payloads;
    # a separate `--task_type publish` process tails the checkpoint root
    # and publishes asynchronously, so a publish-store outage degrades
    # freshness instead of stalling the train step
    publisher_split: bool = False
    # publisher process: cadence for polling the checkpoint root for
    # newly committed payloads
    publish_poll_secs: float = 0.5

    def __post_init__(self):
        if self.min_devices < 1:
            raise ValueError(
                f"elastic.min_devices must be >= 1, got {self.min_devices}"
            )
        if self.prefer_model_parallel < 0:
            raise ValueError(
                f"elastic.prefer_model_parallel must be >= 0 (0 = "
                f"mesh.model_parallel), got {self.prefer_model_parallel}"
            )
        import math

        # NaN slips through plain <= 0 checks and every downstream
        # min/compare — a NaN TTL would mint a never-expiring lease
        if not (self.lease_ttl_secs > 0
                and math.isfinite(self.lease_ttl_secs)):
            raise ValueError(
                f"elastic.lease_ttl_secs must be finite and > 0, got "
                f"{self.lease_ttl_secs}"
            )
        if not (self.heartbeat_interval_secs > 0
                and math.isfinite(self.heartbeat_interval_secs)):
            raise ValueError(
                f"elastic.heartbeat_interval_secs must be finite and > 0, "
                f"got {self.heartbeat_interval_secs}"
            )
        if self.heartbeat_interval_secs >= self.lease_ttl_secs / 2:
            raise ValueError(
                f"elastic.heartbeat_interval_secs="
                f"{self.heartbeat_interval_secs} leaves no headroom under "
                f"lease_ttl_secs={self.lease_ttl_secs}: one delayed "
                f"heartbeat would expire the lease and self-fence the "
                f"trainer — keep the interval under ttl/2"
            )
        if self.registry_debounce_polls < 1:
            raise ValueError(
                f"elastic.registry_debounce_polls must be >= 1, got "
                f"{self.registry_debounce_polls}"
            )
        if self.publish_poll_secs <= 0:
            raise ValueError(
                f"elastic.publish_poll_secs must be > 0, got "
                f"{self.publish_poll_secs}"
            )


@dataclass(frozen=True)
class FleetConfig:
    """Multi-tenant model fleet (``deepfm_tpu/fleet``): N model variants
    served from ONE shard-group pool's precompiled executables.  Weights
    ride the executables as jit ARGUMENTS (serve/reload.py, serve/pool/
    sharded.py), so same-spec tenants cost one payload each and ZERO extra
    executables — variant selection is a payload pick, not a recompile
    (the ``audit_multitenant`` trace contract pins this).  The router
    splits traffic hash-stably across the serving tenants, shadow tenants
    score a sampled slice of the live stream off the response path, and
    each tenant hot-swaps group-atomically without touching its
    neighbours."""

    # tenant bindings: JSON text or a list of entry objects —
    #   [{"name": "prod", "source": "<publish root>", "split_percent": 90},
    #    {"name": "exp",  "source": "...", "split_percent": 10},
    #    {"name": "challenger", "source": "...", "shadow_of": "prod"}]
    # ``model`` may carry executable-NEUTRAL overrides; a tenant whose
    # model overrides touch an executable-spec field is refused at load
    # (Config.__post_init__ names the differing fields).
    tenants: tuple = ()
    # fraction of the incumbent's live stream the shadow challenger scores
    # (hash-stable per key, like the split itself)
    shadow_sample_percent: float = 100.0
    # bounded shadow queue: offers beyond this depth are SHED (counted) —
    # the shadow path may lose samples under load, never add latency
    shadow_queue_depth: int = 128

    def __post_init__(self):
        object.__setattr__(
            self, "tenants", validate_tenant_entries(self.tenants)
        )
        if not 0.0 <= self.shadow_sample_percent <= 100.0:
            raise ValueError(
                f"fleet.shadow_sample_percent must be in [0, 100], got "
                f"{self.shadow_sample_percent}"
            )
        if self.shadow_queue_depth < 1:
            raise ValueError(
                f"fleet.shadow_queue_depth must be >= 1, got "
                f"{self.shadow_queue_depth}"
            )


@dataclass(frozen=True)
class SloConfig:
    """SLO-driven adaptive serving control plane (``deepfm_tpu/serve/
    control``): deadline-aware admission at the micro-batcher, router-
    level hedged tail requests, and elastic shard-group autoscaling.
    Everything here is HOST-side control policy — the ``audit_control_
    plane`` trace contract proves none of it enters the jitted predict.

    Graceful degradation is the invariant the knobs parameterize: shed
    the cheapest work first (shadow offers, then funnel width, then
    plain predicts), never fail work already admitted, always converge
    back (hysteresis on every edge)."""

    # request completion SLO in milliseconds — the default deadline for
    # requests that carry no ``X-Deadline-Ms`` header, AND the hedge
    # trigger budget (a group whose live p95 exceeds this is hedge-
    # eligible).  0 disables deadline admission and hedging.
    deadline_ms: float = 0.0
    # hedge delay as a percent of the first-choice group's live p95: the
    # hedge fires only after the primary has already outlived this share
    # of the typical tail (a p95-based adaptive delay — near-zero extra
    # load when the group is healthy)
    hedge_after_pct: float = 95.0
    # hedges may add at most this percent extra load (token bucket over
    # the recent request rate; an exhausted bucket suppresses hedging,
    # never the primary request)
    hedge_budget_pct: float = 5.0
    # cross-group retries share a token bucket accruing at this percent
    # of the recent request rate; beyond it the router fails fast with
    # 503 + Retry-After instead of amplifying a pool-wide brownout
    retry_budget_pct: float = 10.0
    # -- priority shed ladder (cheapest first; utilizations in [0,1] of
    # the admission queue bound, EWMA-smoothed so a single burst does
    # not flip levels) ------------------------------------------------
    # level 1: shed shadow-scoring offers (zero user impact)
    shed_shadow_util: float = 0.60
    # level 2: degrade recommend expand/rank width toward the floor
    degrade_util: float = 0.75
    # level 3: shed plain predicts at admission (503 + Retry-After)
    shed_predict_util: float = 0.90
    # recommend width floor under level-2 degradation, percent of the
    # requested top_k/return_n (100 = never degrade)
    degrade_floor_pct: float = 50.0
    # -- elastic shard-group autoscaling --------------------------------
    min_groups: int = 1
    max_groups: int = 4
    # scale up when utilization stays above this (or p95 stays over
    # deadline_ms) for scale_up_window_secs
    scale_up_util: float = 0.75
    # scale down when utilization stays below this for
    # scale_down_window_secs (strictly below scale_up_util: the gap is
    # the hysteresis band that prevents flapping)
    scale_down_util: float = 0.25
    scale_up_window_secs: float = 5.0
    scale_down_window_secs: float = 30.0
    # minimum seconds between autoscale actions (lets a fresh group's
    # load signal settle before the next decision)
    cooldown_secs: float = 10.0

    def __post_init__(self):
        import math

        for name in ("deadline_ms",):
            v = getattr(self, name)
            if not (v >= 0 and math.isfinite(v)):
                raise ValueError(
                    f"slo.{name} must be finite and >= 0, got {v}"
                )
        for name in ("hedge_after_pct", "hedge_budget_pct",
                     "retry_budget_pct", "degrade_floor_pct"):
            v = getattr(self, name)
            if not (0.0 <= v <= 100.0 and math.isfinite(v)):
                raise ValueError(
                    f"slo.{name} must be a percent in [0, 100], got {v}"
                )
        for name in ("shed_shadow_util", "degrade_util",
                     "shed_predict_util", "scale_up_util",
                     "scale_down_util"):
            v = getattr(self, name)
            if not (0.0 < v <= 1.0 and math.isfinite(v)):
                raise ValueError(
                    f"slo.{name} must be a utilization in (0, 1], got {v}"
                )
        if not (self.shed_shadow_util <= self.degrade_util
                <= self.shed_predict_util):
            raise ValueError(
                f"slo shed ladder must be ordered cheapest-first: "
                f"shed_shadow_util={self.shed_shadow_util} <= "
                f"degrade_util={self.degrade_util} <= "
                f"shed_predict_util={self.shed_predict_util} — shedding "
                f"plain predicts before shadow offers inverts graceful "
                f"degradation"
            )
        if self.min_groups < 1:
            raise ValueError(
                f"slo.min_groups must be >= 1, got {self.min_groups}"
            )
        if self.max_groups < self.min_groups:
            raise ValueError(
                f"slo.max_groups={self.max_groups} < min_groups="
                f"{self.min_groups}"
            )
        if self.scale_down_util >= self.scale_up_util:
            raise ValueError(
                f"slo.scale_down_util={self.scale_down_util} must stay "
                f"strictly below scale_up_util={self.scale_up_util}: the "
                f"gap is the hysteresis band — without it the autoscaler "
                f"flaps a group up and down on every load ripple"
            )
        for name in ("scale_up_window_secs", "scale_down_window_secs",
                     "cooldown_secs"):
            v = getattr(self, name)
            if not (v > 0 and math.isfinite(v)):
                raise ValueError(
                    f"slo.{name} must be finite and > 0, got {v}"
                )


@dataclass(frozen=True)
class FlywheelConfig:
    """Data flywheel (``deepfm_tpu/flywheel``): serve → log → join →
    train on our own traffic.  The serving pool logs a hash-stable
    sample of scored impressions; a standalone join process matches
    clicks inside an attribution window (negatives synthesized at
    expiry); ``task_type=feedback-train`` points the online trainer at
    the joined stream."""

    # arm the router-side impression logger (task_type=serve pool)
    enabled: bool = False
    # immutable-segment log roots (dirs or object URLs, stream.py)
    impression_log_url: str = ""
    # click events produced by the application (join input)
    click_log_url: str = ""
    # joined labeled stream (join output; feedback-train's input)
    join_output_url: str = ""
    # fraction of requests logged, hash-stable per impression id (the
    # trace id, else the routing key) — the join recomputes the same
    # decision, so clicks for sampled-out impressions are never orphans
    sample_rate: float = 1.0
    # how long after an impression's segment publish a click may still
    # attribute; expiry under the click watermark synthesizes a negative
    attribution_window_secs: float = 1800.0
    # impression-logger segment roll: publish when the buffered segment
    # reaches this many bytes, or when its oldest record has waited this
    # long (online/stream.py SegmentWriter)
    segment_roll_bytes: int = 1 << 20
    segment_roll_age_secs: float = 10.0
    # join durability cadence: flush output + commit {cursors, pending}
    # after this many consumed input segments (checkpoints also land at
    # every run() exit)
    join_checkpoint_every_segments: int = 8
    # bounded logger queue between the serve path and the writer thread;
    # a full queue drops the impression (counted), never blocks serving
    queue_depth: int = 1024

    def __post_init__(self):
        import math

        if not (0.0 < self.sample_rate <= 1.0
                and math.isfinite(self.sample_rate)):
            raise ValueError(
                f"flywheel.sample_rate must be in (0, 1], got "
                f"{self.sample_rate}"
            )
        if not (self.attribution_window_secs > 0
                and math.isfinite(self.attribution_window_secs)):
            raise ValueError(
                f"flywheel.attribution_window_secs must be finite and "
                f"> 0, got {self.attribution_window_secs}"
            )
        if self.segment_roll_bytes < 1:
            raise ValueError(
                f"flywheel.segment_roll_bytes must be >= 1, got "
                f"{self.segment_roll_bytes}"
            )
        if not (self.segment_roll_age_secs > 0
                and math.isfinite(self.segment_roll_age_secs)):
            raise ValueError(
                f"flywheel.segment_roll_age_secs must be finite and > 0, "
                f"got {self.segment_roll_age_secs} — an age-less roll "
                f"strands a trickle of impressions in the writer buffer"
            )
        if self.join_checkpoint_every_segments < 1:
            raise ValueError(
                f"flywheel.join_checkpoint_every_segments must be >= 1, "
                f"got {self.join_checkpoint_every_segments}"
            )
        if self.queue_depth < 1:
            raise ValueError(
                f"flywheel.queue_depth must be >= 1, got "
                f"{self.queue_depth}"
            )
        if self.enabled and not self.impression_log_url:
            raise ValueError(
                "flywheel.enabled needs flywheel.impression_log_url — "
                "the logger has nowhere to publish segments"
            )


@dataclass(frozen=True)
class RegionsConfig:
    """Cross-region active-active serving (``deepfm_tpu/region``): one
    pool + one model store per region, an async manifest replicator
    keeping every region store behind-but-never-torn (marker-last order
    preserved per region), and a front tier routing each user to a
    hash-stable home region with staleness-SLO-gated failover.  All
    host-side control plane — ``audit_region_front`` proves none of it
    enters the jitted predict."""

    # arm the region layer (task_type=region-front)
    enabled: bool = False
    # region cells: each entry {"name", "router_url", "store_root"} —
    # the region pool's router endpoint and the region-local publish
    # root its hot-reload tails (dir or object URL)
    regions: tuple = ()
    # the home publish root the replicator mirrors into region stores
    home_root: str = ""
    # front tier bind address
    front_host: str = "127.0.0.1"
    front_port: int = 8400
    # replicator tail cadence over the home root
    replication_poll_secs: float = 1.0
    # whole-region health probe cadence and consecutive failures before
    # ejection (traffic-observed failures count toward the same bar)
    probe_interval_secs: float = 1.0
    eject_after: int = 2
    # -- staleness SLO (model-version skew, in committed versions) ------
    # a region whose store is more than this many versions behind the
    # home root flips to drain-and-catch-up instead of serving
    # stale-beyond-SLO scores
    max_version_skew: int = 2
    # re-admission bar (hysteresis): a drained or ejected region takes
    # traffic again only once its skew is back at or below this
    readmit_version_skew: int = 0
    # cross-region failover token budget, percent of the recent request
    # rate — beyond it the front fails fast (503 + Retry-After) so a
    # region brownout cannot cascade into a retry storm
    failover_budget_pct: float = 10.0
    # retention floor at the home root: the publisher keeps at least
    # this many versions (max with run.keep_checkpoints) so a region
    # lagging inside the SLO can still fetch what it is catching up to
    # (0 = no widening)
    publish_keep_window: int = 0

    def __post_init__(self):
        import math

        if self.enabled:
            if not self.regions:
                raise ValueError(
                    "regions.enabled needs at least one region entry"
                )
            if not self.home_root:
                raise ValueError(
                    "regions.enabled needs regions.home_root — the "
                    "replicator has nothing to tail"
                )
        names = []
        for entry in self.regions:
            if not isinstance(entry, dict) or not entry.get("name") \
                    or not entry.get("router_url"):
                raise ValueError(
                    f"each regions.regions entry needs 'name' and "
                    f"'router_url' (got {entry!r})"
                )
            names.append(entry["name"])
        if len(names) != len(set(names)):
            raise ValueError(
                f"regions.regions names must be unique, got {names}"
            )
        if self.max_version_skew < 0 or self.readmit_version_skew < 0:
            raise ValueError(
                "regions version-skew bounds must be >= 0"
            )
        if self.readmit_version_skew > self.max_version_skew:
            raise ValueError(
                f"regions.readmit_version_skew="
                f"{self.readmit_version_skew} must not exceed "
                f"max_version_skew={self.max_version_skew} — the "
                f"re-admit bar cannot be laxer than the drain bar"
            )
        if not (0.0 <= self.failover_budget_pct <= 100.0
                and math.isfinite(self.failover_budget_pct)):
            raise ValueError(
                f"regions.failover_budget_pct must be a percent in "
                f"[0, 100], got {self.failover_budget_pct}"
            )
        for name in ("replication_poll_secs", "probe_interval_secs"):
            v = getattr(self, name)
            if not (v > 0 and math.isfinite(v)):
                raise ValueError(
                    f"regions.{name} must be finite and > 0, got {v}"
                )
        if self.eject_after < 1:
            raise ValueError(
                f"regions.eject_after must be >= 1, got "
                f"{self.eject_after}"
            )
        if self.publish_keep_window < 0:
            raise ValueError(
                f"regions.publish_keep_window must be >= 0, got "
                f"{self.publish_keep_window}"
            )


@dataclass(frozen=True)
class RunConfig:
    """Run/driver config: task dispatch + paths (ps:70-79) + cluster identity
    (SM_HOSTS/SM_CURRENT_HOST analogs, ps:80-95)."""

    task_type: str = "train"          # train | eval | infer | export | serve
                                      # | online-train | feedback-train
                                      # (ps:77-79; serve = online scoring
                                      # over the exported servable,
                                      # serve/server.py; online-train =
                                      # continuous training from an event
                                      # log, online/trainer.py; feedback-
                                      # train = online-train over the
                                      # flywheel's joined stream,
                                      # deepfm_tpu/flywheel)
    model_dir: str = "./model_dir"
    servable_model_dir: str = "./servable"
    clear_existing_model: bool = False  # hvd:66-68
    hosts: tuple[str, ...] = ("localhost",)
    current_host: str = "localhost"
    workers_per_host: int = 1         # hvd:80-82 worker_per_host
    log_steps: int = 100
    # optimizer steps fused into ONE compiled dispatch (lax.scan inside the
    # sharded step) with ONE stacked host->device transfer: the standard TPU
    # host-loop design.  Amortizes per-step dispatch/transfer overhead —
    # worth ~2x at reference batch sizes where dispatch latency rivals the
    # 135 us on-chip step.  1 = step-per-dispatch (reference-equivalent
    # cadence).  Checkpoint/eval/logging granularity becomes K steps.
    # Applies to the CTR train task (train/loop.run_train); the retrieval
    # family keeps step-per-dispatch.  On a live FIFO (pipe-mode) feed, K
    # host batches buffer before each dispatch, so a slow producer adds up
    # to K-1 batches of latency and a partial tail chunk only drains at
    # stream close — prefer 1 for latency-sensitive streaming.
    steps_per_loop: int = 1
    eval_start_delay_secs: int = 0    # reference: 1000 (ps:517); 0 = eval immediately
    eval_throttle_secs: int = 0       # reference: 1200 (ps:519)
    checkpoint_every_steps: int = 1000
    keep_checkpoints: int = 3
    seed: int = 0
    profile_dir: str = ""             # jax.profiler trace dir ("" = off)
    serve_port: int = 8501            # task_type=serve bind port
    serve_host: str = "127.0.0.1"     # bind address (0.0.0.0 for remote clients)
    serve_item_corpus: str = ""       # two-tower: JSONL corpus for :retrieve
    serve_workers: int = 1            # >1: SO_REUSEPORT process pool (the
                                      # TF-Serving worker-pool analog,
                                      # serve/server.py serve_pool)
    # micro-batching engine (serve/batcher.py): coalesced requests pad to
    # the smallest of these bucket sizes that fits — each bucket is one
    # precompiled XLA executable
    serve_buckets: str = "8,32,128,512"
    # admission timeout: max ms a request waits for bucket-mates on an
    # IDLE engine (under load the running dispatch is the coalescing
    # window and no extra wait happens)
    serve_max_wait_ms: float = 2.0
    # hot weight reload (serve/reload.py): publish root (dir or object URL,
    # online/publisher.py) polled for new versions; "" = static weights.
    # New versions swap under the precompiled bucket executables after a
    # canary probe, with in-flight dispatches drained across the swap.
    serve_reload_url: str = ""
    serve_reload_interval_secs: float = 2.0
    # router-fronted shard-group serving pool (serve/pool/): >0 runs the
    # serve task as `serve_groups` shard-group member processes (tables
    # row-sharded over each group's mesh, the alltoall exchange on the
    # predict path) behind the consistent-hashing router
    serve_groups: int = 0
    # per-group mesh shape: batch sharding x table row sharding.
    # model_parallel 0 = auto (the member host's devices / data_parallel)
    serve_group_data_parallel: int = 1
    serve_group_model_parallel: int = 0
    # router front: bind port, max extra shard-groups tried per request,
    # health-probe cadence, consecutive probe failures before ejection
    serve_router_port: int = 8500
    serve_retry_limit: int = 2
    serve_health_interval_secs: float = 1.0
    serve_eject_after: int = 2
    # recommendation funnel (deepfm_tpu/funnel; task_type=serve over a
    # funnel servable — sharded top-K retrieval into live-weight ranking):
    # candidates retrieved per user and ranked items returned per user
    # (0 = the servable's funnel.json defaults).  funnel_top_k > 0 also
    # engages the funnel geometry validation in Config.__post_init__.
    funnel_top_k: int = 0
    funnel_return_n: int = 0
    # quantized retrieval tier (funnel/quant.py): "exact" scores the f32
    # corpus bit-exactly; "int8" streams per-row symmetric int8 codes and
    # exactly rescores an oversampled shortlist in f32; "auto" picks int8
    # once the index CAPACITY crosses funnel/quant.AUTO_INT8_MIN_ROWS.
    # Not an executable-spec field, but part of the published funnel
    # manifest — publish and serving modes must agree (stage_version
    # refuses skew).
    funnel_retrieval: str = "exact"
    # int8 shortlist width multiplier: K*oversample candidates survive the
    # quantized pass into the exact f32 rescore
    funnel_oversample: int = 4
    # publish-time recall gate (funnel/recall.py): an int8 publish whose
    # measured recall@top_k falls under this is refused
    funnel_min_recall: float = 0.95
    # the fused Pallas score/top-k kernel (ops/pallas_retrieval.py):
    # on | off | auto (auto = TPU backends only, with a compile-probe
    # fallback to the lax composition)
    funnel_pallas: str = "auto"
    # online continuous training (task_type=online-train, online/trainer.py):
    # publish a servable version every N optimizer steps (0 = only at
    # stream end); stop after N batches (0 = unbounded); stop after N
    # seconds without new events (0 = tail forever)
    online_publish_every_steps: int = 100
    online_max_batches: int = 0
    online_idle_timeout_secs: float = 0.0
    # in-process crash retries with resume-from-checkpoint (the spot-retry
    # analog of use_spot_instances/max_wait, both notebooks cell 4)
    max_restarts: int = 0
    restart_backoff_secs: float = 5.0

    @property
    def host_rank(self) -> int:
        try:
            return list(self.hosts).index(self.current_host)
        except ValueError:
            raise ValueError(
                f"current_host {self.current_host!r} is not in hosts "
                f"{list(self.hosts)!r} — check SM_CURRENT_HOST/SM_HOSTS or "
                f"DEEPFM_CURRENT_HOST/DEEPFM_HOSTS consistency"
            ) from None

    @property
    def num_hosts(self) -> int:
        return len(self.hosts)


@dataclass(frozen=True)
class Config:
    model: ModelConfig = field(default_factory=ModelConfig)
    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    data: DataConfig = field(default_factory=DataConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    run: RunConfig = field(default_factory=RunConfig)
    elastic: ElasticConfig = field(default_factory=ElasticConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    slo: SloConfig = field(default_factory=SloConfig)
    flywheel: FlywheelConfig = field(default_factory=FlywheelConfig)
    regions: RegionsConfig = field(default_factory=RegionsConfig)

    def __post_init__(self):
        """Cross-section contracts no single section can check.

        A mis-sized exchange capacity or an unpackable sort bound does
        not produce a wrong answer — it produces a SLOW one (permanent
        psum fallback, variadic argsort), which nothing downstream would
        ever flag.  Validate at config time: degenerate-by-construction
        shapes raise, merely-suspicious ones warn loudly."""
        import math
        import warnings

        m, o, d, mesh = self.model, self.optimizer, self.data, self.mesh
        mp, dp = mesh.model_parallel, mesh.data_parallel
        # 1. alltoall request capacity vs the batch shape: a fraction so
        # small that one example's field_size distinct ids cannot fit even
        # when spread perfectly across owners means the overflow psum
        # fallback engages on essentially EVERY batch — the exchange would
        # silently run as (slower-than-)psum forever.
        if m.shard_exchange_capacity > 0 and m.shard_exchange != "psum" \
                and mp > 1:
            n_local = -(-d.batch_size // max(1, dp)) * m.field_size
            cap = max(1, min(
                math.ceil(m.shard_exchange_capacity * n_local), n_local))
            if cap * mp < m.field_size:
                raise ValueError(
                    f"shard_exchange_capacity={m.shard_exchange_capacity} "
                    f"gives {cap} request slots/owner x {mp} owners < "
                    f"field_size={m.field_size}: one example's distinct "
                    f"ids cannot fit, so the overflow psum fallback would "
                    f"engage on every batch — raise the capacity (0 = "
                    f"auto: ceil(N/M))"
                )
            even = -(-n_local // mp)
            if cap < -(-even // 2):
                warnings.warn(
                    f"shard_exchange_capacity={m.shard_exchange_capacity} "
                    f"({cap} slots/owner) is under half the even-spread "
                    f"requirement ceil(N/M)={even} for "
                    f"N={n_local} local ids on {mp} owners — expect "
                    f"frequent overflow fallback to the dense psum path "
                    f"(parallel/embedding.py)", stacklevel=2,
                )
        # 1b. zero_sharding='on' with a declared single-replica data axis
        # is a silent no-op (there is nothing to shard the update across);
        # warn so a flag meant for the pod doesn't quietly do nothing on a
        # one-replica debug mesh.  dp == -1 (auto) is resolved at mesh
        # build time and stays quiet here.
        if o.zero_sharding == "on" and dp == 1:
            warnings.warn(
                "optimizer.zero_sharding='on' with mesh.data_parallel=1 "
                "is a no-op: the weight update shards across the data "
                "axis, and there is only one data shard "
                "(train/optimizer.zero_sharded)", stacklevel=2,
            )
        # 2. packed-sort id bound: the dedup paths (exchange plan, lazy
        # pack) sort (id, position) packed into ONE uint32 key; a vocab
        # too large for the local stream length falls back to the ~4x
        # variadic argsort.  Correct, but the dominant sort cost — say so.
        exchanges = mp > 1 or (o.lazy_embedding_updates and dp > 1)
        if exchanges and dp > 0:
            n_local = -(-d.batch_size // dp) * m.field_size
            bound = m.feature_size + 1  # +1: the out-of-range sentinel
            if bound > packed_sort_id_bound(n_local):
                warnings.warn(
                    f"feature_size={m.feature_size} exceeds the packed-"
                    f"sort id bound {packed_sort_id_bound(n_local)} for "
                    f"{n_local} local ids/shard: dedup sorts fall back to "
                    f"the ~4x variadic argsort (ops/embedding.py "
                    f"sort_segments).  Tiered embeddings "
                    f"(model.tiered_embeddings) probe in SLOT space and "
                    f"keep the packed sort at any vocabulary.",
                    stacklevel=2,
                )
        # 3. tiered cache geometry vs the batch's id stream
        if m.tiered_embeddings:
            bf = d.batch_size * m.field_size
            if 0 < m.tiered_hot_slots < bf:
                raise ValueError(
                    f"tiered_hot_slots={m.tiered_hot_slots} cannot hold "
                    f"one batch's id stream (batch_size*field_size={bf})"
                )
            if 0 < m.tiered_stage_rows < bf:
                warnings.warn(
                    f"tiered_stage_rows={m.tiered_stage_rows} < "
                    f"batch_size*field_size={bf}: a cache-cold batch can "
                    f"miss on every id and overflow the staging pack "
                    f"(the pager raises at run time)", stacklevel=2,
                )
            h = m.tiered_host_rows
            if h and h - max(1, h // 16) < bf:
                # one fill must fit inside the host tier's serviceable
                # window (capacity minus one eviction chunk) or a cold
                # batch's miss fetch cannot be satisfied (HostTier
                # raises rather than thrash)
                raise ValueError(
                    f"tiered_host_rows={h} cannot service one batch's "
                    f"miss fetch (window {h - max(1, h // 16)} < "
                    f"batch_size*field_size={bf})"
                )
        # 4. recommendation funnel geometry (deepfm_tpu/funnel): lax.top_k
        # cannot select more rows than one index shard holds (the retrieve
        # executable would be unbuildable), and a user's K-candidate rank
        # fan-out must land on a precompiled serving bucket — K over the
        # largest bucket means even a lone recommend row cannot dispatch
        # through any single rank executable (the pigeonhole), while a
        # bucket padding to >= 2x K halves the rank throughput silently
        # (the wasteful case).  Runtime re-validates against the actual
        # serve mesh (funnel/index.make_funnel_context); this is the
        # config-time gate on the declared topology.
        r = self.run
        # the quantized-tier knobs validate even without funnel_top_k —
        # a typo'd mode string must fail the config load, not the serve
        # boot hours later.  The literal mirrors funnel/quant.py
        # RETRIEVAL_MODES (config stays import-light; a sync test pins
        # the two)
        retrieval_modes = ("exact", "int8", "auto")
        if r.funnel_retrieval not in retrieval_modes:
            raise ValueError(
                f"run.funnel_retrieval={r.funnel_retrieval!r} is not one "
                f"of {retrieval_modes}"
            )
        if r.funnel_pallas not in ("on", "off", "auto"):
            raise ValueError(
                f"run.funnel_pallas={r.funnel_pallas!r} must be "
                f"'on', 'off' or 'auto'"
            )
        if r.funnel_oversample < 1:
            raise ValueError(
                f"run.funnel_oversample={r.funnel_oversample} must be "
                f">= 1 (1 = no oversampling, shortlist width == top_k)"
            )
        if not 0.0 < r.funnel_min_recall <= 1.0:
            raise ValueError(
                f"run.funnel_min_recall={r.funnel_min_recall} must lie "
                f"in (0, 1] — it gates int8 publishes"
            )
        if r.funnel_top_k > 0:
            k = r.funnel_top_k
            if r.funnel_return_n > k:
                raise ValueError(
                    f"funnel_return_n={r.funnel_return_n} exceeds "
                    f"funnel_top_k={k} — cannot return more ranked items "
                    f"than candidates retrieved"
                )
            item_vocab = m.item_vocab_size or m.feature_size
            mp_serve = (r.serve_group_model_parallel if r.serve_groups > 0
                        else mp)
            if mp_serve > 0:
                per_shard = -(-item_vocab // mp_serve)
                if k > per_shard:
                    raise ValueError(
                        f"funnel_top_k={k} exceeds the (padded) per-shard "
                        f"item vocab {per_shard} (item vocab {item_vocab} "
                        f"row-sharded over model_parallel={mp_serve}) — "
                        f"per-shard lax.top_k cannot select more rows than "
                        f"a shard holds"
                    )
                # the int8 shortlist widens the per-shard selection to
                # K*oversample — the same pigeonhole, scaled ("auto" is
                # checked at runtime where the capacity is known)
                if (r.funnel_retrieval == "int8"
                        and k * r.funnel_oversample > per_shard):
                    raise ValueError(
                        f"funnel_top_k*funnel_oversample = "
                        f"{k}*{r.funnel_oversample} = "
                        f"{k * r.funnel_oversample} exceeds the (padded) "
                        f"per-shard item vocab {per_shard} — the int8 "
                        f"shortlist's per-shard lax.top_k cannot select "
                        f"more rows than a shard holds; lower "
                        f"funnel_oversample or funnel_top_k"
                    )
            buckets = _parse_int_list(r.serve_buckets)
            if buckets:
                if k > max(buckets):
                    raise ValueError(
                        f"funnel_top_k={k} exceeds the largest serve "
                        f"bucket {max(buckets)}: one user's K ranking rows "
                        f"cannot fit any precompiled dispatch "
                        f"(run.serve_buckets={r.serve_buckets!r}) — raise "
                        f"the bucket set or lower funnel_top_k"
                    )
                fit = min(b for b in buckets if b >= k)
                if fit >= 2 * k:
                    warnings.warn(
                        f"funnel_top_k={k} pads to serve bucket {fit} "
                        f"(>= 2x): every user's candidate set fills under "
                        f"half a rank dispatch — add a ~{k}-row bucket to "
                        f"run.serve_buckets or raise funnel_top_k",
                        stacklevel=2,
                    )
        # 5. multi-tenant fleet spec compatibility: every tenant on the
        # pool must share the pool's executable spec (weights ride as jit
        # arguments, so same-spec tenants serve from ONE precompiled
        # executable set — audit_multitenant proves it at lowering level).
        # A tenant whose model overrides touch an executable-spec field
        # would force per-tenant modules: refuse at load, naming the
        # fields, instead of recompiling mid-traffic.
        base_model = dataclasses.asdict(m)
        for t in self.fleet.tenants:
            diff = tenant_spec_divergence(base_model, t["model"])
            if diff:
                raise ValueError(
                    f"fleet tenant {t['name']!r} diverges from its "
                    f"executable-sharing group on {diff}: same-spec "
                    f"tenants must share ONE precompiled executable set "
                    f"(EXECUTABLE_SPEC_FIELDS) — serve a divergent spec "
                    f"from its own pool instead"
                )
        # 6. data flywheel cross-section contracts: feedback-train is the
        # online trainer pointed at the JOIN's output — without a join
        # output URL there is nothing to cursor over; and when a shadow
        # challenger is armed alongside impression logging, mismatched
        # sampling rates mean the offline join replays a different slice
        # of traffic than shadow scoring measured — legal, but the two
        # reads are then not comparable, so say so once at config time.
        fw = self.flywheel
        if r.task_type in ("feedback-train", "feedback_train") \
                and not fw.join_output_url:
            raise ValueError(
                "task_type=feedback-train needs flywheel.join_output_url "
                "— the joined labeled stream the online trainer tails "
                "(run `python -m deepfm_tpu.flywheel.join` to produce it)"
            )
        if fw.enabled and any(
                t.get("shadow_of") for t in self.fleet.tenants):
            shadow_rate = self.fleet.shadow_sample_percent / 100.0
            if abs(shadow_rate - fw.sample_rate) > 1e-9:
                warnings.warn(
                    f"flywheel.sample_rate={fw.sample_rate} differs from "
                    f"fleet.shadow_sample_percent="
                    f"{self.fleet.shadow_sample_percent} while a shadow "
                    f"challenger is armed: the flywheel join and shadow "
                    f"scoring will read different traffic slices — align "
                    f"the rates if the joined labels should explain the "
                    f"shadow's divergence", stacklevel=2,
                )
        # 7. cross-region serving: the home root's retention window must
        # cover the staleness SLO — a region allowed to run
        # max_version_skew versions behind will FETCH those versions
        # from the home root while catching up, so retaining fewer than
        # skew+1 versions can delete a version a still-inside-SLO region
        # is mid-fetch on (region/replicator.py).
        rg = self.regions
        if rg.enabled:
            window = max(self.run.keep_checkpoints,
                         rg.publish_keep_window)
            if window < rg.max_version_skew + 1:
                warnings.warn(
                    f"regions.publish_keep_window={rg.publish_keep_window}"
                    f" (effective retention {window} with "
                    f"run.keep_checkpoints={self.run.keep_checkpoints}) "
                    f"is under max_version_skew+1="
                    f"{rg.max_version_skew + 1}: home retention can "
                    f"delete a version a lagging-but-inside-SLO region "
                    f"is still catching up to — widen the keep window",
                    stacklevel=2,
                )

    # ---- overrides ------------------------------------------------------

    def with_overrides(self, **sections: dict[str, Any]) -> "Config":
        """Return a new Config with per-section field overrides:
        ``cfg.with_overrides(model={'embedding_size': 64})``."""
        updates = {}
        for section, fields in sections.items():
            cur = getattr(self, section)
            updates[section] = dataclasses.replace(cur, **fields)
        return dataclasses.replace(self, **updates)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Config":
        """Build from a nested dict (the config.json schema).

        Unknown keys are dropped with a warning rather than raising: saved
        configs (servables, checkpoints) must keep loading across framework
        versions that add or retire fields.  CLI ``--set`` overrides go
        through ``with_overrides`` instead, which still rejects typos."""

        def known(section_cls, section: dict, name: str) -> dict:
            fields = {f.name for f in dataclasses.fields(section_cls)}
            out = {}
            for k, v in section.items():
                if k not in fields:
                    import logging

                    logging.getLogger(__name__).warning(
                        "config: ignoring unknown field %s.%s "
                        "(saved by a different framework version?)", name, k
                    )
                    continue
                out[k] = tuple(v) if isinstance(v, list) else v
            return out

        return cls(
            model=ModelConfig(**known(ModelConfig, d.get("model", {}), "model")),
            optimizer=OptimizerConfig(
                **known(OptimizerConfig, d.get("optimizer", {}), "optimizer")
            ),
            data=DataConfig(**known(DataConfig, d.get("data", {}), "data")),
            mesh=MeshConfig(**known(MeshConfig, d.get("mesh", {}), "mesh")),
            run=RunConfig(**known(RunConfig, d.get("run", {}), "run")),
            elastic=ElasticConfig(
                **known(ElasticConfig, d.get("elastic", {}), "elastic")
            ),
            fleet=FleetConfig(
                **known(FleetConfig, d.get("fleet", {}), "fleet")
            ),
            slo=SloConfig(**known(SloConfig, d.get("slo", {}), "slo")),
            flywheel=FlywheelConfig(
                **known(FlywheelConfig, d.get("flywheel", {}), "flywheel")
            ),
            regions=RegionsConfig(
                **known(RegionsConfig, d.get("regions", {}), "regions")
            ),
        )

    @classmethod
    def from_json(cls, path: str) -> "Config":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    @classmethod
    def from_env(cls, base: "Config | None" = None) -> "Config":
        """Fold platform environment into a config — the SM_HOSTS /
        SM_CURRENT_HOST / SM_CHANNELS capability (ps:80-95, ps:391) done at an
        explicit call site instead of import time."""
        cfg = base or cls()
        run_fields: dict[str, Any] = {}
        if os.environ.get("SM_HOSTS"):
            run_fields["hosts"] = tuple(json.loads(os.environ["SM_HOSTS"]))
        elif os.environ.get("DEEPFM_HOSTS"):
            run_fields["hosts"] = tuple(os.environ["DEEPFM_HOSTS"].split(","))
        if os.environ.get("SM_CURRENT_HOST"):
            run_fields["current_host"] = os.environ["SM_CURRENT_HOST"]
        elif os.environ.get("DEEPFM_CURRENT_HOST"):
            run_fields["current_host"] = os.environ["DEEPFM_CURRENT_HOST"]
        mesh_fields: dict[str, Any] = {}
        if os.environ.get("DEEPFM_COORDINATOR"):
            mesh_fields["coordinator_address"] = os.environ["DEEPFM_COORDINATOR"]
            mesh_fields["num_processes"] = int(os.environ.get("DEEPFM_NUM_PROCESSES", "1"))
            mesh_fields["process_id"] = int(os.environ.get("DEEPFM_PROCESS_ID", "0"))
        out = cfg
        if run_fields:
            out = out.with_overrides(run=run_fields)
        if mesh_fields:
            out = out.with_overrides(mesh=mesh_fields)
        return out
