from .config import Config, DataConfig, MeshConfig, ModelConfig, OptimizerConfig, RunConfig  # noqa: F401
