"""Backend sanitation for environments with auto-registered PJRT plugins.

Some images install a sitecustomize that registers an experimental tunneled
TPU backend ("axon") in every Python process and hooks jax's backend lookup;
its device-attach blocks for minutes (or forever) even when the caller
explicitly requested CPU via ``JAX_PLATFORMS=cpu``.  Calling
``sanitize_backend()`` before the first jax backend initialization makes the
requested platform authoritative: if the request does not include the
tunneled plugin, its factory is deregistered so nothing can dial it.
"""

from __future__ import annotations

import os

_TUNNEL_PLATFORMS = ("axon",)


def is_tpu_backend() -> bool:
    """True when the default backend is TPU hardware — including tunneled
    PJRT plugins that register under their own platform name (e.g. "axon")
    but expose TPU devices (device_kind "TPU v5e" etc.)."""
    try:
        import jax

        d = jax.devices()[0]
        if d.platform == "tpu":
            return True
        return "tpu" in getattr(d, "device_kind", "").lower()
    except Exception:
        return False


def sanitize_backend() -> None:
    requested = os.environ.get("JAX_PLATFORMS", "")
    if any(p in requested for p in _TUNNEL_PLATFORMS):
        return  # the tunnel was explicitly requested; leave it alone
    try:
        import jax

        if requested:
            # effective even if jax was imported (and env read) earlier
            jax.config.update("jax_platforms", requested)
            # The tunnel plugin hooks jax's backend lookup, so the config
            # update alone is insufficient — remove its factory whenever the
            # explicit request does not name it.
            # VERSION FRAGILITY: `jax._src.xla_bridge._backend_factories` is
            # a private dict (present in jax 0.4.x–0.7.x; keyed by platform
            # name).  If a jax upgrade renames it, the AttributeError lands
            # in the except below and the tunnel backend stays registered —
            # symptom: multi-minute hangs at first device attach despite
            # JAX_PLATFORMS=cpu.
            from jax._src import xla_bridge as xb

            for p in _TUNNEL_PLATFORMS:
                if xb._backend_factories.pop(p, None) is not None:
                    import logging

                    logging.getLogger(__name__).warning(
                        "sanitize_backend: deregistered PJRT backend factory "
                        "%r (JAX_PLATFORMS=%r does not include it)",
                        p, requested,
                    )
    except Exception as e:  # never make startup worse than the status quo
        import logging

        logging.getLogger(__name__).warning(
            "sanitize_backend: could not deregister tunnel backends (%s); "
            "device attach may hang if the tunnel is unreachable", e
        )
