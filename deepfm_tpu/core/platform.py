"""Backend sanitation for environments with auto-registered PJRT plugins.

Some images install a sitecustomize that registers an experimental tunneled
TPU backend ("axon") in every Python process and hooks jax's backend lookup;
its device-attach blocks for minutes (or forever) even when the caller
explicitly requested CPU via ``JAX_PLATFORMS=cpu``.  Calling
``sanitize_backend()`` before the first jax backend initialization makes the
requested platform authoritative: if the request does not include the
tunneled plugin, its factory is deregistered so nothing can dial it.
"""

from __future__ import annotations

import os

_TUNNEL_PLATFORMS = ("axon",)


def is_tpu_backend() -> bool:
    """True when the default backend is TPU hardware — including tunneled
    PJRT plugins that register under their own platform name (e.g. "axon")
    but expose TPU devices (device_kind "TPU v5e" etc.)."""
    try:
        import jax

        d = jax.devices()[0]
        if d.platform == "tpu":
            return True
        return "tpu" in getattr(d, "device_kind", "").lower()
    # da:allow[swallowed-exception] capability probe: no usable backend simply means "not TPU"
    except Exception:
        return False


def host_cpu_count() -> int:
    """Usable host cores (cgroup/affinity-aware where the OS exposes it)."""
    try:
        return len(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux
        return os.cpu_count() or 1


def xla_flags_supported(flags: str) -> bool:
    """True when this jaxlib accepts ``flags`` in XLA_FLAGS.

    jaxlib HARD-ABORTS the whole process (``F parse_flags_from_env:
    Unknown flags in XLA_FLAGS``) at first backend init when XLA_FLAGS
    names a flag the bundled XLA doesn't know — e.g. the CPU collective
    watchdog flags on jaxlib < 0.5.  Probing must therefore happen in a
    THROWAWAY subprocess; the verdict is cached on disk per (jaxlib
    version, flags) so the ~2 s probe runs once per machine, not once per
    pytest session."""
    import hashlib
    import subprocess
    import sys
    import tempfile

    try:
        import jaxlib.version

        version = jaxlib.version.__version__
    # da:allow[swallowed-exception] cache-key probe: an unimportable jaxlib still yields a usable key
    except Exception:
        version = "unknown"
    key = hashlib.sha1(f"{version}|{flags}".encode()).hexdigest()[:16]
    cache = os.path.join(tempfile.gettempdir(), f".xla_flag_probe_{key}")
    try:
        with open(cache) as f:
            return f.read().strip() == "1"
    except OSError:
        pass
    # mirror sanitize_backend inside the probe: the ambient sitecustomize
    # may register a tunneled PJRT plugin whose attach blocks even under
    # JAX_PLATFORMS=cpu — deregister it before touching devices
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "try:\n"
        "    from jax._src import xla_bridge as _xb\n"
        "    for _p in ('axon',):\n"
        "        _xb._backend_factories.pop(_p, None)\n"
        "except Exception:\n"
        "    pass\n"
        "jax.devices()\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS=flags)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], env=env,
            capture_output=True, timeout=120,
        )
    # da:allow[swallowed-exception] probe subprocess: failure reads as "unsupported this call", cache stays empty
    except Exception:
        # timeout / spawn failure: transient, NOT evidence about the
        # flags — report unsupported for this call but leave the cache
        # empty so a healthy later run can still enable the watchdogs
        return False
    ok = proc.returncode == 0
    # cache only definitive verdicts: success, or the known unknown-flag
    # fatal abort.  Any other nonzero exit (OOM, env breakage) says
    # nothing about flag support and must not poison the cache.
    if ok or b"Unknown flags in XLA_FLAGS" in proc.stderr:
        try:
            with open(cache, "w") as f:
                f.write("1" if ok else "0")
        except OSError:
            pass
    return ok


def relax_cpu_collective_timeouts(
    warn_s: int = 120, terminate_s: int = 900
) -> None:
    """Raise XLA:CPU's collective-rendezvous watchdogs (default 20 s warn /
    40 s TERMINATE-the-process) via XLA_FLAGS.  On an oversubscribed host —
    N virtual devices time-slicing a core or two, exactly the CI/virtual-
    mesh topology — a long first-compile or a heavy step can keep one
    device thread away from a rendezvous past 40 s and XLA kills the
    process mid-training.  Call BEFORE the first jax backend init; no-op
    for flags the caller already set explicitly, and for a jaxlib that
    doesn't know these flags (older XLA both lacks them and would
    fatal-abort on the unknown names — see :func:`xla_flags_supported`)."""
    flags = os.environ.get("XLA_FLAGS", "")
    add = []
    if "xla_cpu_collective_call_warn_stuck_timeout_seconds" not in flags:
        add.append(
            f"--xla_cpu_collective_call_warn_stuck_timeout_seconds={warn_s}"
        )
    if "xla_cpu_collective_call_terminate_timeout_seconds" not in flags:
        add.append(
            f"--xla_cpu_collective_call_terminate_timeout_seconds={terminate_s}"
        )
    if add and xla_flags_supported(" ".join(add)):
        os.environ["XLA_FLAGS"] = " ".join([flags] + add).strip()


def sanitize_backend() -> None:
    requested = os.environ.get("JAX_PLATFORMS", "")
    if any(p in requested for p in _TUNNEL_PLATFORMS):
        return  # the tunnel was explicitly requested; leave it alone
    try:
        import jax

        # value-stable RNG regardless of output sharding: jax < 0.5
        # defaults this off, making jit(init, out_shardings=sharded)
        # produce different table values than dense init — the framework
        # assumes the (newer-jax default) partitionable threefry everywhere
        # sharded-vs-dense parity matters
        try:
            jax.config.update("jax_threefry_partitionable", True)
        # da:allow[swallowed-exception] older jax without the flag: the default already matches
        except Exception:
            pass
        if requested:
            # effective even if jax was imported (and env read) earlier
            jax.config.update("jax_platforms", requested)
            # The tunnel plugin hooks jax's backend lookup, so the config
            # update alone is insufficient — remove its factory whenever the
            # explicit request does not name it.
            # VERSION FRAGILITY: `jax._src.xla_bridge._backend_factories` is
            # a private dict (present in jax 0.4.x–0.7.x; keyed by platform
            # name).  If a jax upgrade renames it, the AttributeError lands
            # in the except below and the tunnel backend stays registered —
            # symptom: multi-minute hangs at first device attach despite
            # JAX_PLATFORMS=cpu.
            from jax._src import xla_bridge as xb

            for p in _TUNNEL_PLATFORMS:
                if xb._backend_factories.pop(p, None) is not None:
                    import logging

                    logging.getLogger(__name__).warning(
                        "sanitize_backend: deregistered PJRT backend factory "
                        "%r (JAX_PLATFORMS=%r does not include it)",
                        p, requested,
                    )
    except Exception as e:  # never make startup worse than the status quo
        import logging

        logging.getLogger(__name__).warning(
            "sanitize_backend: could not deregister tunnel backends (%s); "
            "device attach may hang if the tunnel is unreachable", e
        )
