from .auc import AUCState, auc_init, auc_merge, auc_update, auc_value, exact_auc  # noqa: F401
from .batch_norm import BNParams, BNState, batch_norm, bn_init  # noqa: F401
from .embedding import dense_lookup, scaled_embedding  # noqa: F401
from .fm import fm_first_order, fm_second_order, fm_second_order_pairwise  # noqa: F401
from .initializers import glorot_normal, glorot_uniform  # noqa: F401
