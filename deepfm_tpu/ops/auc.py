"""Streaming AUC metrics.

Two implementations:

* functional bucketed streaming AUC (``auc_init`` / ``auc_update`` /
  ``auc_merge`` / ``auc_value`` over an ``AUCState``) with trapezoidal
  interpolation, semantics-compatible with
  ``tf.metrics.auc(num_thresholds=200)`` used for the reference's eval
  metric (ps:282): fixed threshold grid with ±ε end buckets, accumulated
  confusion counts, trapezoid ROC integration.  Used for parity claims
  against the reference.
* ``exact_auc`` — rank-based exact AUC (Mann-Whitney U) for a full prediction
  set; the quality oracle the bucketed metric is tested against.

All accumulation math is jit/pjit-friendly (fixed shapes, no host sync); the
state is a small [4, T] count tensor that is psum-reducible across data-
parallel shards.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

_KEPSILON = 1e-7


class AUCState(NamedTuple):
    """Confusion counts per threshold: rows are tp, fp, tn, fn."""

    counts: jnp.ndarray  # f32 [4, num_thresholds]

    @property
    def num_thresholds(self) -> int:
        return self.counts.shape[1]


def auc_thresholds(num_thresholds: int = 200) -> np.ndarray:
    """The tf.metrics.auc threshold grid: interior points evenly spaced on
    (0,1) plus ``-ε`` and ``1+ε`` end thresholds."""
    inner = [(i + 1) / (num_thresholds - 1) for i in range(num_thresholds - 2)]
    return np.asarray([0.0 - _KEPSILON] + inner + [1.0 + _KEPSILON], dtype=np.float32)


def auc_init(num_thresholds: int = 200) -> AUCState:
    return AUCState(jnp.zeros((4, num_thresholds), dtype=jnp.float32))


def auc_update(
    state: AUCState,
    labels: jnp.ndarray,
    predictions: jnp.ndarray,
    weights: jnp.ndarray | None = None,
) -> AUCState:
    """Accumulate a batch.  labels: [B] in {0,1}; predictions: [B] in [0,1]."""
    thresholds = jnp.asarray(auc_thresholds(state.num_thresholds))
    labels = labels.reshape(-1).astype(jnp.float32)
    preds = predictions.reshape(-1).astype(jnp.float32)
    w = jnp.ones_like(preds) if weights is None else weights.reshape(-1).astype(jnp.float32)
    # [B, T] predicted-positive mask per threshold
    pred_pos = (preds[:, None] > thresholds[None, :]).astype(jnp.float32)
    pos = (labels * w)[:, None]
    neg = ((1.0 - labels) * w)[:, None]
    tp = jnp.sum(pred_pos * pos, axis=0)
    fp = jnp.sum(pred_pos * neg, axis=0)
    fn = jnp.sum((1.0 - pred_pos) * pos, axis=0)
    tn = jnp.sum((1.0 - pred_pos) * neg, axis=0)
    return AUCState(state.counts + jnp.stack([tp, fp, tn, fn]))


def auc_merge(a: AUCState, b: AUCState) -> AUCState:
    """Merge shard-local states (psum-compatible: counts are additive)."""
    return AUCState(a.counts + b.counts)


def auc_value(state: AUCState) -> jnp.ndarray:
    """Trapezoidal ROC integration (tf.metrics.auc summation_method default)."""
    tp, fp, tn, fn = state.counts
    tpr = (tp + _KEPSILON) / (tp + fn + _KEPSILON)
    fpr = fp / (fp + tn + _KEPSILON)
    # thresholds ascend -> rates descend; integrate x=fpr, y=tpr
    return jnp.sum((fpr[:-1] - fpr[1:]) * (tpr[:-1] + tpr[1:]) / 2.0)


def exact_auc(labels: np.ndarray, predictions: np.ndarray) -> float:
    """Exact AUC via the rank statistic, with tie handling (average ranks)."""
    labels = np.asarray(labels).reshape(-1)
    preds = np.asarray(predictions).reshape(-1)
    n_pos = float(np.sum(labels == 1))
    n_neg = float(np.sum(labels == 0))
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(preds, kind="mergesort")
    sorted_preds = preds[order]
    ranks = np.empty_like(sorted_preds, dtype=np.float64)
    i = 0
    n = len(sorted_preds)
    while i < n:
        j = i
        while j < n and sorted_preds[j] == sorted_preds[i]:
            j += 1
        ranks[i:j] = 0.5 * (i + j - 1) + 1.0  # average 1-based rank
        i = j
    pos_rank_sum = float(np.sum(ranks[labels[order] == 1]))
    return (pos_rank_sum - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg)
