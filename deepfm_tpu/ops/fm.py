"""Factorization-machine interaction ops.

The FM second-order term uses the O(F·K) identity
``y = 0.5 · Σ_k ((Σ_f e)² − Σ_f e²)`` instead of O(F²·K) pairwise products —
same math as the reference (ps:211-217), expressed as fused elementwise +
reductions that XLA maps onto the VPU in one pass over the [B, F, K] tensor.
"""

from __future__ import annotations

import jax.numpy as jnp


def fm_first_order(feat_weights: jnp.ndarray, feat_vals: jnp.ndarray) -> jnp.ndarray:
    """``y_w = Σ_f w_f · x_f``  (reference ps:207-209).

    feat_weights: [B, F] gathered FM_W rows; feat_vals: [B, F].  Returns [B].
    """
    return jnp.sum(feat_weights * feat_vals, axis=1)


def fm_second_order(embeddings: jnp.ndarray) -> jnp.ndarray:
    """``y_v = 0.5 Σ_k ((Σ_f e)² − Σ_f e²)``  (reference ps:211-217).

    embeddings: [B, F, K] — already scaled by feature values (v_ij · x_i).
    Returns [B].
    """
    sum_f = jnp.sum(embeddings, axis=1)            # [B, K]
    sum_square = jnp.square(sum_f)                 # (Σ_f e)²
    square_sum = jnp.sum(jnp.square(embeddings), axis=1)  # Σ_f e²
    return 0.5 * jnp.sum(sum_square - square_sum, axis=1)


def fm_second_order_pairwise(embeddings: jnp.ndarray) -> jnp.ndarray:
    """O(F²) explicit pairwise form — test oracle for the identity above."""
    # Σ_{i<j} <e_i, e_j>
    gram = jnp.einsum("bik,bjk->bij", embeddings, embeddings)
    f = embeddings.shape[1]
    mask = jnp.triu(jnp.ones((f, f), dtype=embeddings.dtype), k=1)
    return jnp.sum(gram * mask, axis=(1, 2))
