"""Initializers matching TF1 semantics for convergence parity.

The reference initializes FM_W/FM_V with ``tf.glorot_normal_initializer()``
(ps:190-197) — variance scaling, fan_avg, *truncated* normal with the
0.87962566 correction — and the MLP with ``xavier_initializer()`` (glorot
uniform, the tf.contrib.layers.fully_connected default) and zero biases.
JAX's stock glorot initializers reject rank-1 shapes (FM_W is [V]), so we
implement TF's fan computation: for rank-1, fan_in = fan_out = shape[0].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# stddev correction for a normal truncated to ±2σ (TF's _compute_fans path)
_TRUNC_CORRECTION = 0.87962566103423978


def _fans(shape: tuple[int, ...]) -> tuple[float, float]:
    if len(shape) < 1:
        return 1.0, 1.0
    if len(shape) == 1:
        return float(shape[0]), float(shape[0])
    receptive = 1
    for d in shape[:-2]:
        receptive *= d
    return float(shape[-2] * receptive), float(shape[-1] * receptive)


def glorot_normal(key: jax.Array, shape: tuple[int, ...], dtype=jnp.float32) -> jax.Array:
    """TF ``glorot_normal_initializer``: truncated normal, fan_avg scaling."""
    fan_in, fan_out = _fans(shape)
    scale = 2.0 / (fan_in + fan_out)
    stddev = (scale**0.5) / _TRUNC_CORRECTION
    return stddev * jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)


def glorot_uniform(key: jax.Array, shape: tuple[int, ...], dtype=jnp.float32) -> jax.Array:
    """TF ``xavier_initializer`` (the fully_connected default)."""
    fan_in, fan_out = _fans(shape)
    limit = (6.0 / (fan_in + fan_out)) ** 0.5
    return jax.random.uniform(key, shape, dtype, -limit, limit)
