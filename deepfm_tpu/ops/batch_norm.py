"""Functional batch normalization with moving statistics.

Capability parity with the reference's dual-graph ``contrib.layers.batch_norm``
helper (ps:316-338): train mode normalizes by batch statistics and updates
the moving averages in place (``updates_collections=None`` semantics); eval
mode normalizes by the moving averages.  Here the moving stats are explicit
functional state threaded through the step (no graph collections, no
``tf.cond`` dual graphs — one traced function per mode).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class BNState(NamedTuple):
    moving_mean: jnp.ndarray  # [C]
    moving_var: jnp.ndarray   # [C]


class BNParams(NamedTuple):
    scale: jnp.ndarray  # gamma [C]
    bias: jnp.ndarray   # beta  [C]


def bn_init(num_features: int, dtype=jnp.float32) -> tuple[BNParams, BNState]:
    return (
        BNParams(jnp.ones(num_features, dtype), jnp.zeros(num_features, dtype)),
        BNState(jnp.zeros(num_features, dtype), jnp.ones(num_features, dtype)),
    )


def batch_norm(
    x: jnp.ndarray,
    params: BNParams,
    state: BNState,
    *,
    train: bool,
    decay: float = 0.9,
    eps: float = 0.001,  # contrib.layers.batch_norm default epsilon
) -> tuple[jnp.ndarray, BNState]:
    """Returns (normalized x, new state).  x: [B, C]."""
    if train:
        mean = jnp.mean(x, axis=0)
        var = jnp.var(x, axis=0)
        new_state = BNState(
            decay * state.moving_mean + (1.0 - decay) * mean,
            decay * state.moving_var + (1.0 - decay) * var,
        )
    else:
        mean, var = state.moving_mean, state.moving_var
        new_state = state
    inv = jnp.reciprocal(jnp.sqrt(var + eps))
    y = (x - mean) * inv * params.scale + params.bias
    return y, new_state
