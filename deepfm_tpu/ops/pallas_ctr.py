"""Pallas TPU kernel: fused CTR embedding gather + FM interaction.

The DeepFM hot op (reference ps:206-217) is two HBM table gathers followed
by elementwise scaling and the FM reductions.  The bandwidth-dominant part —
the FM_V [V, K] row gather — is hand-scheduled here as a deduplicated DMA
pipeline; the cheap parts (the [V] FM_W gather and the FM first/second-order
reductions) stay in XLA, which fuses them into single VPU passes over the
kernel's output.

Mosaic cannot DMA a K=32-float row at an arbitrary HBM offset (slices along
the minor dimension must be 128-lane tiles), so the kernel works on an
*aligned-window view* of the table:

    table  [V, K]  →  windows [V·K/128, 128]   (4 rows per window for K=32)
    row r lives in window r·K/128 at lane offset (r·K) mod 128

**Dedup-before-DMA** (v2 — fixes the round-1 skewed-id regression): ids are
deduplicated in XLA first (one sort), and the kernel gathers each *unique*
row exactly once, in sorted order:

    XLA   : unique(ids)  →  sorted unique rows + inverse map
    kernel: per unique row, DMA its 128-lane window HBM→VMEM — but only
            when the window differs from the previous row's (sorted ids
            put same-window rows adjacent), NSEM copies in flight
    kernel: log-step forward-fill propagates each DMA'd window to the
            following rows that share it, then a static-roll masked select
            picks the K-lane sub-window per row (VPU)
    XLA   : emb = unique_rows[inverse] * vals   (one dense gather + scale)

On Zipf-skewed Criteo ids a batch of 1024×39 lookups hits only ~30-40% as
many unique rows, and sorted adjacency packs ~`128/K` unique rows per
window, so HBM traffic drops several-fold exactly where the round-1 kernel
lost to XLA (hot windows were re-DMA'd per duplicate).  Uniform ids benefit
from the window packing alone.  The dedup's sort also pays for the
backward: the custom VJP segment-sums row gradients by the same inverse
map and scatter-adds each unique row once — no duplicate-index scatter
serialization.

**Measured on a real v5e chip (round 3, docs/BENCH_TPU_TUNE.json)**: v2
compiles and is bit-correct on hardware (tests/test_pallas_ctr.py compiled)
and the whole-step rate at the flagship shape (V=117,581, F=39, K=32) is
within a few percent of the XLA-gather path across batch sizes — e.g.
~170 µs vs ~135 µs at batch 1024, and at batch 4096 the fused kernel edges
XLA out (25.0M vs 23.4M ex/s).  At this vocab the 15 MB table is
VMEM-resident, so XLA's plain gather is already near-optimal and the step
is bounded by the fixed dense-Adam state update; the dedup design's real
payoff is the regime where the table does NOT fit fast memory (the
100M-row north star served by the lazy path, docs/BENCH_LARGE_VOCAB.json).
The default stays "off": XLA wins or ties at reference shapes, with
hardware evidence either way.

Only the gathered working set sits in VMEM, so the kernel scales to
vocabularies far beyond VMEM (the 100M-row north star) — the table stays in
HBM and is touched only near the gathered rows, exactly like the
parameter-server pull the reference does over grpc (README.md:15,63), but at
HBM-DMA latency instead of network latency.

Use ``fused_ctr_interaction`` (the custom-vjp wrapper).  On CPU the kernel
runs in Pallas interpret mode — the same code path CI exercises
deterministically (tests/test_pallas_ctr.py).  The default stays
``fused_kernel="off"`` per the recorded round-3 hardware evidence above
(bench.py measures both paths and reports the faster).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax < 0.5 spells the unconstrained/off-chip memory space ANY; newer jax
# added the explicit HBM alias this kernel targets
_HBM = getattr(pltpu, "HBM", pltpu.ANY)

_LANES = 128
_N_TILE = 1024          # gathered rows per grid step
_NSEM = 64              # DMA pipeline depth (copies in flight)


def _dedup_plan(flat_ids: jnp.ndarray, per_win: int):
    """XLA-side dedup: one sort over the flat id stream.

    Returns (uids, inv, valid, win, sel, first, dist, dma_rows) where all
    per-row arrays are padded to a ``_N_TILE`` multiple:

      uids    [N]  sorted unique row ids (pad slots hold a repeated id)
      inv     [n]  position of each original id in ``uids``
      valid   [N]  True for real unique slots (False for padding)
      win     [N]  window index per unique row
      sel     [N]  lane-offset selector (0..per_win-1)
      first   [N]  1 where the row's window differs from the previous row's
                   (or at a tile boundary) — exactly the rows the kernel DMAs
      dist    [N]  distance to the row's window source (for forward-fill)
      dma_rows[N]  per tile, at flat index base+d: the row-in-tile of the
                   d-th DMA — lets the kernel retire semaphores in order
    """
    n = flat_ids.shape[0]
    uids, inv, counts = jnp.unique(
        flat_ids, size=n, fill_value=0, return_inverse=True,
        return_counts=True,
    )
    pad = (-n) % _N_TILE
    total = n + pad
    if pad:
        uids = jnp.pad(uids, (0, pad), mode="edge")
        counts = jnp.pad(counts, (0, pad))
    valid = counts > 0
    win = (uids // per_win).astype(jnp.int32)
    sel = (uids % per_win).astype(jnp.int32)
    j = jnp.arange(total, dtype=jnp.int32)
    prev_win = jnp.concatenate([win[:1] - 1, win[:-1]])
    first = ((j % _N_TILE == 0) | (win != prev_win)).astype(jnp.int32)
    src = jax.lax.associative_scan(jnp.maximum, jnp.where(first == 1, j, -1))
    dist = (j - src).astype(jnp.int32)
    n_tiles = total // _N_TILE
    ft = first.reshape(n_tiles, _N_TILE)
    c = jnp.cumsum(ft, axis=1) - 1
    rows = jnp.broadcast_to(
        jnp.arange(_N_TILE, dtype=jnp.int32)[None], (n_tiles, _N_TILE)
    )
    dma_rows = (
        jnp.zeros((n_tiles, _N_TILE), jnp.int32)
        .at[jnp.arange(n_tiles)[:, None], jnp.where(ft == 1, c, _N_TILE)]
        .set(rows, mode="drop")
        .reshape(-1)
    )
    return uids, inv, valid, win, sel, first, dist, dma_rows


def _gather_unique_kernel(
    win_ref, first_ref, dma_rows_ref, sel_ref, dist_ref, table_ref, emb_ref,
    windows, sems, *, per_win,
):
    """Gather one tile of SORTED unique rows, one DMA per distinct window.

    win_ref/first_ref/dma_rows_ref: scalar-prefetch [N] int32 (see
    ``_dedup_plan``); sel_ref/dist_ref: [N_TILE, 1] int32 VMEM;
    table_ref: [V·K/LANES, LANES] f32 HBM (aligned-window view);
    emb_ref: out [N_TILE, K] f32 VMEM; windows: scratch [N_TILE, LANES];
    sems: [NSEM] DMA semaphores.
    """
    i = pl.program_id(0)
    base = i * _N_TILE
    k = emb_ref.shape[1]

    def dma(row, d):
        return pltpu.make_async_copy(
            table_ref.at[win_ref[base + row]],   # (LANES,) aligned window
            windows.at[row],
            sems.at[d % _NSEM],
        )

    def issue(j, cnt):
        f = first_ref[base + j]

        @pl.when(f == 1)
        def _():
            # retire the copy that used this semaphore slot NSEM DMAs ago,
            # then reuse the slot — keeps up to NSEM copies in flight
            @pl.when(cnt >= _NSEM)
            def _():
                dma(dma_rows_ref[base + cnt - _NSEM], cnt - _NSEM).wait()

            dma(j, cnt).start()

        return cnt + f

    total = jax.lax.fori_loop(0, _N_TILE, issue, jnp.int32(0))

    def drain(d, _):
        dma(dma_rows_ref[base + d], d).wait()
        return ()

    jax.lax.fori_loop(jnp.maximum(total - _NSEM, 0), total, drain, ())

    # forward-fill: propagate each DMA'd window down to the rows sharing it.
    # Sorted unique ids put same-window rows adjacent, so a real row's
    # source is at most per_win-1 rows back — ceil(log2(per_win)) passes.
    # At pass b, rows with dist in [2^b, 2^(b+1)) copy from a row whose own
    # dist < 2^b, i.e. already resolved.  (Rows with j < shift would wrap,
    # but their dist ≤ j < shift, so the mask never takes them.)
    w = windows[:]                                       # [N_TILE, LANES]
    d = dist_ref[:]                                      # [N_TILE, 1]
    for b in range(max(0, per_win - 1).bit_length()):
        s = 1 << b
        cand = pltpu.roll(w, shift=s, axis=0)
        w = jnp.where((d >= s) & (d < 2 * s), cand, w)

    # epilogue (VPU): pick the K-lane sub-window per row.  q is static per
    # branch, so roll shifts are static; the dynamic lane offset is resolved
    # by the masked select over LANES/K candidates.
    sel = sel_ref[:]                                     # [N_TILE, 1]
    e = jnp.zeros((_N_TILE, k), jnp.float32)
    for q in range(per_win):
        cand = pltpu.roll(w, shift=(-q * k) % _LANES, axis=1)[:, :k]
        e = jnp.where(sel == q, cand, e)
    emb_ref[:] = e


@functools.partial(jax.jit, static_argnames=("interpret",))
def _gather_unique(fm_v, win, sel, first, dist, dma_rows, *, interpret: bool):
    """Pallas gather of sorted unique rows: [V,K] + plan -> [N, K]."""
    v, k = fm_v.shape
    if _LANES % k:
        raise ValueError(f"embedding_size {k} must divide {_LANES}")
    per_win = _LANES // k

    # aligned-window view: pad rows to a window multiple, flatten, refold
    v_pad = (-v) % per_win
    table = fm_v if not v_pad else jnp.pad(fm_v, ((0, v_pad), (0, 0)))
    table = table.reshape(-1, _LANES)                    # [Vp·K/LANES, LANES]

    n = win.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,                           # win, first, dma_rows
        grid=(n // _N_TILE,),
        in_specs=[
            pl.BlockSpec((_N_TILE, 1), lambda i, *_: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_N_TILE, 1), lambda i, *_: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=_HBM),
        ],
        out_specs=pl.BlockSpec(
            (_N_TILE, k), lambda i, *_: (i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((_N_TILE, _LANES), jnp.float32),
            pltpu.SemaphoreType.DMA((_NSEM,)),
        ],
    )
    return pl.pallas_call(
        functools.partial(_gather_unique_kernel, per_win=per_win),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, k), jnp.float32),
        interpret=interpret,
    )(win, first, dma_rows, sel[:, None], dist[:, None], table)


# The dedup plan rides scalar-prefetch (SMEM, 1 MB): three int32 arrays of
# the flat id-stream length must fit, capping one kernel invocation at
# ~87k ids (measured: 160k ids over-subscribes SMEM 1.83M/1.00M).  Larger
# batches are mapped through the kernel in row chunks — FM terms and emb
# rows are independent per batch row, so chunking the batch axis is exact.
_MAX_FLAT_IDS = 65_536


def fused_ctr_interaction(fm_w, fm_v, ids, vals, interpret=False):
    """Fused gather + FM: (fm_w [V], fm_v [V,K], ids [B,F], vals [B,F]) ->
    (emb [B,F,K], y_w [B], y_v [B]).  emb is already vals-scaled (ps:212-214);
    y_w/y_v are the first/second-order FM terms (ps:207-217).  Out-of-range
    ids clip to [0, V-1] like ``jnp.take(mode='clip')``.  Batches whose flat
    id stream exceeds the SMEM plan budget are processed in row chunks via
    ``lax.map`` (dedup is then chunk-local; table cotangents accumulate
    across chunks in the scan)."""
    ids = ids.reshape(-1, ids.shape[-1])
    vals = vals.reshape(ids.shape)
    b, f = ids.shape
    rows_per_chunk = max(_MAX_FLAT_IDS // f, 1)
    if b <= rows_per_chunk:
        return _fused_chunk(fm_w, fm_v, ids, vals, interpret)
    pad = (-b) % rows_per_chunk
    if pad:
        ids = jnp.concatenate([ids, jnp.zeros((pad, f), ids.dtype)])
        vals = jnp.concatenate([vals, jnp.zeros((pad, f), vals.dtype)])
    emb, y_w, y_v = jax.lax.map(
        lambda iv: _fused_chunk(fm_w, fm_v, iv[0], iv[1], interpret),
        (ids.reshape(-1, rows_per_chunk, f), vals.reshape(-1, rows_per_chunk, f)),
    )
    k = emb.shape[-1]
    return emb.reshape(-1, f, k)[:b], y_w.reshape(-1)[:b], y_v.reshape(-1)[:b]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fused_chunk(fm_w, fm_v, ids, vals, interpret=False):
    """One SMEM-sized chunk of the fused gather+FM (see the public wrapper)."""
    out, _ = _forward(fm_w, fm_v, ids, vals, interpret)
    return out


def _forward(fm_w, fm_v, ids, vals, interpret):
    ids = ids.reshape(-1, ids.shape[-1])
    vals = vals.astype(jnp.float32)
    b, f = ids.shape
    v, k = fm_v.shape
    # clip in the incoming (possibly int64) dtype FIRST: casting an
    # unvalidated id >= 2**31 would wrap onto an arbitrary in-range row
    # before the clip could bound it (same contract as ops.embedding
    # narrow_ids)
    ids = jnp.clip(ids, 0, v - 1).astype(jnp.int32)
    flat = ids.reshape(-1)
    uids, inv, valid, win, sel, first, dist, dma_rows = _dedup_plan(
        flat, _LANES // k
    )
    rows_u = _gather_unique(
        fm_v, win, sel, first, dist, dma_rows, interpret=interpret
    )
    emb = rows_u[inv].reshape(b, f, k) * vals[..., None]
    # small gather + reductions stay in XLA: fused into one pass over emb
    w_rows = jnp.take(fm_w, ids, axis=0)
    y_w = jnp.sum(w_rows * vals, axis=1)
    sum_e = jnp.sum(emb, axis=1)
    y_v = 0.5 * jnp.sum(
        jnp.square(sum_e) - jnp.sum(jnp.square(emb), axis=1), axis=1
    )
    return (emb, y_w, y_v), (ids, uids, inv, valid, rows_u)


def _fused_fwd(fm_w, fm_v, ids, vals, interpret):
    out, (ids2d, uids, inv, valid, rows_u) = _forward(
        fm_w, fm_v, ids, vals, interpret
    )
    return out, (fm_w, fm_v, ids2d, vals, uids, inv, valid, rows_u)


def _fused_bwd(interpret, res, cotangents):
    """Backward in plain XLA, deduplicated: row grads are segment-summed by
    the forward's inverse map, so the table scatter-add touches each unique
    row once — no duplicate-index serialization on skewed ids."""
    fm_w, fm_v, ids, vals, uids, inv, valid, rows_u = res
    g_emb, g_yw, g_yv = cotangents
    v, k = fm_v.shape
    vals = vals.astype(jnp.float32)
    v_rows = rows_u[inv].reshape(*ids.shape, k)            # [B, F, K]
    e = v_rows * vals[..., None]
    sum_e = jnp.sum(e, axis=1)                             # [B, K]
    # ∂y_v/∂e_bfk = Σ_f' e_bf'k − e_bfk  (derivative of the FM identity)
    g_e = g_emb + g_yv[:, None, None] * (sum_e[:, None, :] - e)
    d_v_rows = g_e * vals[..., None]
    n_seg = uids.shape[0]
    d_u = jax.ops.segment_sum(
        d_v_rows.reshape(-1, k), inv, num_segments=n_seg
    )
    d_uw = jax.ops.segment_sum(
        (g_yw[:, None] * vals).reshape(-1), inv, num_segments=n_seg
    )
    scatter_idx = jnp.where(valid, uids, v)                # OOB pads drop
    d_fm_v = jnp.zeros_like(fm_v).at[scatter_idx].add(d_u, mode="drop")
    d_fm_w = jnp.zeros_like(fm_w).at[scatter_idx].add(d_uw, mode="drop")
    w_rows = jnp.take(fm_w, ids, axis=0)
    d_vals = jnp.sum(g_e * v_rows, axis=-1) + g_yw[:, None] * w_rows
    return d_fm_w, d_fm_v, None, d_vals.astype(vals.dtype)


_fused_chunk.defvjp(_fused_fwd, _fused_bwd)


def fused_kernel_available() -> bool:
    """True when the default backend can run the kernel compiled (TPU)."""
    from ..core.platform import is_tpu_backend

    return is_tpu_backend()


def resolve_fused(setting: str) -> bool:
    """Resolve ModelConfig.fused_kernel: "on" | "off" | "auto".

    "auto" enables the kernel on TPU backends only; "on" forces it (interpret
    mode on CPU — used by tests); "off" keeps the XLA gather path.
    """
    if setting == "on":
        return True
    if setting == "auto":
        return fused_kernel_available()
    return False
