"""Pallas TPU kernel: fused CTR embedding gather + FM interaction.

The DeepFM hot op (reference ps:206-217) is two HBM table gathers followed
by elementwise scaling and the FM reductions.  The bandwidth-dominant part —
the FM_V [V, K] row gather — is hand-scheduled here as a deep DMA pipeline;
the cheap parts (the [V] FM_W gather and the FM first/second-order
reductions) stay in XLA, which fuses them into single VPU passes over the
kernel's output.

Mosaic cannot DMA a K=32-float row at an arbitrary HBM offset (slices along
the minor dimension must be 128-lane tiles), so the kernel works on an
*aligned-window view* of the table:

    table  [V, K]  →  windows [V·K/128, 128]   (4 rows per window for K=32)
    row r lives in window r·K/128 at lane offset (r·K) mod 128

    per row  : DMA one 128-lane window HBM→VMEM, NSEM copies in flight
    per tile : epilogue selects the K-lane sub-window with static
               pltpu.roll + masked select, then scales by vals (VPU)

Only the gathered working set sits in VMEM, so the kernel scales to
vocabularies far beyond VMEM (the 100M-row north star) — the table stays in
HBM and is touched only near the gathered rows, exactly like the
parameter-server pull the reference does over grpc (README.md:15,63), but at
HBM-DMA latency instead of network latency.

Backward is a custom VJP in plain XLA (gather + scatter-add): the backward
of an embedding gather is a sparse scatter, which XLA already emits
optimally, so only the bandwidth-bound forward is hand-scheduled.

Measured on one v5e chip (batch 1024×39, V=117,581, K=32, full train step,
see bench.py): at parity with the XLA gather path on uniform ids (~100µs vs
~104µs/step) but ~2x slower on Zipf-skewed Criteo-like ids (~240µs), where
the same hot window is re-DMA'd thousands of times per batch while XLA's
native gather apparently exploits the duplicate locality.  Default is
therefore ``fused_kernel="off"``; bench.py measures both paths and reports
the faster, and "auto"/"on" opt in per run.

Use ``fused_ctr_interaction`` (the custom-vjp wrapper).  On CPU the kernel
runs in Pallas interpret mode — the same code path CI exercises
deterministically (tests/test_pallas_ctr.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_N_TILE = 1024          # gathered rows per grid step
_NSEM = 64             # DMA pipeline depth (copies in flight)


def _gather_kernel(win_ref, sel_ref, vals_ref, table_ref, emb_ref, windows, sems):
    """Gather one tile of rows as aligned 128-lane windows, then select+scale.

    win_ref:   scalar-prefetch [N] int32 — window index per gathered row
    sel_ref:   [N_TILE, 1] int32 VMEM — lane-offset selector (0..LANES/K-1)
    vals_ref:  [N_TILE, 1] f32 VMEM — per-row scale (feature values)
    table_ref: [V·K/LANES, LANES] f32 HBM — aligned-window view of FM_V
    emb_ref:   out [N_TILE, K] f32 VMEM — scaled gathered rows
    windows:   scratch [N_TILE, LANES] f32 VMEM
    sems:      [NSEM] DMA semaphores
    """
    i = pl.program_id(0)
    k = emb_ref.shape[1]

    def dma(n):
        return pltpu.make_async_copy(
            table_ref.at[win_ref[i * _N_TILE + n]],   # (LANES,) aligned window
            windows.at[n],
            sems.at[n % _NSEM],
        )

    def issue(n, _):
        # retire the copy that used this semaphore slot NSEM steps ago,
        # then reuse the slot — keeps NSEM copies in flight
        @pl.when(n >= _NSEM)
        def _():
            dma(n - _NSEM).wait()

        dma(n).start()
        return ()

    jax.lax.fori_loop(0, _N_TILE, issue, ())

    def drain(n, _):
        dma(n).wait()
        return ()

    jax.lax.fori_loop(_N_TILE - _NSEM, _N_TILE, drain, ())

    # epilogue (VPU): pick the K-lane sub-window per row, scale by vals.
    # q is static per branch, so roll shifts are static; the dynamic lane
    # offset is resolved by the masked select over LANES/K candidates.
    w = windows[:]                                       # [N_TILE, LANES]
    sel = sel_ref[:]                                     # [N_TILE, 1]
    e = jnp.zeros((_N_TILE, k), jnp.float32)
    for q in range(_LANES // k):
        cand = pltpu.roll(w, shift=(-q * k) % _LANES, axis=1)[:, :k]
        e = jnp.where(sel == q, cand, e)
    emb_ref[:] = e * vals_ref[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _gather_scaled(fm_v, ids, vals, *, interpret: bool):
    """Pallas path for ``scaled_embedding``: [V,K], [B,F], [B,F] -> [B,F,K]."""
    batch, f_size = ids.shape
    v, k = fm_v.shape
    if _LANES % k:
        raise ValueError(f"embedding_size {k} must divide {_LANES}")
    per_win = _LANES // k
    ids = jnp.clip(ids.astype(jnp.int32), 0, v - 1)

    # aligned-window view: pad rows to a window multiple, flatten, refold
    v_pad = (-v) % per_win
    table = fm_v if not v_pad else jnp.pad(fm_v, ((0, v_pad), (0, 0)))
    table = table.reshape(-1, _LANES)                    # [Vp·K/LANES, LANES]

    n = batch * f_size
    n_pad = (-n) % _N_TILE
    flat_ids = jnp.pad(ids.reshape(-1), (0, n_pad))
    flat_vals = jnp.pad(vals.astype(jnp.float32).reshape(-1), (0, n_pad))
    win = flat_ids // per_win
    sel = flat_ids % per_win

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=((n + n_pad) // _N_TILE,),
        in_specs=[
            pl.BlockSpec((_N_TILE, 1), lambda i, w: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_N_TILE, 1), lambda i, w: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.HBM),
        ],
        out_specs=pl.BlockSpec(
            (_N_TILE, k), lambda i, w: (i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((_N_TILE, _LANES), jnp.float32),
            pltpu.SemaphoreType.DMA((_NSEM,)),
        ],
    )
    emb_flat = pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n + n_pad, k), jnp.float32),
        interpret=interpret,
    )(win, sel[:, None], flat_vals[:, None], table)
    return emb_flat[:n].reshape(batch, f_size, k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_ctr_interaction(fm_w, fm_v, ids, vals, interpret=False):
    """Fused gather + FM: (fm_w [V], fm_v [V,K], ids [B,F], vals [B,F]) ->
    (emb [B,F,K], y_w [B], y_v [B]).  emb is already vals-scaled (ps:212-214);
    y_w/y_v are the first/second-order FM terms (ps:207-217)."""
    return _forward(fm_w, fm_v, ids, vals, interpret)


def _forward(fm_w, fm_v, ids, vals, interpret):
    ids = ids.reshape(-1, ids.shape[-1])
    vals = vals.astype(jnp.float32)
    emb = _gather_scaled(fm_v, ids, vals, interpret=interpret)
    # small gather + reductions stay in XLA: fused into one pass over emb
    w_rows = jnp.take(fm_w, jnp.clip(ids, 0, fm_w.shape[0] - 1), axis=0)
    y_w = jnp.sum(w_rows * vals, axis=1)
    sum_e = jnp.sum(emb, axis=1)
    y_v = 0.5 * jnp.sum(
        jnp.square(sum_e) - jnp.sum(jnp.square(emb), axis=1), axis=1
    )
    return emb, y_w, y_v


def _fused_fwd(fm_w, fm_v, ids, vals, interpret):
    out = _forward(fm_w, fm_v, ids, vals, interpret)
    return out, (fm_w, fm_v, ids, vals)


def _fused_bwd(interpret, res, cotangents):
    fm_w, fm_v, ids, vals = res
    g_emb, g_yw, g_yv = cotangents
    ids = jnp.clip(ids, 0, fm_v.shape[0] - 1)
    vals = vals.astype(jnp.float32)
    w_rows = jnp.take(fm_w, ids, axis=0)                   # [B, F]
    v_rows = jnp.take(fm_v, ids, axis=0)                   # [B, F, K]
    e = v_rows * vals[..., None]
    sum_e = jnp.sum(e, axis=1)                             # [B, K]
    # ∂y_v/∂e_bfk = Σ_f' e_bf'k − e_bfk  (derivative of the FM identity)
    g_e = g_emb + g_yv[:, None, None] * (sum_e[:, None, :] - e)
    d_v_rows = g_e * vals[..., None]
    flat_ids = ids.reshape(-1)
    d_fm_v = jnp.zeros_like(fm_v).at[flat_ids].add(
        d_v_rows.reshape(-1, fm_v.shape[1])
    )
    d_fm_w = jnp.zeros_like(fm_w).at[flat_ids].add(
        (g_yw[:, None] * vals).reshape(-1)
    )
    d_vals = jnp.sum(g_e * v_rows, axis=-1) + g_yw[:, None] * w_rows
    return d_fm_w, d_fm_v, None, d_vals.astype(vals.dtype)


fused_ctr_interaction.defvjp(_fused_fwd, _fused_bwd)


def fused_kernel_available() -> bool:
    """True when the default backend can run the kernel compiled (TPU)."""
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def resolve_fused(setting: str) -> bool:
    """Resolve ModelConfig.fused_kernel: "on" | "off" | "auto".

    "auto" enables the kernel on TPU backends only; "on" forces it (interpret
    mode on CPU — used by tests); "off" keeps the XLA gather path.
    """
    if setting == "on":
        return True
    if setting == "auto":
        return fused_kernel_available()
    return False
