"""Embedding lookup ops.

Dense (replicated-table) path for single-chip / small-vocab runs — the
``tf.nn.embedding_lookup`` capability (reference ps:206, ps:212).  The
row-sharded multi-chip lookup lives in ``deepfm_tpu/parallel/embedding.py``;
both expose the same ``lookup(table, ids) -> rows`` signature so models are
agnostic to the sharding strategy.
"""

from __future__ import annotations

import jax.numpy as jnp


def dense_lookup(table: jnp.ndarray, ids: jnp.ndarray) -> jnp.ndarray:
    """Gather rows: table [V] or [V, K], ids [B, F] -> [B, F] or [B, F, K].

    ``mode="clip"`` matches XLA:TPU's in-bounds guarantee while keeping the
    op fully vectorizable (no dynamic bounds checks in the hot path).
    """
    return jnp.take(table, ids, axis=0, mode="clip")


def scaled_embedding(
    table: jnp.ndarray, ids: jnp.ndarray, vals: jnp.ndarray
) -> jnp.ndarray:
    """``e_ij = V[id_ij] * x_ij`` — the FM input tensor (ps:212-214).

    table [V, K], ids [B, F], vals [B, F] -> [B, F, K].
    """
    return dense_lookup(table, ids) * vals[..., None]
