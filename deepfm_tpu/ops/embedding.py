"""Embedding lookup ops.

Dense (replicated-table) path for single-chip / small-vocab runs — the
``tf.nn.embedding_lookup`` capability (reference ps:206, ps:212).  The
row-sharded multi-chip lookup lives in ``deepfm_tpu/parallel/embedding.py``;
both expose the same ``lookup(table, ids) -> rows`` signature so models are
agnostic to the sharding strategy.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.config import packed_sort_id_bound

# TPU has no native 64-bit integer datapath: int64 index arithmetic runs on
# an emulated 32-bit-pair representation and int64 gather/scatter indices
# double the index traffic and can force slower lowerings.  Any vocabulary
# that fits int32 should index with int32 on device.
_INT32_MAX_ROWS = 2**31 - 1


def narrow_ids(ids, vocab_size: int, enabled: bool = True):
    """Cast int64 ids to int32 when every row of a ``vocab_size``-row table
    is addressable in 32 bits.  Works on host numpy arrays (cast before the
    device transfer — halves the id bytes moved) and on traced/device
    arrays (a cheap elementwise op XLA fuses away).  No-op for int32 input,
    an int32-unsafe vocabulary, or ``enabled=False``
    (``ModelConfig.narrow_ids``, the ablation switch).

    The dense path does NOT validate ids before this cast (train/step.py
    feeds raw batch ids straight in), so a stray id >= 2**31 would WRAP
    under a bare ``astype(int32)`` and land on an arbitrary in-range row.
    Ids are therefore clipped to ``[0, vocab_size - 1]`` before casting —
    exactly the row the downstream clip-mode gather (``dense_lookup``)
    would have produced for the original int64 value, so the cast stays a
    pure representation change for every input."""
    if enabled and ids.dtype == np.int64 and vocab_size <= _INT32_MAX_ROWS:
        return ids.clip(0, vocab_size - 1).astype(np.int32)
    return ids


def dense_lookup(table: jnp.ndarray, ids: jnp.ndarray) -> jnp.ndarray:
    """Gather rows: table [V] or [V, K], ids [B, F] -> [B, F] or [B, F, K].

    ``mode="clip"`` matches XLA:TPU's in-bounds guarantee while keeping the
    op fully vectorizable (no dynamic bounds checks in the hot path).
    """
    return jnp.take(table, ids, axis=0, mode="clip")


def scaled_embedding(
    table: jnp.ndarray, ids: jnp.ndarray, vals: jnp.ndarray
) -> jnp.ndarray:
    """``e_ij = V[id_ij] * x_ij`` — the FM input tensor (ps:212-214).

    table [V, K], ids [B, F], vals [B, F] -> [B, F, K].
    """
    return dense_lookup(table, ids) * vals[..., None]


def sort_segments(flat_ids: jnp.ndarray, id_bound: int | None = None):
    """Sort ids and describe the equal-id runs.

    Returns ``(order, seg, row_id, valid)``: ``order`` sorts the ids,
    ``seg[p]`` is the segment index of sorted position p, ``row_id[s]`` the
    id shared by segment s, ``valid[s]`` whether segment s exists (segments
    form a prefix).  One structure serves every table gathered with the
    same ids (the lazy-Adam update, the segsum backward below, and the
    all-to-all shard exchange's routing plan, parallel/embedding.py).

    ``id_bound`` is the caller's STATIC promise that every id lies in
    ``[0, id_bound)``.  It unlocks the packed single-key sort: XLA's
    comparator sort pays ~4x for a variadic (key, payload) sort vs one
    scalar key, and the sort is the dominant cost of every dedup path on
    CPU/TPU.  When ``bits(id_bound) + ceil(log2 n)`` fits 32 bits, the
    (id, position) pair packs losslessly into ONE uint32 key — the
    position in the low bits tie-breaks ascending, i.e. exactly the
    stable argsort permutation — so one single-key unsigned sort yields
    both the sorted ids and the order.  (uint32 needs no jax x64 mode; an
    int64 packing would silently TRUNCATE with x64 off.)  Without the
    bound, or when it does not fit (e.g. huge-vocab streams), the general
    variadic argsort runs instead — the flagship shape V=117,581 with
    B_local*F ~= 20k packs exactly (17 + 15 bits).  The fit test is
    ``core.config.packed_sort_id_bound`` — ONE definition shared with the
    config-time validation that warns when a vocab/batch shape would
    silently demote every dedup sort to the slow path.  Tiered-embedding
    cache-probe streams (deepfm_tpu/tiered) always fit: their ids are
    SLOTS bounded by the hot-cache capacity, not the vocabulary."""
    n = flat_ids.shape[0]
    shift = max(1, int(n - 1).bit_length()) if n > 1 else 1
    if (
        flat_ids.dtype == jnp.int32
        and id_bound is not None
        and n > 1
        and id_bound <= packed_sort_id_bound(n)
    ):
        key = (flat_ids.astype(jnp.uint32) << shift) | jnp.arange(
            n, dtype=jnp.uint32
        )
        skey = jnp.sort(key)
        order = (skey & ((1 << shift) - 1)).astype(jnp.int32)
        sid = (skey >> shift).astype(jnp.int32)  # logical shift: unsigned
    else:
        order = jnp.argsort(flat_ids)
        sid = flat_ids[order]
    first = jnp.concatenate([jnp.ones((1,), bool), sid[1:] != sid[:-1]])
    seg = jnp.cumsum(first) - 1
    row_id = jnp.zeros((n,), sid.dtype).at[seg].set(
        sid, indices_are_sorted=True
    )
    valid = jnp.arange(n) < jnp.sum(first)
    return order, seg, row_id, valid


def _segsum_meta(table) -> tuple:
    return (tuple(table.shape), str(table.dtype))


def _segsum_impl(meta, table, ids):
    return jnp.take(table, ids, axis=0, mode="clip")


def _segsum_fwd(meta, table, ids):
    return _segsum_impl(meta, table, ids), ids


def _segsum_bwd(meta, ids, g):
    import jax

    shape, dtype = meta
    rows, tail = shape[0], tuple(shape[1:])
    flat_ids = ids.reshape(-1)
    n = flat_ids.shape[0]
    flat_g = g.reshape((n,) + tail)
    # collapse out-of-range ids onto the single sentinel ``rows`` BEFORE
    # the sort: their cotangents were always dropped (the write below is
    # mode="drop"), and the bounded non-negative stream unlocks the
    # packed single-key sort
    flat_ids = jnp.where(
        (flat_ids >= 0) & (flat_ids < rows), flat_ids,
        jnp.asarray(rows, flat_ids.dtype),
    )
    order, seg, row_id, valid = sort_segments(flat_ids, rows + 1)
    summed = jax.ops.segment_sum(
        flat_g[order], seg, num_segments=n, indices_are_sorted=True
    )
    # one write per UNIQUE row; empty segments target distinct out-of-range
    # rows (rows + position) so the index vector stays sorted AND unique —
    # XLA can emit a vectorized scatter instead of a serialized one
    if rows + n - 1 <= jnp.iinfo(row_id.dtype).max:
        write = jnp.where(
            valid, row_id, rows + jnp.arange(n, dtype=row_id.dtype)
        )
        grad = jnp.zeros((rows,) + tail, dtype).at[write].add(
            summed.astype(dtype), indices_are_sorted=True,
            unique_indices=True, mode="drop",
        )
    else:
        # the sentinel run rows..rows+n-1 would overflow the id dtype, and
        # NO out-of-range sentinel is representable at all: .at[] wraps
        # negative indices python-style (mode="drop" only drops >= rows,
        # it does not drop negatives).  So route invalid segments at row 0
        # and zero their contributions EXPLICITLY — segment_sum already
        # leaves empty segments at 0, but masking here keeps correctness
        # independent of that invariant.  Forfeits the sorted+unique
        # scatter hint; only reachable when the table ends within B*F
        # rows of the dtype max, so the slow scatter is a non-issue.
        mask = valid.reshape((n,) + (1,) * len(tail))
        write = jnp.where(valid, row_id, jnp.array(0, row_id.dtype))
        grad = jnp.zeros((rows,) + tail, dtype).at[write].add(
            jnp.where(mask, summed.astype(dtype), 0), mode="drop",
        )
    import numpy as _np

    return grad, _np.zeros(ids.shape, jax.dtypes.float0)


def _make_segsum_call():
    import functools

    import jax

    call = jax.custom_vjp(_segsum_impl, nondiff_argnums=(0,))
    call.defvjp(_segsum_fwd, _segsum_bwd)
    return call


_SEGSUM_CALL = _make_segsum_call()


def segsum_lookup(table: jnp.ndarray, ids: jnp.ndarray) -> jnp.ndarray:
    """``dense_lookup`` with a sort+segment-sum backward.

    The gather's default VJP is a scatter-add with one update per LOOKUP
    (B·F of them, duplicate rows colliding) — the pattern XLA:TPU
    serializes, measured at ~9-16 ms/step for the flagship shape (round-5
    finding, docs/TPU_REPORT.md).  This variant's backward sorts the ids
    once, segment-sums duplicate rows' cotangents, and issues ONE
    sorted-unique write per distinct row — the same dedup structure the
    lazy-Adam update uses (train/lazy.py).  Forward is identical
    (clip-mode gather); select with ``ModelConfig.table_grad='segsum'``.

    Numerical note: duplicate rows' contributions are summed in sorted-id
    order instead of scatter order; f32 addition reorders, so gradients
    match the scatter backward to float tolerance, not bit-exactly
    (tests/test_segsum_grad.py)."""
    return _SEGSUM_CALL(_segsum_meta(table), table, ids)
