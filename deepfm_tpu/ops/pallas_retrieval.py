"""Fused int8 score + running top-K for the quantized retrieval tier.

The quantized funnel scorer (funnel/index.py ``retrieval_mode="int8"``)
never materializes the per-shard ``[B_local, rows_local]`` score tensor:
the item codes stream through in row tiles and a per-query top-(K·os)
accumulator is merged after every tile, so the only f32 live at any point
is tile-sized — the FlashAttention shape applied to top-k selection
(arxiv 2205.14135): tile, score, select, carry ``[B, K·os]`` forward.

Two implementations share that contract:

* :func:`score_topk_tiles` — the lax composition (unrolled tile loop,
  ``lax.top_k`` merge).  This is the portable path; it is what the
  trace audit proves corpus-f32-free and what CPU hosts (and the bench's
  2·10⁶-row synthetic corpus) run.  Three measured facts shape it:
  (1) the dequantize must happen IN FLIGHT — the broadcast multiply-
  reduce ``sum(u[:,None,:] * codes.astype(f32), -1)`` fuses the int8
  load, convert and MAC into one pass (reads 1 byte/element where the
  exact matmul reads 4), while an explicit ``codes.astype(f32)`` before
  a dot materializes the f32 copy and LOSES to the exact matmul (so do
  int8·int8→int32 dots: XLA:CPU emits scalar int8 MACs); (2) the tile
  loop is a python loop over ``dynamic_slice``, not ``lax.scan`` — the
  scan's per-step carry shuffling on XLA:CPU costs ~2× the whole
  scoring pass; (3) ``lax.top_k`` over the raw tile dominates
  (~60 ns/element on CPU), so selection is screened by group maxima:
  rows tile in groups of ``screen_group``, the top-``kos`` GROUPS by
  group max provably contain the top-``kos`` rows (each selected group
  holds a row scoring >= any excluded row), and only ``kos ·
  screen_group`` candidates reach a ``top_k``.  At 2·10⁶ rows, D=32,
  B=8 this composition beats the exact matmul + full top-k ~1.6×.
* :func:`retrieval_topk_kernel` — the Pallas TPU kernel: same tiling,
  but the accumulator lives in VMEM scratch across grid steps and only
  the final ``[B, K·os]`` pair is written back — the score row never
  round-trips HBM at all.  Gated exactly like ``fused_kernel``
  (``resolve_retrieval_kernel``: on | off | auto) with a compile-probe
  fallback (:func:`retrieval_kernel_lowers`) to the lax composition, so
  a Mosaic lowering gap degrades to the portable path instead of failing
  the boot.

Both return ``(scores [B, kos] f32, rows [B, kos] i32)`` sorted by
(-score, row): ``lax.top_k`` keeps the earlier input index on ties, the
accumulator is ordered ahead of each tile, and tiles arrive in row order
— so ties break toward the smaller local row at every merge, matching
the exact path's lexicographic contract.  Rows carrying score ``-inf``
(masked pads, or slots past the corpus) hold meaningless row indices; the
caller masks on the score before trusting them.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# scan tile for the lax composition: large tiles amortize the per-tile
# screen + merge (measured on CPU at 2M rows, D=32: 128Ki edges out 64Ki
# and 256Ki).  The Pallas kernel tiles much smaller — its tile must fit
# VMEM next to the accumulator.
DEFAULT_SCAN_TILE = 131072
DEFAULT_KERNEL_TILE = 2048

# rows per screening group, and the unroll budget for the tile loop (past
# it the tile grows instead, keeping the traced program bounded)
DEFAULT_SCREEN_GROUP = 128
_MAX_UNROLL = 64

_NEG_INF = jnp.float32(-jnp.inf)


def _tiled(codes, scales, ids, tile: int):
    """Pad the per-shard arrays to a tile multiple (pad rows id=-1,
    scale 0 — indistinguishable from index pad rows) and reshape to
    ``[n_tiles, tile, ...]``.  int8/i32/f32-vector ops only: nothing
    corpus-sized is ever f32-2D here."""
    rows = codes.shape[0]
    pad = (-rows) % tile
    if pad:
        codes = jnp.pad(codes, ((0, pad), (0, 0)))
        scales = jnp.pad(scales, (0, pad))
        ids = jnp.pad(ids, (0, pad), constant_values=-1)
    nt = (rows + pad) // tile
    return (codes.reshape(nt, tile, codes.shape[1]),
            scales.reshape(nt, tile), ids.reshape(nt, tile), nt)


def score_topk_tiles(u, codes, scales, ids, *, kos: int,
                     tile: int = DEFAULT_SCAN_TILE,
                     screen_group: int = DEFAULT_SCREEN_GROUP):
    """The lax composition: stream row tiles of the int8 corpus, keep a
    running per-query top-``kos``.

    ``u [B, D] f32`` (full-precision queries — asymmetric scoring, the
    ScaNN shape), ``codes [R, D] i8``, ``scales [R] f32``, ``ids [R]
    i32`` (< 0 marks pad rows).  Returns ``(scores [B, kos], rows [B,
    kos])`` with rows as LOCAL row indices.

    Selection is EXACT despite the screening (see module docstring):
    the top-``kos`` groups by group max must contain the top-``kos``
    rows, and because groups are contiguous ascending row ranges and
    ``lax.top_k`` keeps the earlier index on ties, a group winning a
    group-max tie holds only smaller rows than the loser — the
    smaller-row tie-break survives the screen.  Tiles whose size the
    group does not divide (or too small to be worth screening) take the
    plain whole-tile ``top_k``."""
    b = u.shape[0]
    rows = codes.shape[0]
    t = max(1, min(int(tile), rows))
    gr = max(1, int(screen_group))
    if -(-rows // t) > _MAX_UNROLL:
        # grow the tile (rounded up to a group multiple) instead of
        # unrolling an unbounded loop into the traced program
        t = -(-rows // _MAX_UNROLL)
        t = -(-t // gr) * gr
    pad = (-rows) % t
    if pad:
        codes = jnp.pad(codes, ((0, pad), (0, 0)))
        scales = jnp.pad(scales, (0, pad))
        ids = jnp.pad(ids, (0, pad), constant_values=-1)
    nt = (rows + pad) // t
    screen = gr > 1 and t % gr == 0 and (t // gr) >= 2 * kos
    ng = t // gr if screen else 0

    acc_s = jnp.full((b, kos), _NEG_INF, jnp.float32)
    acc_r = jnp.zeros((b, kos), jnp.int32)
    for step in range(nt):
        c = lax.dynamic_slice_in_dim(codes, step * t, t)       # [t, D] i8
        sc = lax.dynamic_slice_in_dim(scales, step * t, t)
        ii = lax.dynamic_slice_in_dim(ids, step * t, t)
        # dequantize in flight: the convert fuses into the reduce, so
        # the scoring pass reads int8 and the largest f32 it produces
        # is the [B, t] tile score (the audit's no-corpus-f32 contract)
        s = jnp.sum(u[:, None, :] * c[None, :, :].astype(jnp.float32),
                    axis=2)                                    # [B, t]
        s = jnp.where(ii[None, :] >= 0, s * sc[None, :], _NEG_INF)
        if screen:
            sg = s.reshape(b, ng, gr)
            gmax = sg.max(axis=2)
            _, gi = lax.top_k(gmax, kos)                       # [B, kos]
            # ascending group order = ascending row order, restoring
            # the smaller-row preference for the candidate top_k
            gi = jnp.sort(gi, axis=1)
            cand = jnp.take_along_axis(
                sg, gi[:, :, None], axis=1
            ).reshape(b, kos * gr)
            crow = (
                gi[:, :, None] * gr
                + jnp.arange(gr, dtype=jnp.int32)[None, None, :]
            ).reshape(b, kos * gr)
            s_t, ci = lax.top_k(cand, kos)
            r_t = jnp.take_along_axis(crow, ci, axis=1) + step * t
        else:
            s_t = s
            r_t = jnp.broadcast_to(
                step * t + jnp.arange(t, dtype=jnp.int32), (b, t)
            )
        # top_k keeps the earlier input position on ties: accumulator
        # entries (all smaller rows) sit ahead of the tile, so the
        # smaller-row tie-break holds inductively across tiles
        cat_s = jnp.concatenate([acc_s, s_t], axis=1)
        cat_r = jnp.concatenate([acc_r, r_t], axis=1)
        acc_s, idx = lax.top_k(cat_s, kos)
        acc_r = jnp.take_along_axis(cat_r, idx, axis=1)
    return acc_s, acc_r


# ---------------------------------------------------------------------------
# the Pallas fused kernel

def _retrieval_kernel_body(u_ref, codes_ref, scales_ref, ids_ref,
                           s_out, r_out, acc_s, acc_r, *, tile, kos):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_s[...] = jnp.full(acc_s.shape, -jnp.inf, jnp.float32)
        acc_r[...] = jnp.zeros(acc_r.shape, jnp.int32)

    # dequantize the VMEM-resident tile and score it against the (small,
    # replicated) query block; f32 MACs — the HBM win is the int8 stream,
    # not the multiplier width (see module docstring)
    t_f32 = codes_ref[...].astype(jnp.float32) * scales_ref[...]   # [t, D]
    s = jnp.dot(u_ref[...], t_f32.T,
                preferred_element_type=jnp.float32)                # [B, t]
    ii = ids_ref[...].reshape(1, tile)
    s = jnp.where(ii >= 0, s, -jnp.inf)
    b = s.shape[0]
    r = i * tile + lax.broadcasted_iota(jnp.int32, (b, tile), 1)
    cat_s = jnp.concatenate([acc_s[...], s], axis=1)
    cat_r = jnp.concatenate([acc_r[...], r], axis=1)
    s2, idx = lax.top_k(cat_s, kos)
    acc_s[...] = s2
    acc_r[...] = jnp.take_along_axis(cat_r, idx, axis=1)

    @pl.when(i == pl.num_programs(0) - 1)
    def _done():
        s_out[...] = acc_s[...]
        r_out[...] = acc_r[...]


@functools.partial(
    jax.jit, static_argnames=("kos", "tile", "interpret")
)
def retrieval_topk_kernel(u, codes, scales, ids, *, kos: int,
                          tile: int = DEFAULT_KERNEL_TILE,
                          interpret: bool = False):
    """Fused score + running top-``kos`` as one ``pallas_call``: the item
    tiles pipeline HBM→VMEM, the accumulator persists in VMEM scratch
    across the (sequential) grid, and only ``[B, kos]`` writes back.

    Same signature and return contract as :func:`score_topk_tiles` —
    the two are interchangeable behind ``build_retrieve_with``."""
    b, d = u.shape
    t = max(1, min(tile, codes.shape[0]))   # both static under jit
    codes_t, scales_t, ids_t, nt = _tiled(codes, scales, ids, t)
    codes_p = codes_t.reshape(nt * t, d)
    scales_p = scales_t.reshape(nt * t, 1)
    ids_p = ids_t.reshape(nt * t, 1)
    kernel = functools.partial(_retrieval_kernel_body, tile=t, kos=kos)
    return pl.pallas_call(
        kernel,
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((b, d), lambda i: (0, 0)),
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((b, kos), lambda i: (0, 0)),
            pl.BlockSpec((b, kos), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, kos), jnp.float32),
            jax.ShapeDtypeStruct((b, kos), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, kos), jnp.float32),
            pltpu.VMEM((b, kos), jnp.int32),
        ],
        interpret=interpret,
    )(u, codes_p, scales_p, ids_p)


# ---------------------------------------------------------------------------
# gating (the resolve_fused idiom, ops/pallas_ctr.py)

def retrieval_kernel_available() -> bool:
    """True when the default backend can run the kernel compiled (TPU)."""
    from ..core.platform import is_tpu_backend

    return is_tpu_backend()


def resolve_retrieval_kernel(setting: str) -> bool:
    """Resolve the ``funnel_pallas`` knob: "on" | "off" | "auto".

    "auto" engages the kernel on TPU backends only; "on" forces it
    (interpret mode off-TPU — tests drive that path); "off" keeps the
    lax composition."""
    if setting == "on":
        return True
    if setting == "auto":
        return retrieval_kernel_available()
    return False


@functools.lru_cache(maxsize=32)
def retrieval_kernel_lowers(b: int, d: int, rows: int, kos: int,
                            tile: int) -> bool:
    """Compile-probe the kernel at one shard shape.  A Mosaic gap (an op
    the TPU lowering lacks, a tiling it refuses) answers False and the
    builder falls back to the lax composition — the knob degrades, the
    boot never fails on it."""
    try:
        jax.jit(
            lambda u, c, s, i: retrieval_topk_kernel(
                u, c, s, i, kos=kos, tile=tile,
                interpret=not retrieval_kernel_available(),
            )
        ).lower(
            jax.ShapeDtypeStruct((b, d), jnp.float32),
            jax.ShapeDtypeStruct((rows, d), jnp.int8),
            jax.ShapeDtypeStruct((rows,), jnp.float32),
            jax.ShapeDtypeStruct((rows,), jnp.int32),
        ).compile()
        return True
    # da:allow[swallowed-exception] capability probe: an uncompilable kernel means "use the lax fallback", not an error
    except Exception:
        return False
