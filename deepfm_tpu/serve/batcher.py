"""Dynamic micro-batching serving engine.

The TF-Serving role the reference hands its SavedModel to (ps:535-551)
includes a *batching config*: concurrent predict requests coalesce into
shared device dispatches.  The first cut of that here (the round-3
``BatchingScorer``) coalesced by backpressure only and still pushed every
coalesced batch through ONE fixed padded shape — a 3-row request paid
full-batch compute, and the single global executable shape was chosen for
the largest expected batch, not the live traffic.

This module is the full engine, GSPMD-style thinking applied to serving
(pick the executable shape per workload instead of one shape for all):

* **Bucketed executables.**  Requests coalesce into padded power-of-two
  buckets (default 8/32/128/512, configurable).  Each bucket shape is a
  separate XLA executable — :meth:`MicroBatcher.precompile` compiles all
  of them at startup so no live request ever pays a compile.  A dispatch
  pads only up to the smallest bucket that fits, so light traffic runs
  small fast shapes and bursts run big ones.
* **Admission timeout.**  A lone request is not held hostage waiting for
  a full bucket: the batcher thread waits at most ``max_wait_ms`` past
  the oldest queued request's arrival before flushing whatever is queued,
  and stops waiting as soon as the SMALLEST bucket is full — a flushable
  batch in hand beats idling the device for more coalescing, since the
  next dispatch's own duration is itself a coalescing window (arrivals
  pile up while the device is busy).  Worst-case added idle latency is
  exactly ``max_wait_ms``.
* **Bounded queue + backpressure.**  Beyond ``max_queue_rows`` queued rows
  callers fail fast with :class:`OverloadedError` (mapped to HTTP 503 by
  the server) instead of growing an unbounded backlog.  The bound sheds
  BACKLOG, not request size: a request bigger than the bound is admitted
  when the queue is idle (it chunks through the largest bucket).
* **Metrics.**  Request/row/dispatch counters, a per-bucket batch-size
  histogram, live queue depth, and p50/p95/p99 end-to-end latency over a
  sliding window — all held in the shared observability registry
  (obs/metrics.py), served as the same ``/v1/metrics`` JSON document as
  ever (schema-pinned) and scrape-able via ``GET /metrics`` in
  Prometheus text format (serve/server.py).
* **Tracing.**  When the calling thread carries an active trace context
  (obs/trace.py — set by the HTTP handler), the engine records
  per-request spans: queue wait and each device dispatch (bucket chosen,
  rows coalesced, padding).  Timers wrap the dispatch boundary on the
  host — nothing is ever recorded inside the jitted executable
  (``audit_observability`` pins this).

Correctness invariants: shape validation happens on the *caller's* thread
(a malformed request fails alone, never poisoning a batch); per-request
output slices fan back to the right caller; a runtime failure fails every
request in that dispatch, and the worker keeps serving.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Sequence

import numpy as np

from ..obs.metrics import MetricsRegistry
from ..obs.trace import current_trace
from .control.admission import DeadlineExpiredError


class OverloadedError(RuntimeError):
    """Queue depth exceeded: the engine sheds load instead of growing an
    unbounded backlog (mapped to HTTP 503/429-style rejection upstream)."""


# the serving engine's default executable shapes — THE definition every
# consumer (MicroBatcher, load_batching_servable, the trace-time recompile
# audit) imports, so changing it here re-points the audit automatically
DEFAULT_BUCKETS = (8, 32, 128, 512)


def admission_starts(rows: int, cap: int) -> range:
    """Chunk offsets ``score()`` splits an admitted request at (each chunk
    <= ``cap`` rows).  Shared with the recompile audit: the audit's notion
    of "admissible dispatch size" is derived from this exact split."""
    return range(0, rows, cap)


def pick_bucket(buckets: Sequence[int], rows: int) -> int:
    """Smallest bucket that fits ``rows`` (the largest one for oversized
    batches, which the admission path has already chunked down to it).

    Module-level on purpose: this IS the engine's executable-shape map, and
    the trace-time recompile audit (analysis/trace_audit.py) imports it to
    prove every admissible request shape lands on a precompiled bucket."""
    for b in buckets:
        if rows <= b:
            return b
    return buckets[-1]


def instances_to_arrays(
    instances: list[dict],
) -> tuple[np.ndarray, np.ndarray]:
    """JSON ``instances`` rows -> ([N, F] int64 ids, [N, F] f32 vals).

    Malformed rows raise ``ValueError`` with a row-indexed message (the
    server maps ValueError to HTTP 400 — a client's bad request must never
    read as a 500 outage)."""
    ids_rows, val_rows = [], []
    for n, inst in enumerate(instances):
        if not isinstance(inst, dict):
            raise ValueError(
                f"instances[{n}] is {type(inst).__name__}, expected an "
                f"object with 'feat_ids' and 'feat_vals'"
            )
        missing = [k for k in ("feat_ids", "feat_vals") if k not in inst]
        if missing:
            raise ValueError(
                f"instances[{n}] is missing {missing} (has "
                f"{sorted(inst)})"
            )
        ids_rows.append(inst["feat_ids"])
        val_rows.append(inst["feat_vals"])
    try:
        ids = np.asarray(ids_rows, np.int64)
        vals = np.asarray(val_rows, np.float32)
    except (ValueError, TypeError) as e:
        raise ValueError(
            f"instances rows are ragged or non-numeric: {e}"
        ) from None
    return ids, vals


def check_features(ids: np.ndarray, vals: np.ndarray, fields: int) -> None:
    """Reject malformed [N, F] pairs with one shared message shape."""
    if ids.ndim != 2 or ids.shape[1] != fields:
        raise ValueError(f"expected [N, {fields}] features, got {ids.shape}")
    if vals.shape != ids.shape:
        raise ValueError(
            f"feat_vals shape {vals.shape} != feat_ids shape {ids.shape}"
        )


class _Metrics:
    """Engine counters + sliding latency window, held in the shared
    observability registry (obs/metrics.py).

    Families are labeled ``engine=<name>`` so one registry carries many
    engines (the two-tower scorer's user/item pair, a funnel member's
    recommend engine); ``snapshot()`` re-renders the SAME ``/v1/metrics``
    JSON document the pre-registry counters produced — the schema is
    pinned by tests — while ``GET /metrics`` scrapes the registry
    directly."""

    def __init__(self, buckets: Sequence[int], *, name: str = "predict",
                 registry: MetricsRegistry | None = None,
                 window: int = 4096):
        self.registry = registry if registry is not None else MetricsRegistry()
        r = self.registry
        self._requests = r.counter(
            "deepfm_serve_requests_total",
            "requests admitted to the micro-batching engine",
            labels=("engine",),
        ).labels(name)
        self._rows = r.counter(
            "deepfm_serve_rows_total", "rows admitted", labels=("engine",),
        ).labels(name)
        self._rejected = r.counter(
            "deepfm_serve_rejected_total",
            "requests shed by queue backpressure", labels=("engine",),
        ).labels(name)
        self._padded = r.counter(
            "deepfm_serve_padded_rows_total",
            "dispatched minus real rows (padding waste)",
            labels=("engine",),
        ).labels(name)
        dispatches = r.counter(
            "deepfm_serve_dispatches_total",
            "device dispatches by bucket shape",
            labels=("engine", "bucket"),
        )
        # pre-create every bucket child so the histogram renders zeros
        # (the pinned batch_size_hist schema lists all buckets up front)
        self._dispatch_by_bucket = {
            int(b): dispatches.labels(name, str(int(b))) for b in buckets
        }
        self._latency = r.histogram(
            "deepfm_serve_latency_seconds",
            "end-to-end request latency through the engine",
            labels=("engine",), window=window,
        ).labels(name)
        self._expired = r.counter(
            "deepfm_serve_expired_total",
            "requests whose deadline passed while queued (answered 504 "
            "at dequeue, never dispatched)", labels=("engine",),
        ).labels(name)

    def record_admit(self, rows: int) -> None:
        self._requests.inc()
        self._rows.inc(rows)

    def record_reject(self) -> None:
        self._rejected.inc()

    def record_dispatch(self, bucket: int, rows: int) -> None:
        self._padded.inc(bucket - rows)
        self._dispatch_by_bucket[bucket].inc()

    def record_expired(self) -> None:
        self._expired.inc()

    def record_latency(self, seconds: float) -> None:
        self._latency.observe(seconds)

    def snapshot(self) -> dict:
        hist = {
            str(b): int(c.value)
            for b, c in sorted(self._dispatch_by_bucket.items())
        }
        return {
            "requests_total": int(self._requests.value),
            "rows_total": int(self._rows.value),
            "dispatches_total": sum(hist.values()),
            "padded_rows_total": int(self._padded.value),
            "rejected_total": int(self._rejected.value),
            "expired_total": int(self._expired.value),
            "batch_size_hist": hist,
            "latency_ms": self._latency.snapshot(include_max=True),
        }


class _Request:
    """One caller's submission: output assembled from dispatch slices."""

    __slots__ = ("rows", "out", "remaining", "done", "error", "t_submit",
                 "trace", "t_dispatch")

    def __init__(self, rows: int, chunks: int, trace=None):
        self.rows = rows
        self.out: np.ndarray | None = None   # allocated on first slice
        self.remaining = chunks
        self.done = threading.Event()
        self.error: BaseException | None = None
        self.t_submit = time.perf_counter()
        # the caller's trace context (obs/trace.py), captured on the
        # submitting thread so the dispatch thread can attach spans
        self.trace = trace
        self.t_dispatch: float | None = None  # first dispatch start


class MicroBatcher:
    """Continuous micro-batching front over a jitted ``fn(ids, vals)``.

    ``fn`` maps ([B, F] int64 ids, [B, F] f32 vals) to [B] or [B, D]
    outputs for any B; the engine only ever calls it at the bucket shapes,
    so exactly ``len(buckets)`` XLA executables exist (precompiled via
    :meth:`precompile`).  Same call surface as the old ``Scorer``
    (``score`` / ``score_instances``) so handlers and benchmarks swap
    engines freely.

    With an :class:`~.control.admission.AdmissionController` attached the
    engine additionally prices every arrival against its deadline
    (explicit ``deadline_s`` — the ``X-Deadline-Ms`` header made
    absolute — or the controller's config default) BEFORE it occupies
    queue slots, sheds by priority class under sustained saturation, and
    answers 504 at dequeue for requests whose deadline passed while
    queued — their bucket slots are backfilled from the queue before any
    padding is computed, so a stale request never costs a dispatch.
    """

    # handlers probe this before passing deadline/priority kwargs (the
    # single-lock benchmark Scorer and other engines don't take them)
    supports_deadline = True

    def __init__(
        self,
        fn: Callable,
        field_size: int,
        *,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        max_wait_ms: float = 2.0,
        max_queue_rows: int | None = None,
        name: str = "predict",
        registry: MetricsRegistry | None = None,
        admission=None,
    ):
        if not buckets:
            raise ValueError("need at least one bucket size")
        self._buckets = tuple(sorted(int(b) for b in buckets))
        if self._buckets[0] <= 0:
            raise ValueError(f"bucket sizes must be positive: {buckets}")
        if len(set(self._buckets)) != len(self._buckets):
            raise ValueError(f"duplicate bucket sizes: {buckets}")
        self._fn = fn
        self._fields = int(field_size)
        self._max_wait_s = float(max_wait_ms) / 1e3
        self._max_queue_rows = (
            16 * self._buckets[-1] if max_queue_rows is None
            else int(max_queue_rows)
        )
        self.name = name
        # precomputed span names: the trace hot path must not pay an
        # f-string per request
        self._span_queue = f"{name}.queue"
        self._span_dispatch = f"{name}.dispatch"
        # ``registry`` shares one obs registry across a process's engines
        # (served by GET /metrics); None keeps the engine hermetic
        self.metrics = _Metrics(self._buckets, name=name, registry=registry)
        self.registry = self.metrics.registry
        # deadline-aware cost-based admission (serve/control/admission.py):
        # None keeps the legacy bound-only backpressure.  The controller is
        # shareable across a member's per-tenant engines (one cost model —
        # the tenants dispatch through the SAME executables)
        self.admission = admission
        self._g_queue_rows = self.registry.gauge(
            "deepfm_serve_queue_rows", "rows queued awaiting dispatch",
            labels=("engine",),
        ).labels(name)
        self._g_queue_requests = self.registry.gauge(
            "deepfm_serve_queue_requests", "queued request chunks",
            labels=("engine",),
        ).labels(name)
        self.registry.on_collect(self._refresh_queue_gauges)
        self._cond = threading.Condition()
        # queue items: (request, req_offset, ids_chunk, vals_chunk,
        # arrival, deadline)  — deadline is absolute perf_counter seconds
        # or None; checked at dequeue (expired chunks answer 504 and
        # their slots backfill)
        self._queue: deque[tuple] = deque()
        self._queued_rows = 0
        # the dispatch currently executing, as (bucket_rows, started_at)
        # — admission prices its REMAINING time ahead of the queue drain
        # (in-flight work is invisible to queue depth, yet every arrival
        # waits behind it: without this the deadline promise can run one
        # full bucket's service time late)
        self._inflight_dispatch: tuple[int, float] | None = None
        self._closed = False
        self._worker = threading.Thread(
            target=self._run, daemon=True, name=f"micro-batcher-{name}"
        )
        self._worker.start()

    # ---------------------------------------------------------------- public

    @property
    def buckets(self) -> tuple[int, ...]:
        return self._buckets

    @property
    def max_wait_ms(self) -> float:
        return self._max_wait_s * 1e3

    def precompile(self) -> dict[int, float]:
        """Compile the per-bucket executables before traffic arrives.

        Returns {bucket: seconds}.  jax.jit caches by shape, so one zero
        batch per bucket shape is exactly one executable each; live
        requests then never block on a compile."""
        timings: dict[int, float] = {}
        for b in self._buckets:
            ids = np.zeros((b, self._fields), np.int64)
            vals = np.zeros((b, self._fields), np.float32)
            t0 = time.perf_counter()
            np.asarray(self._fn(ids, vals))
            timings[b] = round(time.perf_counter() - t0, 4)
        return timings

    def score(self, ids: np.ndarray, vals: np.ndarray, *,
              deadline_s: float | None = None,
              priority: str = "predict") -> np.ndarray:
        """ids/vals [N, F] -> output [N] (or [N, D]); blocks until scored.

        Raises ``ValueError`` for malformed shapes (validated HERE, on the
        caller's thread — a bad request never reaches the shared queue),
        :class:`OverloadedError` when the queue bound would be exceeded,
        and — with an admission controller attached —
        ``DeadlineRejectedError``/``ShedError`` at admission (503 +
        Retry-After upstream) or ``DeadlineExpiredError`` when
        ``deadline_s`` (absolute ``time.perf_counter`` seconds) passed
        while the request was queued (504 upstream)."""
        ids = np.asarray(ids, np.int64)
        vals = np.asarray(vals, np.float32)
        check_features(ids, vals, self._fields)
        n = ids.shape[0]
        if n == 0:
            return np.zeros((0,), np.float32)
        # oversized requests split into <= largest-bucket chunks up front,
        # so the worker never has to slice mid-item
        cap = self._buckets[-1]
        starts = list(admission_starts(n, cap))
        req = _Request(n, len(starts), trace=current_trace())
        with self._cond:
            if self._closed:
                raise RuntimeError(
                    f"MicroBatcher {self.name!r} is closed"
                )
            # the bound sheds BACKLOG, not request size: a single request
            # bigger than the bound is admitted when the queue is empty —
            # rejecting it would lock large-batch clients out forever on
            # an idle server
            if (self._queued_rows > 0
                    and self._queued_rows + n > self._max_queue_rows):
                self.metrics.record_reject()
                raise OverloadedError(
                    f"scoring queue full ({self._queued_rows} rows queued, "
                    f"bound {self._max_queue_rows}); retry later"
                )
            arrival = time.perf_counter()
            if self.admission is not None:
                # deadline pricing + the shed ladder, decided at the door
                # (raises — nothing was enqueued yet, nothing to undo);
                # returns the effective absolute deadline to stamp the
                # queue items with
                deadline_s = self.admission.check(
                    rows=n, queued_rows=self._queued_rows,
                    max_queue_rows=self._max_queue_rows,
                    deadline_s=deadline_s, priority=priority, now=arrival,
                    inflight=self._inflight_dispatch,
                )
            for s in starts:
                self._queue.append(
                    (req, s, ids[s : s + cap], vals[s : s + cap], arrival,
                     deadline_s)
                )
            self._queued_rows += n
            self._cond.notify()
        self.metrics.record_admit(n)
        req.done.wait()
        self.metrics.record_latency(time.perf_counter() - req.t_submit)
        if req.trace is not None and req.t_dispatch is not None:
            # queue wait = admission to first device dispatch; the
            # dispatch spans themselves were recorded by the worker
            req.trace.add_span(
                self._span_queue, req.t_submit, req.t_dispatch, rows=n,
            )
        if req.error is not None:
            raise req.error
        return req.out

    def score_instances(self, instances: list[dict], *,
                        deadline_s: float | None = None,
                        priority: str = "predict") -> np.ndarray:
        return self.score(*instances_to_arrays(instances),
                          deadline_s=deadline_s, priority=priority)

    def _refresh_queue_gauges(self) -> None:
        """Pre-scrape hook: surface live queue depth as gauges."""
        with self._cond:
            rows, reqs = self._queued_rows, len(self._queue)
        self._g_queue_rows.set(rows)
        self._g_queue_requests.set(reqs)

    def metrics_snapshot(self) -> dict:
        with self._cond:
            queue_rows, queue_requests = self._queued_rows, len(self._queue)
        snap = {
            "engine": "micro_batcher",
            "name": self.name,
            "buckets": list(self._buckets),
            "max_wait_ms": round(self.max_wait_ms, 3),
            "max_queue_rows": self._max_queue_rows,
            "queue_rows": queue_rows,
            "queue_requests": queue_requests,
        }
        snap.update(self.metrics.snapshot())
        if self.admission is not None:
            snap["admission"] = self.admission.snapshot()
        return snap

    def close(self) -> None:
        """Stop the worker thread (tests/benchmarks hygiene; in-flight
        requests finish first, later submissions raise RuntimeError)."""
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._worker.join(timeout=10)

    # ---------------------------------------------------------------- worker

    def _pick_bucket(self, rows: int) -> int:
        return pick_bucket(self._buckets, rows)

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if self._closed and not self._queue:
                    return
                # coalescing window: the oldest item's deadline caps how
                # long we hold the flush, and a full SMALLEST bucket ends
                # the wait early — holding out for a bigger bucket would
                # idle the device while work is in hand, capping
                # throughput near queued_rows/max_wait whenever a
                # dispatch outpaces the timeout.  The next dispatch's own
                # duration coalesces the stragglers instead.
                deadline = self._queue[0][4] + self._max_wait_s
                while (self._queued_rows < self._buckets[0]
                       and not self._closed):
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                batch, rows = [], 0
                t_collect = time.perf_counter()
                while self._queue and rows + self._queue[0][2].shape[0] \
                        <= self._buckets[-1]:
                    item = self._queue.popleft()
                    if item[0].error is not None:
                        # a sibling chunk's dispatch already failed this
                        # request and unblocked its caller: don't burn a
                        # bucket execution on — or batch live requests
                        # with — an orphan chunk
                        self._queued_rows -= item[2].shape[0]
                        continue
                    if item[5] is not None and t_collect > item[5]:
                        # the deadline passed while queued: answer 504
                        # NOW and keep collecting — the slot this chunk
                        # would have taken backfills from the queue
                        # before any padding is computed, so a bucket
                        # of stale work dispatches nothing
                        self._queued_rows -= item[2].shape[0]
                        req = item[0]
                        if req.error is None:
                            req.error = DeadlineExpiredError(
                                f"deadline passed while queued "
                                f"({(t_collect - item[5]) * 1e3:.1f} ms "
                                f"late at dequeue)"
                            )
                            self.metrics.record_expired()
                        req.done.set()
                        continue
                    batch.append(item)
                    rows += item[2].shape[0]
                self._queued_rows -= rows
                if batch:
                    # visible to admission while the worker is busy
                    self._inflight_dispatch = (
                        self._pick_bucket(rows), time.perf_counter()
                    )
            if batch:
                try:
                    self._dispatch(batch, rows)
                finally:
                    with self._cond:
                        self._inflight_dispatch = None

    def _dispatch(self, batch: list[tuple], rows: int) -> None:
        bucket = self._pick_bucket(rows)
        t0 = time.perf_counter()
        for req, *_ in batch:
            if req.t_dispatch is None:
                req.t_dispatch = t0
        try:
            ids = np.zeros((bucket, self._fields), np.int64)
            vals = np.zeros((bucket, self._fields), np.float32)
            off = 0
            for _req, _ro, cids, cvals, *_ in batch:
                ids[off : off + cids.shape[0]] = cids
                vals[off : off + cids.shape[0]] = cvals
                off += cids.shape[0]
            res = np.asarray(self._fn(ids, vals))
            self.metrics.record_dispatch(bucket, rows)
            t1 = time.perf_counter()
            if self.admission is not None:
                # the admission cost model eats the SAME host-side
                # boundary the dispatch span records — per bucket shape
                self.admission.cost.observe(bucket, t1 - t0)
            for req, *_ in batch:
                if req.trace is not None:
                    # host-side timer AROUND the dispatch boundary — the
                    # jitted fn itself carries no instrumentation
                    req.trace.add_span(
                        self._span_dispatch, t0, t1, bucket=bucket,
                        rows_coalesced=rows, padded=bucket - rows,
                    )
            off = 0
            for req, req_off, cids, *_ in batch:
                k = cids.shape[0]
                if req.out is None:
                    req.out = np.empty(
                        (req.rows, *res.shape[1:]), res.dtype
                    )
                req.out[req_off : req_off + k] = res[off : off + k]
                off += k
        except Exception as e:  # runtime failure: fail the whole dispatch
            for req, *_ in batch:
                req.error = e
        finally:
            for req, *_ in batch:
                req.remaining -= 1
                if req.remaining == 0 or req.error is not None:
                    req.done.set()
