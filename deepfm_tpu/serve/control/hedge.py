"""Hedged tail requests + the shared retry/hedge token budget.

**The budget** is the brownout guard: every routed request accrues a
fractional token (``ratio`` = budget percent / 100), every cross-group
retry and every hedge spends one.  Steady state, retries+hedges are
capped at ``ratio`` of the live request rate; in a pool-wide brownout
the bucket drains and the router FAILS FAST (503 + Retry-After) instead
of multiplying the offered load by the retry factor exactly when
capacity is scarcest — the amplification stays sub-linear by
construction.

**Hedging** tames the tail when ONE group is degraded (a paging stall,
a mid-swap drain) without ejecting it: when the first-choice group's
live p95 exceeds the SLO budget, the router arms a hedge to the next
healthy candidate, fires it only after the primary has outlived an
adaptive delay (``hedge_after_pct`` of that p95), takes the first
answer, and counts the loser as cancelled.  The delay keeps the extra
load near zero on a healthy pool; the token budget hard-caps it under
stress.
"""

from __future__ import annotations

import threading


class TokenBudget:
    """Request-rate-proportional token bucket; thread-safe.

    ``note_request()`` accrues ``ratio`` tokens (so the spend rate is
    capped at ``ratio`` of the recent request rate with burst headroom
    ``burst``); ``try_spend()`` takes one or answers False — callers
    MUST fail fast on False, never block."""

    def __init__(self, ratio: float, *, burst: float = 16.0,
                 initial: float | None = None):
        if ratio < 0:
            raise ValueError(f"budget ratio must be >= 0, got {ratio}")
        self._ratio = float(ratio)
        self._burst = max(1.0, float(burst))
        self._lock = threading.Lock()
        self._tokens = self._burst if initial is None else float(initial)
        self.spent_total = 0
        self.exhausted_total = 0

    def note_request(self) -> None:
        with self._lock:
            self._tokens = min(self._burst, self._tokens + self._ratio)

    def try_spend(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self.spent_total += 1
                return True
            self.exhausted_total += 1
            return False

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "ratio": self._ratio,
                "tokens": round(self._tokens, 3),
                "burst": self._burst,
                "spent_total": self.spent_total,
                "exhausted_total": self.exhausted_total,
            }


class HedgeController:
    """The hedge decision: whether to arm, and after what delay.

    ``plan(p95_ms)`` consults the first-choice group's live p95
    (router-measured sliding window): under the SLO budget the answer is
    None (no hedge state, no threads, no cost); over it, the adaptive
    delay is ``hedge_after_pct`` of that p95 — the hedge fires only for
    requests already slower than most of the degraded group's own
    traffic.  Token spend is the caller's (the budget is shared with
    retries); win/loss accounting lives here."""

    def __init__(self, *, slo_budget_ms: float,
                 after_pct: float = 95.0,
                 budget: TokenBudget | None = None):
        if slo_budget_ms <= 0:
            raise ValueError(
                f"hedging needs a positive SLO budget, got {slo_budget_ms}"
            )
        self._slo_ms = float(slo_budget_ms)
        self._after = max(0.0, float(after_pct)) / 100.0
        self.budget = budget
        self._lock = threading.Lock()
        self.fired_total = 0
        self.wins_total = 0
        self.cancelled_total = 0
        self.suppressed_budget_total = 0

    def plan(self, p95_ms: float | None) -> float | None:
        """Delay in SECONDS before the hedge fires, or None (group
        healthy: p95 inside the SLO budget, or no signal yet)."""
        if p95_ms is None or p95_ms <= self._slo_ms:
            return None
        return (p95_ms * self._after) / 1e3

    def try_fire(self) -> bool:
        """Spend a budget token for one hedge (False = suppressed)."""
        if self.budget is not None and not self.budget.try_spend():
            with self._lock:
                self.suppressed_budget_total += 1
            return False
        with self._lock:
            self.fired_total += 1
        return True

    def record_outcome(self, *, hedge_won: bool) -> None:
        """First answer decided the race: the loser counts as
        cancelled (its group did the work; nobody consumed it)."""
        with self._lock:
            if hedge_won:
                self.wins_total += 1
            self.cancelled_total += 1

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "slo_budget_ms": self._slo_ms,
                "after_pct": self._after * 100.0,
                "fired_total": self.fired_total,
                "wins_total": self.wins_total,
                "cancelled_total": self.cancelled_total,
                "suppressed_budget_total": self.suppressed_budget_total,
            }
        if self.budget is not None:
            out["budget"] = self.budget.snapshot()
        return out
