"""Per-bucket online dispatch cost model.

The admission decision (admission.py) needs an answer to "if this
request is admitted NOW, when does it finish?" — which is the queue's
drain time plus the request's own dispatch, both priced per executable
shape.  The engine already measures exactly the right quantity: the
``dispatch`` span (obs/trace.py) brackets each ``fn(ids, vals)`` call
per bucket.  This model is an EWMA over those host-side timings, one
cell per bucket, fed by the MicroBatcher's dispatch path.

Cold-start honesty: a bucket that has never dispatched has NO estimate,
and the model answers ``None`` for it rather than a guess — the
admission layer treats unknown cost as admissible (rejecting on a made-
up number would shed real traffic on every process restart).  The
nearest observed bucket's per-row rate backstops the drain estimate as
soon as any bucket has run.
"""

from __future__ import annotations

import threading
from typing import Sequence


class BucketCostModel:
    """EWMA dispatch-seconds per bucket shape; thread-safe.

    ``alpha`` is the EWMA weight of the newest observation — high enough
    to track a paging stall within a few dispatches, low enough that one
    outlier dispatch does not flip admission."""

    def __init__(self, buckets: Sequence[int], *, alpha: float = 0.2):
        if not buckets:
            raise ValueError("cost model needs at least one bucket size")
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self._buckets = tuple(sorted(int(b) for b in buckets))
        self._alpha = float(alpha)
        self._lock = threading.Lock()
        self._ewma_s: dict[int, float] = {}
        self.observations_total = 0

    @property
    def buckets(self) -> tuple[int, ...]:
        return self._buckets

    def _fit(self, rows: int) -> int:
        for b in self._buckets:
            if rows <= b:
                return b
        return self._buckets[-1]

    def observe(self, bucket: int, seconds: float) -> None:
        """Feed one dispatch timing (the host-side t1-t0 around the
        engine's ``fn`` call — the same boundary the trace span uses)."""
        bucket = int(bucket)
        if seconds < 0:
            return
        with self._lock:
            prev = self._ewma_s.get(bucket)
            self._ewma_s[bucket] = (
                seconds if prev is None
                else prev + self._alpha * (seconds - prev)
            )
            self.observations_total += 1

    def dispatch_estimate_s(self, rows: int) -> float | None:
        """Estimated seconds for one dispatch of ``rows`` rows (through
        the smallest bucket that fits).  None while that cost is still
        unobserved and no other bucket can stand in."""
        bucket = self._fit(rows)
        with self._lock:
            est = self._ewma_s.get(bucket)
            if est is not None:
                return est
            # backstop: scale the nearest observed bucket's per-row rate
            if self._ewma_s:
                near = min(self._ewma_s, key=lambda b: abs(b - bucket))
                return self._ewma_s[near] * (bucket / near)
        return None

    def drain_estimate_s(self, queued_rows: int) -> float | None:
        """Estimated seconds to drain ``queued_rows`` already-queued rows
        ahead of a new arrival.  The engine drains through the LARGEST
        bucket under load (full coalescing), so the queue is priced as
        ``ceil(queued/largest)`` big dispatches plus one remainder-sized
        one.  None while the model is cold."""
        if queued_rows <= 0:
            return 0.0
        big = self._buckets[-1]
        full, rem = divmod(int(queued_rows), big)
        total = 0.0
        if full:
            per = self.dispatch_estimate_s(big)
            if per is None:
                return None
            total += full * per
        if rem:
            per = self.dispatch_estimate_s(rem)
            if per is None:
                return None
            total += per
        return total

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "observations_total": self.observations_total,
                "dispatch_ewma_ms": {
                    str(b): round(s * 1e3, 3)
                    for b, s in sorted(self._ewma_s.items())
                },
            }
