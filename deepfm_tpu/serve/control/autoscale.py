"""Elastic shard-group autoscaling: the decision logic.

Pure policy, deliberately separated from execution: this class watches
(utilization, p95, group count) samples and answers "up", "down", or
None; the pool supervisor (serve/pool/__main__.py) owns the machinery —
spawning a member process, waiting out its ``/readyz`` gate, admitting
it to the router, or draining the emptiest group through the existing
stop-admitting → wait-in-flight → terminate discipline.  The split
keeps the policy unit-testable with an injected clock and keeps every
process-management hazard in the one file that already handles them.

Hysteresis on BOTH edges: a breach must persist for
``up_window_secs`` before a scale-up (one burst must not buy a group),
slack must persist for ``down_window_secs`` before a scale-down (much
longer — capacity should linger after a spike, not chase it), and a
``cooldown_secs`` refractory period follows every action so the new
topology's signal settles before the next decision.  Bounds are
absolute: never below ``min_groups``, never above ``max_groups``.
"""

from __future__ import annotations

from ...obs import flight as obs_flight


class AutoScaler:
    """Sustained-breach / sustained-slack scaling decisions.

    ``observe(now, groups=..., util=..., p95_ms=...)`` folds one control
    sample in and returns ``"up"``, ``"down"`` or ``None``.  A breach is
    utilization over ``up_util`` OR p95 over ``slo_ms`` (when set);
    slack is utilization under ``down_util`` AND no p95 breach.  The
    caller reports the action's completion via ``note_scaled(now)``
    which starts the cooldown."""

    def __init__(
        self,
        *,
        min_groups: int = 1,
        max_groups: int = 4,
        up_util: float = 0.75,
        down_util: float = 0.25,
        slo_ms: float = 0.0,
        up_window_secs: float = 5.0,
        down_window_secs: float = 30.0,
        cooldown_secs: float = 10.0,
    ):
        if min_groups < 1 or max_groups < min_groups:
            raise ValueError(
                f"need 1 <= min_groups <= max_groups, got "
                f"[{min_groups}, {max_groups}]"
            )
        if down_util >= up_util:
            raise ValueError(
                f"down_util={down_util} must stay below up_util="
                f"{up_util} (the hysteresis band)"
            )
        self.min_groups = int(min_groups)
        self.max_groups = int(max_groups)
        self._up_util = float(up_util)
        self._down_util = float(down_util)
        self._slo_ms = float(slo_ms)
        self._up_window = float(up_window_secs)
        self._down_window = float(down_window_secs)
        self._cooldown = float(cooldown_secs)
        self._breach_since: float | None = None
        self._slack_since: float | None = None
        self._cooldown_until: float = 0.0
        self.scale_ups_total = 0
        self.scale_downs_total = 0

    def note_scaled(self, now: float) -> None:
        self._breach_since = None
        self._slack_since = None
        self._cooldown_until = now + self._cooldown

    def observe(self, now: float, *, groups: int, util: float,
                p95_ms: float | None = None) -> str | None:
        slo_breach = (self._slo_ms > 0 and p95_ms is not None
                      and p95_ms > self._slo_ms)
        breach = util > self._up_util or slo_breach
        slack = util < self._down_util and not slo_breach
        # windows accumulate even during cooldown — a breach that spans
        # the refractory period acts the moment it ends, it does not
        # restart the clock
        self._breach_since = (
            (self._breach_since if self._breach_since is not None else now)
            if breach else None
        )
        self._slack_since = (
            (self._slack_since if self._slack_since is not None else now)
            if slack else None
        )
        if now < self._cooldown_until:
            return None
        if (breach and groups < self.max_groups
                and now - self._breach_since >= self._up_window):
            self.scale_ups_total += 1
            obs_flight.record(
                "autoscale_decision", subsystem="slo", action="up",
                groups=groups, util=round(util, 4),
                p95_ms=None if p95_ms is None else round(p95_ms, 2),
                breach_secs=round(now - self._breach_since, 2),
            )
            return "up"
        if (slack and groups > self.min_groups
                and now - self._slack_since >= self._down_window):
            self.scale_downs_total += 1
            obs_flight.record(
                "autoscale_decision", subsystem="slo", action="down",
                groups=groups, util=round(util, 4),
                p95_ms=None if p95_ms is None else round(p95_ms, 2),
                slack_secs=round(now - self._slack_since, 2),
            )
            return "down"
        return None

    def snapshot(self) -> dict:
        return {
            "min_groups": self.min_groups,
            "max_groups": self.max_groups,
            "up_util": self._up_util,
            "down_util": self._down_util,
            "slo_ms": self._slo_ms,
            "scale_ups_total": self.scale_ups_total,
            "scale_downs_total": self.scale_downs_total,
            "in_breach": self._breach_since is not None,
            "in_slack": self._slack_since is not None,
        }
