"""Deadline-aware cost-based admission + the priority shed ladder.

**Admission** prices each arriving request against its deadline BEFORE
it occupies queue slots: estimated completion = remaining time of the
dispatch already executing (in-flight work is invisible to queue depth,
yet the arrival waits behind it — up to one full bucket's service time)
plus drain time of the rows already queued (cost.py, per-bucket EWMA)
plus the request's own dispatch.  A request that cannot finish inside its deadline is rejected
at the door with a ``Retry-After`` hint — strictly better than the
status quo of admitting it, letting it time out in the queue, and
burning a bucket slot scoring an answer nobody is waiting for.

**The shed ladder** handles sustained saturation (the regime where
deadline math alone just rejects everything equally).  Work sheds in
declared cheapest-first order as smoothed queue utilization climbs:

    level 1  shadow-scoring offers     (zero user impact — a challenger
                                        loses samples, counted)
    level 2  recommend expand/rank     (degraded answers, never absent
             width -> configured floor  ones; an int8 funnel also
                                        narrows its retrieval oversample
                                        to the floor — funnel/serve.py
                                        keeps a pre-compiled degraded
                                        executable for it)
    level 3  plain predicts            (503 + Retry-After at admission)

Utilization is EWMA-smoothed so one burst cannot flip levels, and each
threshold releases at 85% of its engage value (hysteresis) so the
ladder converges back instead of oscillating on the boundary.  Every
shed is counted per priority class and every level transition is
flight-recorded.

Invariant: nothing in this module ever fails work that was already
admitted — expiry-at-dequeue (the 504 path) lives in the engine and
fires only for requests whose deadline passed while queued, which the
admission estimate exists to make rare.
"""

from __future__ import annotations

import threading
import time

from ...obs import flight as obs_flight
from ...obs.metrics import MetricsRegistry
from .cost import BucketCostModel

# priority classes, cheapest-to-shed first.  The wire surface is the
# X-Priority header (router -> member); anything unrecognized scores as
# a plain predict — an unknown class must degrade LAST, not first.
PRIORITY_SHADOW = "shadow"
PRIORITY_RECOMMEND = "recommend"
PRIORITY_PREDICT = "predict"


class DeadlineRejectedError(RuntimeError):
    """Admission-time rejection: the request cannot finish inside its
    deadline given current queue depth (mapped to HTTP 503 with a
    ``Retry-After`` hint — the client should back off, not resubmit
    immediately into the same queue)."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = max(0.001, float(retry_after_s))


class ShedError(DeadlineRejectedError):
    """Priority-ladder shed at admission (same 503 + Retry-After wire
    shape as a deadline rejection; distinguished for counting)."""


class DeadlineExpiredError(RuntimeError):
    """The deadline passed while the request was QUEUED: answered 504 at
    dequeue, slot backfilled — never dispatched, never scored."""


class AdmissionController:
    """Per-engine admission policy: deadline pricing + the shed ladder.

    One controller fronts one MicroBatcher's queue (per-tenant engines
    on a member share one controller — their dispatches share the same
    executables, so one cost model prices all of them).  All methods are
    thread-safe and O(1); they run on the caller's thread inside the
    engine's admission path."""

    def __init__(
        self,
        cost_model: BucketCostModel,
        *,
        deadline_ms: float = 0.0,
        shed_shadow_util: float = 0.60,
        degrade_util: float = 0.75,
        shed_predict_util: float = 0.90,
        degrade_floor_pct: float = 50.0,
        util_alpha: float = 0.1,
        name: str = "predict",
        registry: MetricsRegistry | None = None,
    ):
        self.cost = cost_model
        self._deadline_s = max(0.0, float(deadline_ms)) / 1e3
        self._thresholds = (
            float(shed_shadow_util), float(degrade_util),
            float(shed_predict_util),
        )
        if not (self._thresholds[0] <= self._thresholds[1]
                <= self._thresholds[2]):
            raise ValueError(
                f"shed ladder thresholds must be ordered cheapest-first, "
                f"got {self._thresholds}"
            )
        self._degrade_floor = float(degrade_floor_pct) / 100.0
        self._alpha = float(util_alpha)
        self._lock = threading.Lock()
        self._util_ewma = 0.0
        self._level = 0
        self.name = name
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        sheds = self.registry.counter(
            "deepfm_slo_sheds_total",
            "admission-time sheds by priority class",
            labels=("engine", "class"))
        # pre-create every class child so the shed breakdown renders
        # zeros (the bench reports the full ladder either way)
        self._c_shed = {
            p: sheds.labels(name, p)
            for p in (PRIORITY_SHADOW, PRIORITY_RECOMMEND, PRIORITY_PREDICT)
        }
        self._c_deadline = self.registry.counter(
            "deepfm_slo_deadline_rejected_total",
            "requests rejected at admission: deadline unmeetable",
            labels=("engine",)).labels(name)

    # -- deadline ----------------------------------------------------------
    @property
    def default_deadline_s(self) -> float:
        """Config default deadline in seconds (0 = none)."""
        return self._deadline_s

    def effective_deadline(self, now: float,
                           deadline_s: float | None) -> float | None:
        """The request's absolute deadline: the explicit one
        (``X-Deadline-Ms``, already made absolute by the handler) or
        now + the config default; None when neither exists."""
        if deadline_s is not None:
            return deadline_s
        if self._deadline_s > 0:
            return now + self._deadline_s
        return None

    # -- the ladder --------------------------------------------------------
    def observe_utilization(self, queued_rows: int,
                            max_queue_rows: int) -> int:
        """Fold one queue-depth sample into the smoothed utilization and
        return the (possibly transitioned) ladder level.  Called on
        every admission; EWMA supplies the "sustained" in "sustained
        saturation", and release thresholds sit at 85% of engage so the
        ladder steps down cleanly instead of chattering."""
        util = queued_rows / max(1, max_queue_rows)
        with self._lock:
            self._util_ewma += self._alpha * (util - self._util_ewma)
            ew, level = self._util_ewma, self._level
            new = level
            # engage upward against the full thresholds...
            while new < 3 and ew > self._thresholds[new]:
                new += 1
            # ...release downward only once under 85% of the band below
            while new > 0 and ew < 0.85 * self._thresholds[new - 1]:
                new -= 1
            if new != level:
                self._level = new
            else:
                return level
        obs_flight.record(
            "shed_level", subsystem="slo", engine=self.name,
            level=new, previous=level, util_ewma=round(ew, 4),
        )
        return new

    def level(self) -> int:
        with self._lock:
            return self._level

    @property
    def degrade_floor(self) -> float:
        """The configured level-2 width multiplier — what
        :meth:`degrade_factor` returns once the ladder engages.  Callers
        that pre-compile a degraded executable (the funnel's narrowed
        oversample) size it off this at boot."""
        return self._degrade_floor

    def degrade_factor(self) -> float:
        """Width multiplier for recommend expand/rank (and the int8
        funnel's retrieval oversample) at the current ladder level: 1.0
        normally, the configured floor at level >= 2 (degraded answers
        beat absent ones)."""
        return self._degrade_floor if self.level() >= 2 else 1.0

    # -- the admission decision --------------------------------------------
    def check(self, *, rows: int, queued_rows: int, max_queue_rows: int,
              deadline_s: float | None, priority: str = PRIORITY_PREDICT,
              now: float | None = None,
              inflight: tuple[int, float] | None = None) -> float | None:
        """Admit or raise.  Returns the request's effective absolute
        deadline (None = none) so the engine can stamp queue items.

        ``inflight`` is the dispatch currently executing, as ``(bucket_
        rows, started_at)`` (absolute ``perf_counter`` seconds), or None
        when the worker is idle: its estimated REMAINING time is priced
        ahead of the queue drain, since every queued row waits behind it.

        Raises :class:`ShedError` when the ladder sheds this priority
        class, :class:`DeadlineRejectedError` when the cost model says
        the deadline is unmeetable at current depth.  Never raises for
        a cold cost model — unknown cost is admissible."""
        now = time.perf_counter() if now is None else now
        level = self.observe_utilization(queued_rows, max_queue_rows)
        if level >= 3 and priority != PRIORITY_SHADOW:
            # level 3 sheds everything arriving; shadow-class work was
            # already gone at level 1 (counted where it sheds)
            self._c_shed[
                priority if priority in self._c_shed else PRIORITY_PREDICT
            ].inc()
            raise ShedError(
                f"engine {self.name!r} saturated (shed level {level}); "
                f"retry later",
                retry_after_s=self._retry_after(queued_rows),
            )
        if level >= 1 and priority == PRIORITY_SHADOW:
            self._c_shed[PRIORITY_SHADOW].inc()
            raise ShedError(
                f"engine {self.name!r} shedding shadow-class work "
                f"(level {level})",
                retry_after_s=self._retry_after(queued_rows),
            )
        deadline = self.effective_deadline(now, deadline_s)
        if deadline is None:
            return None
        drain = self.cost.drain_estimate_s(queued_rows)
        own = self.cost.dispatch_estimate_s(rows)
        if drain is None or own is None:
            return deadline      # cold model: admit
        busy = self._inflight_remaining_s(inflight, now)
        eta = now + busy + drain + own
        if eta > deadline:
            self._c_deadline.inc()
            late_by = eta - deadline
            raise DeadlineRejectedError(
                f"deadline unmeetable: estimated completion in "
                f"{(busy + drain + own) * 1e3:.1f} ms exceeds the "
                f"deadline by {late_by * 1e3:.1f} ms "
                f"({queued_rows} rows queued)",
                retry_after_s=max(late_by, busy + drain),
            )
        return deadline

    def _inflight_remaining_s(self, inflight: tuple[int, float] | None,
                              now: float) -> float:
        if inflight is None:
            return 0.0
        bucket_rows, started_at = inflight
        est = self.cost.dispatch_estimate_s(bucket_rows)
        if est is None:
            return 0.0          # cold for this shape: claim nothing
        return max(0.0, est - (now - started_at))

    def _retry_after(self, queued_rows: int) -> float:
        est = self.cost.drain_estimate_s(queued_rows)
        return est if est else 1.0

    def record_shed(self, priority: str) -> None:
        """Count a shed decided OUTSIDE the admission path (the router's
        shadow gate reports through this)."""
        self._c_shed[
            priority if priority in self._c_shed else PRIORITY_PREDICT
        ].inc()

    def snapshot(self) -> dict:
        with self._lock:
            ew, level = self._util_ewma, self._level
        return {
            "level": level,
            "util_ewma": round(ew, 4),
            "deadline_ms": round(self._deadline_s * 1e3, 3),
            "degrade_factor": (self._degrade_floor if level >= 2 else 1.0),
            "deadline_rejected_total": int(self._c_deadline.value),
            "sheds_total": {
                p: int(c.value) for p, c in self._c_shed.items()
            },
            "cost": self.cost.snapshot(),
        }


class LoadShedGate:
    """Router-side saturation signal for the shadow shed-first hook.

    The router has no queue to watch — its saturation evidence is the
    member responses themselves (503s are the engines' backpressure).
    The gate smooths that into an overload score; while it is high,
    ``allow_shadow()`` answers False and the ShadowScorer sheds offers
    at the source (fleet/shadow.py ``gate=``) — the first rung of the
    ladder, applied before the offer even reaches the bounded queue."""

    def __init__(self, *, threshold: float = 0.3, alpha: float = 0.05):
        self._threshold = float(threshold)
        self._alpha = float(alpha)
        self._lock = threading.Lock()
        self._overload_ewma = 0.0
        self._shedding = False

    def note(self, overloaded: bool) -> None:
        """Fold one routed-request outcome in (True = backpressure)."""
        with self._lock:
            self._overload_ewma += self._alpha * (
                (1.0 if overloaded else 0.0) - self._overload_ewma
            )
            was = self._shedding
            # engage/release hysteresis mirrors the ladder's
            if not was and self._overload_ewma > self._threshold:
                self._shedding = True
            elif was and self._overload_ewma < 0.5 * self._threshold:
                self._shedding = False
            flipped = was != self._shedding
            now_shedding = self._shedding
        if flipped:
            obs_flight.record(
                "shadow_gate", subsystem="slo",
                shedding=now_shedding,
                overload_ewma=round(self._overload_ewma, 4),
            )

    def allow_shadow(self) -> bool:
        with self._lock:
            return not self._shedding

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "shedding": self._shedding,
                "overload_ewma": round(self._overload_ewma, 4),
            }
