"""SLO-driven adaptive serving control plane.

The layer that turns the obs stack's measurements into actions, with
graceful degradation as the invariant: shed the cheapest work first,
never fail work already admitted, always converge back.

* :mod:`cost` — per-bucket online dispatch cost model (EWMA over the
  engine's ``dispatch`` span timings) the admission decision prices
  queue drain against.
* :mod:`admission` — deadline-aware cost-based admission for the
  MicroBatcher plus the priority shed ladder (shadow offers first,
  then recommend width, then plain predicts) and the router-side
  shadow shed gate.
* :mod:`hedge` — the shared retry/hedge token budget and the
  p95-adaptive hedged-request policy.
* :mod:`autoscale` — the elastic shard-group scaling decision logic
  (sustained-breach/sustained-slack hysteresis, cooldown, bounds);
  the pool supervisor (serve/pool/__main__.py) executes its decisions.

Everything in this package is HOST-side policy over host-side
measurements.  None of it may enter the jitted predict — the
``audit_control_plane`` trace contract (analysis/trace_audit.py) lowers
the serving predict with the whole control plane constructed and active
and proves the module is unchanged: transfer-guard-clean, no callback
custom_calls, deterministic across fresh builds.
"""

from .admission import (  # noqa: F401
    AdmissionController,
    DeadlineExpiredError,
    DeadlineRejectedError,
    LoadShedGate,
    ShedError,
)
from .autoscale import AutoScaler  # noqa: F401
from .cost import BucketCostModel  # noqa: F401
from .hedge import HedgeController, TokenBudget  # noqa: F401
