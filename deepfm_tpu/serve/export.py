"""Export + batch inference — the SavedModel/TF-Serving capability.

The reference exports a SavedModel with a raw-tensor serving signature
(``feat_ids`` int64 [None, F], ``feat_vals`` float [None, F] -> ``prob``;
ps:535-551) from hosts[0]/rank 0 only, and its ``infer`` task streams
probabilities to ``pred.txt`` (ps:526-533).

The servable here is a directory artifact:
    servable/
      config.json        — full framework Config (the signature's shape info)
      params/            — Orbax checkpoint of (params, model_state)
Loading returns a jitted ``predict(feat_ids, feat_vals) -> prob`` closure —
the serving signature as an XLA executable rather than a TF graph.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Iterator

import jax
import numpy as np
import orbax.checkpoint as ocp

from ..core.config import Config
from ..models.base import get_model
from ..train.step import TrainState


def export_servable(
    cfg: Config, state: TrainState, directory: str | os.PathLike
) -> str:
    """Write the servable artifact.

    The reference exports from hosts[0]/rank 0 only (ps:548, hvd:475-493) to
    avoid concurrent writers.  Here the Orbax save is a *collective*: in a
    multi-host run every process must call it (each serializes only its
    addressable shards; Orbax coordinates one atomic directory), so all
    processes enter; only process 0 writes the small config.json.
    """
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    if jax.process_index() == 0:
        with open(os.path.join(directory, "config.json"), "w") as f:
            json.dump(cfg.to_dict(), f, indent=2)
    ckptr = ocp.StandardCheckpointer()
    payload = {"params": state.params, "model_state": state.model_state}
    path = os.path.join(directory, "params")
    ckptr.save(path, payload, force=True)
    ckptr.wait_until_finished()
    ckptr.close()
    return directory


def _load_config(directory: str) -> Config:
    with open(os.path.join(directory, "config.json")) as f:
        return Config.from_dict(json.load(f))


def _restore_payload(directory: str, init_fn: Callable) -> tuple[dict, dict]:
    """Restore (params, model_state) against the abstract structure implied
    by the config — shape-safe (and silences orbax's no-target warning)."""
    abstract_params, abstract_state = jax.eval_shape(init_fn)
    device = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    abstract_params, abstract_state = jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=device),
        (abstract_params, abstract_state),
    )
    ckptr = ocp.StandardCheckpointer()
    payload = ckptr.restore(
        os.path.join(directory, "params"),
        {"params": abstract_params, "model_state": abstract_state},
    )
    ckptr.close()
    return payload["params"], payload["model_state"]


def load_servable(directory: str | os.PathLike) -> tuple[Callable, Config]:
    """Load a CTR servable and return (jitted predict fn, config).

    predict(feat_ids [B, F] int, feat_vals [B, F] f32) -> prob [B] f32 —
    the reference's serving signature (ps:538-547).
    """
    directory = os.path.abspath(directory)
    cfg = _load_config(directory)
    if cfg.model.model_name == "two_tower":
        raise ValueError(
            "this servable is a two-tower retrieval model; "
            "use serve.load_retrieval_servable"
        )
    model = get_model(cfg.model)
    params, model_state = _restore_payload(
        directory, lambda: model.init(jax.random.PRNGKey(0), cfg.model)
    )

    @jax.jit
    def predict(feat_ids, feat_vals):
        logits, _ = model.apply(
            params, model_state, feat_ids, feat_vals, cfg=cfg.model, train=False
        )
        return jax.nn.sigmoid(logits)

    return predict, cfg


def load_batching_servable(
    directory: str | os.PathLike,
    *,
    buckets: tuple[int, ...] | None = None,
    max_wait_ms: float = 2.0,
    max_queue_rows: int | None = None,
    precompile: bool = True,
):
    """Load a CTR servable wrapped in the micro-batching engine.

    Returns ``(MicroBatcher, Config)`` — the servable's jitted predict
    closure behind the dynamic batcher (serve/batcher.py): concurrent
    ``score`` calls coalesce into padded bucket shapes, each bucket one
    XLA executable, all compiled here (``precompile=True``) so the first
    live request never pays a compile.  This is the embeddable form of
    what ``serve_forever`` runs behind HTTP.
    """
    from .batcher import DEFAULT_BUCKETS, MicroBatcher

    predict, cfg = load_servable(directory)
    batcher = MicroBatcher(
        predict, cfg.model.field_size,
        buckets=DEFAULT_BUCKETS if buckets is None else buckets,
        max_wait_ms=max_wait_ms, max_queue_rows=max_queue_rows,
    )
    if precompile:
        batcher.precompile()
    return batcher, cfg


def load_retrieval_servable(
    directory: str | os.PathLike,
) -> tuple[Callable, Callable, Config]:
    """Load a two-tower servable: (encode_user, encode_item, config).

    ``encode_user(user_ids [B,Fu] int, user_vals [B,Fu] f32) -> [B,D] f32``
    and symmetrically for items — the dual-encoder serving signature (query
    encoding online, corpus encoding offline for ANN indexing).
    """
    from ..models.two_tower import encode_tower, init_two_tower

    directory = os.path.abspath(directory)
    cfg = _load_config(directory)
    if cfg.model.model_name != "two_tower":
        raise ValueError(
            f"servable holds model {cfg.model.model_name!r}; use load_servable"
        )
    params, _ = _restore_payload(
        directory, lambda: init_two_tower(jax.random.PRNGKey(0), cfg.model)
    )

    @jax.jit
    def encode_user(user_ids, user_vals):
        return encode_tower(
            params, user_ids, user_vals, cfg=cfg.model, side="user"
        )

    @jax.jit
    def encode_item(item_ids, item_vals):
        return encode_tower(
            params, item_ids, item_vals, cfg=cfg.model, side="item"
        )

    return encode_user, encode_item, cfg


def write_predictions(
    probs: Iterator[np.ndarray] | Iterator[float], path: str | os.PathLike
) -> int:
    """The ``infer``-task output: one probability per line (ps:526-533).
    An object-URL path uploads the finished file (spooled via tempfile so
    memory stays O(spool buffer), matching the local streaming write)."""
    from ..data.object_store import get_store, is_url

    count = 0
    if is_url(path):
        import tempfile

        with tempfile.SpooledTemporaryFile(
            max_size=1 << 24, mode="w+b"
        ) as f:
            for p in probs:
                arr = np.atleast_1d(np.asarray(p))
                for v in arr:
                    f.write(f"{float(v):.6f}\n".encode())
                    count += 1
            length = f.tell()
            f.seek(0)
            get_store().put_stream(str(path), f, length)
        return count
    with open(path, "w") as f:
        for p in probs:
            arr = np.atleast_1d(np.asarray(p))
            for v in arr:
                f.write(f"{float(v):.6f}\n")
                count += 1
    return count
