"""Online scoring — the TF-Serving role behind the reference's export.

The reference's serving story ends at ``export_savedmodel`` (ps:535-551):
the SavedModel is handed to TF Serving, which exposes a REST predict
endpoint.  This module is that last mile for the framework's servable
artifact, with zero extra dependencies:

* **REST mode** (default): an ``http.server`` endpoint speaking the TF
  Serving REST request/response shape —

      POST /v1/models/<name>:predict
      {"instances": [{"feat_ids": [...F ints], "feat_vals": [...F floats]},
                     ...]}
      -> {"predictions": [p0, p1, ...]}

  so a client written against TF Serving's CTR signature works unchanged
  (modulo host/port).  ``GET /v1/models/<name>`` returns a status document.

* **stdin mode** (``--stdin``): scores libsvm lines (``label id:val ...`` —
  label ignored) or JSON-object lines to one probability per line, for
  shell pipelines and smoke tests.

* **retrieval mode** (automatic for two-tower servables): ``:encode_user``
  and ``:encode_item`` return L2-normalized embeddings; with
  ``--item-corpus`` (JSONL items encoded at startup) ``:retrieve`` returns
  top-k corpus ids + scores per user query — the dual-encoder deployment
  pattern (query encoding online, corpus offline).

Requests are scored through the dynamic micro-batching engine
(serve/batcher.py): concurrent requests coalesce into padded power-of-two
buckets (``--buckets``), each bucket a precompiled XLA executable, with an
admission timeout (``--max-wait-ms``) and bounded-queue backpressure
(503 on overload).  ``GET /v1/metrics`` exposes request counts, the
batch-size histogram, queue depth, and p50/p95/p99 latency.

    python -m deepfm_tpu.serve.server --servable /path/servable --port 8501
    cat batch.libsvm | python -m deepfm_tpu.serve.server --servable D --stdin
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

import numpy as np

from ..obs import flight as obs_flight
from ..obs.metrics import MetricsRegistry
from ..obs.trace import (
    DEFAULT_SAMPLE_RATE,
    TRACE_HEADER,
    Tracer,
    current_trace,
)
from .batcher import (
    MicroBatcher,
    OverloadedError,
    check_features,
    instances_to_arrays,
)
from .control.admission import (
    DeadlineExpiredError,
    DeadlineRejectedError,
)

_check_features = check_features


def _parse_buckets(s) -> tuple[int, ...]:
    if isinstance(s, str):
        return tuple(int(x) for x in s.split(",") if x.strip())
    return tuple(int(x) for x in s)


def _apply_fixed_batch(
    fn: Callable, ids: np.ndarray, vals: np.ndarray,
    *, fields: int, batch_size: int, lock: threading.Lock,
) -> np.ndarray:
    """Run ``fn(ids, vals)`` over [N, F] inputs in fixed-size chunks, zero-
    padding the tail so XLA compiles exactly one executable.  Output may be
    [B] (probabilities) or [B, D] (embeddings)."""
    _check_features(ids, vals, fields)
    n = ids.shape[0]
    out = None
    with lock:
        for i in range(0, n, batch_size):
            ci, cv = ids[i : i + batch_size], vals[i : i + batch_size]
            b = ci.shape[0]
            pad = batch_size - b
            if pad:
                ci = np.concatenate([ci, np.zeros((pad, fields), ids.dtype)])
                cv = np.concatenate([cv, np.zeros((pad, fields), vals.dtype)])
            res = np.asarray(fn(ci, cv))[:b]
            if out is None:
                out = np.empty((n, *res.shape[1:]), np.float32)
            out[i : i + b] = res
    if out is None:
        return np.zeros((0,), np.float32)
    return out


_instances_to_arrays = instances_to_arrays


class Scorer:
    """Fixed-batch wrapper over the servable predict closure.

    This is the pre-batcher single-lock engine: every request serializes
    behind one lock and chunks through ONE fixed padded shape.  Kept as
    the baseline the micro-batching engine is benchmarked against
    (benchmarks/serving.py) — production serving goes through
    :class:`deepfm_tpu.serve.batcher.MicroBatcher`."""

    def __init__(self, predict: Callable, field_size: int, batch_size: int = 256):
        self._predict = predict
        self._fields = field_size
        self._batch = batch_size
        self._lock = threading.Lock()  # jit dispatch is cheap; keep it simple

    def score(self, ids: np.ndarray, vals: np.ndarray) -> np.ndarray:
        """ids/vals [N, F] -> prob [N], padded through the fixed batch."""
        return _apply_fixed_batch(
            self._predict, ids, vals,
            fields=self._fields, batch_size=self._batch, lock=self._lock,
        )

    def score_instances(self, instances: list[dict]) -> np.ndarray:
        return self.score(*_instances_to_arrays(instances))


class RetrievalScorer:
    """Two-tower serving: encode either side; top-k retrieve against a
    pre-encoded item corpus (the dual-encoder deployment pattern — query
    encoding online, corpus encoded at startup for scoring/ANN).

    Each tower gets its own micro-batching engine (separate field widths,
    separate bucket executables), so concurrent user- and item-encode
    traffic coalesces independently."""

    def __init__(self, encode_user: Callable, encode_item: Callable,
                 cfg, buckets=(8, 32, 128, 512), max_wait_ms: float = 2.0,
                 max_queue_rows: int | None = None, registry=None):
        # one registry, two engines: the families are labeled by engine
        # name, so GET /metrics shows both towers side by side
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._batchers = {
            "user": MicroBatcher(
                encode_user, cfg.model.user_field_size, buckets=buckets,
                max_wait_ms=max_wait_ms, max_queue_rows=max_queue_rows,
                name="encode_user", registry=self.registry,
            ),
            "item": MicroBatcher(
                encode_item, cfg.model.item_field_size, buckets=buckets,
                max_wait_ms=max_wait_ms, max_queue_rows=max_queue_rows,
                name="encode_item", registry=self.registry,
            ),
        }
        self._corpus_ids: np.ndarray | None = None
        self._corpus_emb: np.ndarray | None = None

    def precompile(self) -> dict:
        return {s: b.precompile() for s, b in self._batchers.items()}

    def metrics_snapshot(self) -> dict:
        return {s: b.metrics_snapshot() for s, b in self._batchers.items()}

    def encode(self, side: str, ids: np.ndarray, vals: np.ndarray) -> np.ndarray:
        try:
            return self._batchers[side].score(ids, vals)
        except ValueError as e:
            raise ValueError(f"{side}: {e}") from None

    def encode_instances(self, side: str, instances: list[dict]) -> np.ndarray:
        ids = np.asarray([i[f"{side}_ids"] for i in instances], np.int64)
        vals = np.asarray([i[f"{side}_vals"] for i in instances], np.float32)
        return self.encode(side, ids, vals)

    def load_corpus(self, path: str) -> int:
        """JSONL corpus: one item per line,
        ``{"id": <int>, "item_ids": [...], "item_vals": [...]}``;
        encoded once at load."""
        ids_out, rows = [], []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                obj = json.loads(line)
                ids_out.append(int(obj["id"]))
                rows.append(obj)
        if not rows:
            raise ValueError(f"empty item corpus {path!r}")
        self._corpus_emb = self.encode_instances("item", rows)
        self._corpus_ids = np.asarray(ids_out, np.int64)
        return len(rows)

    def retrieve(self, user_instances: list[dict], k: int):
        if self._corpus_emb is None:
            raise ValueError(
                "no item corpus loaded (start the server with --item-corpus)"
            )
        k = int(k)
        if k <= 0:
            raise ValueError(f"k must be positive, got {k}")
        u = self.encode_instances("user", user_instances)   # [B, D]
        scores = u @ self._corpus_emb.T                     # [B, N]
        k = min(k, scores.shape[1])
        top = np.argpartition(-scores, kth=k - 1, axis=1)[:, :k]
        row = np.arange(scores.shape[0])[:, None]
        order = np.argsort(-scores[row, top], axis=1)
        top = top[row, order]
        return self._corpus_ids[top], scores[row, top]


def make_retrieval_handler(scorer: RetrievalScorer, model_name: str,
                           tracer=None):
    base = f"/v1/models/{model_name}"
    tracer = tracer if tracer is not None else Tracer(
        model_name, sample_rate=DEFAULT_SAMPLE_RATE)

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"  # keep-alive (Content-Length always sent)
        disable_nagle_algorithm = True  # no Nagle+delayed-ACK stalls
        _send = _send_json
        _send_plain = _send_text
        obs_tracer = tracer

        def do_GET(self):  # noqa: N802
            if self.path == "/healthz":
                self._send(200, {"status": "alive"})
            elif self.path == "/metrics":
                self._send_plain(200, scorer.registry.render_prometheus())
            elif self.path == "/v1/trace/recent":
                self._send(200, {"traces": tracer.recent()})
            elif self.path == "/v1/flight":
                self._send(200, {"events": obs_flight.render_events()})
            elif self.path == "/readyz":
                # retrieval servables have no reload path: ready once the
                # engines precompiled (which happened before the socket
                # opened)
                self._send(200, {"ready": True, "engine_compiled": True,
                                 "weights_loaded": True})
            elif self.path == base:
                self._send(
                    200,
                    {
                        "model_version_status": [
                            {"version": "1", "state": "AVAILABLE"}
                        ],
                        "corpus_items": (
                            0 if scorer._corpus_ids is None
                            else int(scorer._corpus_ids.shape[0])
                        ),
                    },
                )
            elif self.path == "/v1/metrics":
                self._send(
                    200,
                    {"model": model_name, **scorer.metrics_snapshot()},
                )
            else:
                self._send(404, {"error": f"unknown path {self.path!r}"})

        def do_POST(self):  # noqa: N802
            known = {
                f"{base}:encode_user", f"{base}:encode_item",
                f"{base}:retrieve",
            }
            traced = self.path in known
            ctx = (tracer.begin(self.path.rsplit(":", 1)[-1], self.headers)
                   if traced else None)
            token = tracer.activate(ctx)
            self._obs_status = None
            try:
                self._handle_post(known)
            finally:
                tracer.finish(ctx, token, status=self._obs_status)

        def _handle_post(self, known):
            if self.path not in known:
                self._send(404, {"error": f"unknown path {self.path!r}"})
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(length))
                instances = req["instances"]
            except Exception as e:
                self._send(400, {"error": f"{type(e).__name__}: {e}"})
                return
            try:
                if self.path == f"{base}:encode_user":
                    emb = scorer.encode_instances("user", instances)
                    self._send(200, {"embeddings": emb.tolist()})
                elif self.path == f"{base}:encode_item":
                    emb = scorer.encode_instances("item", instances)
                    self._send(200, {"embeddings": emb.tolist()})
                elif self.path == f"{base}:retrieve":
                    ids, scores = scorer.retrieve(
                        instances, req.get("k", 10)
                    )
                    self._send(
                        200,
                        {
                            "neighbors": ids.tolist(),
                            "scores": scores.tolist(),
                        },
                    )
            except OverloadedError as e:
                self._send(503, {"error": str(e)})
            except (ValueError, KeyError, TypeError) as e:
                self._send(400, {"error": f"{type(e).__name__}: {e}"})
            except Exception as e:
                self._send(500, {"error": f"{type(e).__name__}: {e}"})

        def log_message(self, fmt, *args):
            pass

    return Handler


class ScoringHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a serving-appropriate listen backlog.

    The stdlib default (request_queue_size=5) drops SYNs under a modest
    connection burst — 16 simultaneous clients saw ~1s TCP-retransmit
    stalls (p95 1033 ms on an idle host, docs/BENCH_SERVING.json) before
    this override.  ``reuse_port`` lets N worker processes share one port
    (the kernel load-balances accepted connections across listeners) —
    the TF-Serving-style multi-worker front, see :func:`serve_pool`."""

    request_queue_size = 128
    reuse_port = False

    def server_bind(self):
        if self.reuse_port:
            import socket

            self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        super().server_bind()


def _send_json(self, code: int, payload: dict,
               extra_headers: dict | None = None) -> None:
    import os

    body = json.dumps(payload).encode()
    self.send_response(code)
    self.send_header("Content-Type", "application/json")
    self.send_header("Content-Length", str(len(body)))
    if extra_headers:
        for k, v in extra_headers.items():
            self.send_header(k, str(v))
    # which process answered — lets pool clients/ops attribute responses
    # (and lets the bench warm every SO_REUSEPORT worker deterministically)
    self.send_header("X-Serving-Pid", str(os.getpid()))
    ctx = current_trace()
    if ctx is not None:
        # every traced response carries its trace id, success or error —
        # the client's correlation handle into /v1/trace/recent
        self.send_header(TRACE_HEADER, ctx.trace_id)
    self.end_headers()
    self.wfile.write(body)
    # observed by the tracing wrapper (finish() stamps it as the status)
    self._obs_status = code


def _slo_kwargs(headers, scorer) -> dict:
    """Per-request SLO kwargs for engines that understand them
    (``supports_deadline`` — the micro-batching engine): the client's
    ``X-Deadline-Ms`` made ABSOLUTE against this host's clock at parse
    time, so queue wait counts against it, plus the declared
    ``X-Priority`` class (shadow | recommend | predict).  Engines
    without the attribute get neither kwarg — the headers degrade to
    no-ops, never TypeErrors."""
    if not getattr(scorer, "supports_deadline", False):
        return {}
    kw: dict = {}
    hdr = headers.get("X-Deadline-Ms")
    if hdr is not None:
        try:
            ms = float(hdr)
        except ValueError:
            ms = -1.0
        if ms >= 0:
            kw["deadline_s"] = time.perf_counter() + ms / 1e3
    pri = headers.get("X-Priority")
    if pri:
        kw["priority"] = pri.strip().lower()
    return kw


def _retry_after_headers(e: "DeadlineRejectedError") -> dict:
    # Retry-After is integer seconds on the wire; never advertise 0
    # (that reads as "retry immediately" — the opposite of the hint)
    return {"Retry-After": max(1, int(e.retry_after_s + 0.999))}


def _send_text(self, code: int, body: str,
               content_type: str = "text/plain; version=0.0.4") -> None:
    raw = body.encode()
    self.send_response(code)
    self.send_header("Content-Type", content_type)
    self.send_header("Content-Length", str(len(raw)))
    self.end_headers()
    self.wfile.write(raw)


def make_handler(scorer, model_name: str, reload_status=None,
                 readiness=None, group_status=None, registry=None,
                 tracer=None):
    """REST handler over any engine exposing score/score_instances —
    the micro-batching engine in production; the single-lock Scorer only
    in the benchmark baseline.  ``GET /v1/metrics`` serves the engine's
    metrics snapshot when the engine provides one, plus a ``paging``
    section (hit rate, staged/cold bytes, tier residency) whenever the
    engine pages weights through tiers (``paging_snapshot`` hook — the
    tiered giant-vocab scorer, deepfm_tpu/tiered/serving.py).

    ``group_status`` (a zero-arg callable) turns on the shard-group pool
    surface (serve/pool/): its document —

        {"shard_group": <str>, "tenant": <str>, "group_generation": <int>,
         "exchange": "alltoall"|"psum", "mesh": [dp, mp],
         "exchange_wire_bytes_est": <int>}

    — is served as the ``router`` section of ``/v1/metrics`` and merged
    into the ``/readyz`` document (the pool router reads generation +
    wire-bytes from readiness probes); every JSON ``:predict`` response
    carries its ``shard_group``, ``tenant`` and ``group_generation`` keys
    (so a client sees WHICH group, tenant and generation scored it,
    alongside the existing ``model_version``) without the rest of the
    gauge noise.  ``tenant`` names the model variant that scored the
    request (deepfm_tpu/fleet; a pool without a fleet config serves one
    tenant, "default") and ``group_generation`` is that TENANT's
    generation — generations are per tenant, so one tenant's swap never
    relabels another's responses.  A JSON response whose tenant's
    generation moved between admission and response assembly (a commit
    or rollback landed mid-request) is refused with a 409 by the pool
    member's attribution guard rather than sent under an ambiguous
    label — the router re-pins and retries.  The binary predict path
    stays a bare float array — group attribution rides the
    ``X-Shard-Group`` / ``X-Tenant`` / ``X-Group-Generation`` response
    headers there, and is at-most-one-behind across a swap window (the
    headers are written before the body; exact provenance needs the
    JSON path).

    ``reload_status`` (a zero-arg callable returning the HotSwapper status
    dict, serve/reload.py) turns on hot-reload observability: the status
    document and every predict response carry the live ``model_version``,
    and ``/v1/metrics`` gains a ``reload`` section (version, weight
    staleness, swap latency, rollback count).

    ``GET /healthz`` is liveness (the process answers), ``GET /readyz``
    readiness (engine compiled + weights loaded + reloader not
    open-circuit — 503 otherwise, so load balancers rotate a worker whose
    weight supply is broken out before it serves stale scores silently);
    ``readiness`` is a zero-arg callable returning the readiness doc with
    a boolean ``ready`` key (default: ready once the handler exists, which
    is after precompile).

    Observability surfaces (obs/): ``GET /metrics`` renders ``registry``
    (default: the scorer's own) in Prometheus text exposition format;
    ``GET /v1/trace/recent`` serves the bounded recent-traces ring;
    ``GET /v1/flight`` serves the process flight-recorder ring.  Predict
    requests are traced through ``tracer`` (accepting a client-supplied
    ``X-Trace-Id``/``X-Span-Id`` pair, else head-sampling) and every
    traced response carries ``X-Trace-Id``."""
    predict_path = f"/v1/models/{model_name}:predict"
    binary_path = f"/v1/models/{model_name}:predict_binary"
    status_path = f"/v1/models/{model_name}"
    registry = registry if registry is not None \
        else getattr(scorer, "registry", None)
    tracer = tracer if tracer is not None else Tracer(
        model_name, sample_rate=DEFAULT_SAMPLE_RATE)

    class Handler(BaseHTTPRequestHandler):
        # HTTP/1.1 keep-alive: every response carries Content-Length, so
        # persistent connections are safe; without this the stdlib speaks
        # HTTP/1.0 and clients pay a TCP reconnect per request.
        # TCP_NODELAY is mandatory with keep-alive: small request/response
        # exchanges on a persistent socket otherwise hit the Nagle +
        # delayed-ACK interaction (~40 ms stall per round trip, measured)
        protocol_version = "HTTP/1.1"
        disable_nagle_algorithm = True
        _send = _send_json
        _send_plain = _send_text
        obs_tracer = tracer          # member handlers reuse the same head

        def do_GET(self):  # noqa: N802 (http.server API)
            if self.path == "/healthz":
                self._send(200, {"status": "alive"})
            elif self.path == "/metrics" and registry is not None:
                self._send_plain(200, registry.render_prometheus())
            elif self.path == "/v1/trace/recent":
                self._send(200, {"traces": tracer.recent()})
            elif self.path == "/v1/flight":
                self._send(200, {"events": obs_flight.render_events()})
            elif self.path == "/readyz":
                doc = (readiness() if readiness is not None
                       else {"ready": True, "engine_compiled": True,
                             "weights_loaded": True})
                if group_status is not None:
                    doc = {**doc, **group_status()}
                self._send(200 if doc.get("ready") else 503, doc)
            elif self.path == status_path:
                version = "1"
                if reload_status is not None:
                    version = str(reload_status().get("model_version", 0))
                self._send(
                    200,
                    {
                        "model_version_status": [
                            {"version": version, "state": "AVAILABLE"}
                        ]
                    },
                )
            elif (self.path == "/v1/metrics"
                  and hasattr(scorer, "metrics_snapshot")):
                snap = {"model": model_name, **scorer.metrics_snapshot()}
                if reload_status is not None:
                    snap["reload"] = reload_status()
                # tiered engines (deepfm_tpu/tiered TieredScorer — or any
                # engine paging weights) publish cache hit-rate + paging
                # gauges; generic hook so every engine shape gets them
                if "paging" not in snap and hasattr(
                        scorer, "paging_snapshot"):
                    snap["paging"] = scorer.paging_snapshot()
                # funnel engines (deepfm_tpu/funnel FunnelScorer) publish
                # retrieval latency, candidates/s, index version/occupancy
                # and the merge-overflow count — same hook pattern
                if "funnel" not in snap and hasattr(
                        scorer, "funnel_snapshot"):
                    snap["funnel"] = scorer.funnel_snapshot()
                # multi-tenant members (deepfm_tpu/fleet) publish the
                # per-tenant generation/version/engine table — same hook
                if "tenants" not in snap and hasattr(
                        scorer, "tenants_snapshot"):
                    snap["tenants"] = scorer.tenants_snapshot()
                if group_status is not None:
                    snap["router"] = group_status()
                self._send(200, snap)
            else:
                self._send(404, {"error": f"unknown path {self.path!r}"})

        def do_POST(self):  # noqa: N802
            traced = self.path in (predict_path, binary_path)
            ctx = (tracer.begin(self.path.rsplit(":", 1)[-1], self.headers)
                   if traced else None)
            token = tracer.activate(ctx)
            self._obs_status = None
            try:
                self._handle_post()
            finally:
                tracer.finish(ctx, token, status=self._obs_status)

        def _handle_post(self):
            if self.path == binary_path:
                self._predict_binary()
                return
            if self.path != predict_path:
                self._send(404, {"error": f"unknown path {self.path!r}"})
                return
            # parse/validate -> 400 (client's fault); scoring -> 500
            # (server's fault, e.g. a device/runtime error mid-request) so
            # clients and monitoring can tell outages from bad requests
            try:
                length = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(length))
                instances = req["instances"]
            except Exception as e:
                self._send(400, {"error": f"{type(e).__name__}: {e}"})
                return
            try:
                probs = scorer.score_instances(
                    instances, **_slo_kwargs(self.headers, scorer))
            except (ValueError, KeyError, TypeError) as e:
                self._send(400, {"error": f"{type(e).__name__}: {e}"})
                return
            except DeadlineRejectedError as e:
                # admission said no (deadline unmeetable, or the shed
                # ladder dropped this class) — 503 + back-off hint
                self._send(503, {"error": str(e),
                                 "retry_after_s": round(e.retry_after_s, 3)},
                           extra_headers=_retry_after_headers(e))
                return
            except DeadlineExpiredError as e:
                # admitted, then the deadline passed while queued: the
                # engine answered at dequeue without scoring — 504
                self._send(504, {"error": str(e)})
                return
            except OverloadedError as e:
                self._send(503, {"error": str(e)})
                return
            except Exception as e:
                self._send(500, {"error": f"{type(e).__name__}: {e}"})
                return
            doc = {"predictions": [float(p) for p in probs]}
            if reload_status is not None:
                # the engine's LIVE version at response-assembly time.  A
                # request in flight across a swap may have scored on the
                # previous version (at most one behind); per-dispatch
                # attribution would have to thread through the coalescing
                # engine — for exact score provenance compare against the
                # published artifact (its manifest carries param_hash)
                doc["model_version"] = reload_status().get("model_version", 0)
            if group_status is not None:
                gs = group_status()
                doc.update({
                    k: gs[k]
                    for k in ("shard_group", "tenant", "group_generation")
                    if k in gs
                })
            self._send(200, doc)

        def _predict_binary(self):
            # the gRPC-role analog, dependency-free: JSON encode/decode of
            # ~80k numbers dominates the HTTP layer at large client batches
            # (53 ms http vs 11.5 ms scorer at batch 1024, BENCH_SERVING).
            # Wire format (all little-endian):
            #   request:  u32 n, u32 f, n*f int64 feat_ids, n*f f32 feat_vals
            #   response: n f32 probabilities (Content-Type octet-stream)
            try:
                length = int(self.headers.get("Content-Length", "0"))
                buf = self.rfile.read(length)
                if len(buf) < 8:
                    raise ValueError("truncated header")
                n, f = (int(x) for x in np.frombuffer(buf, "<u4", count=2))
                need = 8 + n * f * 12
                if len(buf) != need:
                    raise ValueError(
                        f"body is {len(buf)} bytes, expected {need} "
                        f"for n={n} f={f}"
                    )
                ids = np.frombuffer(
                    buf, "<i8", count=n * f, offset=8
                ).reshape(n, f)
                vals = np.frombuffer(
                    buf, "<f4", count=n * f, offset=8 + n * f * 8
                ).reshape(n, f)
            except Exception as e:
                self._send(400, {"error": f"{type(e).__name__}: {e}"})
                return
            try:
                probs = np.ascontiguousarray(
                    scorer.score(ids, vals,
                                 **_slo_kwargs(self.headers, scorer)),
                    np.float32,
                )
            except (ValueError, KeyError, TypeError) as e:
                self._send(400, {"error": f"{type(e).__name__}: {e}"})
                return
            except DeadlineRejectedError as e:
                self._send(503, {"error": str(e),
                                 "retry_after_s": round(e.retry_after_s, 3)},
                           extra_headers=_retry_after_headers(e))
                return
            except DeadlineExpiredError as e:
                self._send(504, {"error": str(e)})
                return
            except OverloadedError as e:
                self._send(503, {"error": str(e)})
                return
            except Exception as e:
                self._send(500, {"error": f"{type(e).__name__}: {e}"})
                return
            import os as _os

            body = probs.astype("<f4", copy=False).tobytes()
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(len(body)))
            self.send_header("X-Serving-Pid", str(_os.getpid()))
            ctx = current_trace()
            if ctx is not None:
                self.send_header(TRACE_HEADER, ctx.trace_id)
            self._obs_status = 200
            if group_status is not None:
                gs = group_status()
                self.send_header("X-Shard-Group", str(gs.get("shard_group")))
                if "tenant" in gs:
                    self.send_header("X-Tenant", str(gs.get("tenant")))
                self.send_header(
                    "X-Group-Generation", str(gs.get("group_generation"))
                )
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # quiet by default
            pass

    return Handler


def serve_pool(
    servable_dir: str, *, workers: int, port: int = 8501,
    host: str = "127.0.0.1", model_name: str = "deepfm",
    buckets=(8, 32, 128, 512), max_wait_ms: float = 2.0,
    max_queue_rows: int | None = None, item_corpus: str | None = None,
    reload_url: str | None = None, reload_interval_secs: float = 2.0,
    funnel_top_k: int = 0, funnel_return_n: int = 0,
    funnel_retrieval: str = "", funnel_oversample: int = 0,
    funnel_pallas: str = "",
    funnel_data_parallel: int = 1, funnel_model_parallel: int = 0,
    max_restarts: int = 10,
    ready: threading.Event | None = None,
) -> None:
    """Multi-process serving front: ``workers`` processes share ONE port
    via SO_REUSEPORT — each runs its own full server (own GIL, own jitted
    servable, own micro-batching scorer), and the kernel spreads incoming
    connections across them.  This is the concurrency architecture of the
    reference's serving tier (TF Serving's C++ worker pool, ps:535-551)
    expressed Unix-natively: process-level parallelism, no shared state,
    crash isolation (a dead worker is restarted, bounded by
    ``max_restarts``; the survivors keep serving).

    The parent holds a bound (never listening) SO_REUSEPORT placeholder
    socket so ``port=0`` resolves once and every worker binds the same
    resolved port.  Workers are forked BEFORE jax/servable load, so each
    child initializes its own runtime (fork-safety).

    ``GET /healthz``/``/readyz`` ride the shared port like every other
    route: the kernel picks a worker per probe, so repeated probes sample
    the pool — a worker whose reload breaker is open answers 503 on
    ``/readyz`` while the rest keep answering 200.
    """
    import os
    import signal
    import socket
    import time

    placeholder = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    placeholder.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    placeholder.bind((host, port))
    port = placeholder.getsockname()[1]

    def spawn(idx: int) -> int:
        pid = os.fork()
        if pid == 0:
            code = 0
            try:
                # restarted workers fork AFTER the parent installed its
                # supervisor handlers; inherited, they would swallow the
                # shutdown SIGTERM and wedge the pool teardown in waitpid
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                signal.signal(signal.SIGINT, signal.SIG_DFL)
                ScoringHTTPServer.reuse_port = True
                serve_forever(
                    servable_dir, port=port, host=host,
                    model_name=model_name, buckets=buckets,
                    max_wait_ms=max_wait_ms, max_queue_rows=max_queue_rows,
                    item_corpus=item_corpus,
                    # each worker polls + swaps independently; versions are
                    # committed marker-last, so workers converge without
                    # coordination (briefly mixed versions during a rollout)
                    reload_url=reload_url,
                    reload_interval_secs=reload_interval_secs,
                    funnel_top_k=funnel_top_k,
                    funnel_return_n=funnel_return_n,
                    funnel_retrieval=funnel_retrieval,
                    funnel_oversample=funnel_oversample,
                    funnel_pallas=funnel_pallas,
                    funnel_data_parallel=funnel_data_parallel,
                    funnel_model_parallel=funnel_model_parallel,
                )
            except BaseException:
                # the traceback is the only diagnostic a crash-looping
                # worker leaves; status 1 lets the parent's log (and any
                # exit-code monitoring) tell crashes from clean exits
                import traceback

                traceback.print_exc()
                code = 1
            finally:
                os._exit(code)
        return pid

    children = {spawn(i): i for i in range(workers)}
    print(f"serving pool: {workers} workers on {host}:{port}",
          file=sys.stderr)
    if ready is not None:
        ready.port = port  # type: ignore[attr-defined]
        ready.set()

    stop = threading.Event()

    def _terminate(*_):
        stop.set()

    signal.signal(signal.SIGTERM, _terminate)
    signal.signal(signal.SIGINT, _terminate)
    restarts = 0
    try:
        while not stop.is_set():
            try:
                pid, status = os.waitpid(-1, os.WNOHANG)
            except ChildProcessError:
                break
            if pid == 0:
                stop.wait(0.2)
                continue
            idx = children.pop(pid, None)
            if idx is None or stop.is_set():
                continue
            restarts += 1
            if restarts > max_restarts:
                print(f"serving pool: worker {idx} died (status {status}); "
                      f"restart budget exhausted", file=sys.stderr)
                break
            print(f"serving pool: worker {idx} died (status {status}); "
                  f"restarting ({restarts}/{max_restarts})", file=sys.stderr)
            children[spawn(idx)] = idx
    finally:
        for pid in children:
            try:
                os.kill(pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
        # bounded reap: a worker that ignores TERM (wedged request, stuck
        # runtime) is escalated to KILL rather than hanging the pool exit
        remaining = set(children)
        deadline = time.monotonic() + 10.0
        while remaining and time.monotonic() < deadline:
            for pid in list(remaining):
                try:
                    done, _ = os.waitpid(pid, os.WNOHANG)
                except ChildProcessError:
                    remaining.discard(pid)
                    continue
                if done:
                    remaining.discard(pid)
            if remaining:
                stop.wait(0.1)
        for pid in remaining:
            try:
                os.kill(pid, signal.SIGKILL)
                os.waitpid(pid, 0)
            except (ProcessLookupError, ChildProcessError):
                pass
        placeholder.close()


def serve_forever(
    servable_dir: str, *, port: int = 8501, host: str = "127.0.0.1",
    model_name: str = "deepfm", buckets=(8, 32, 128, 512),
    max_wait_ms: float = 2.0, max_queue_rows: int | None = None,
    item_corpus: str | None = None,
    reload_url: str | None = None, reload_interval_secs: float = 2.0,
    funnel_top_k: int = 0, funnel_return_n: int = 0,
    funnel_retrieval: str = "", funnel_oversample: int = 0,
    funnel_pallas: str = "",
    funnel_data_parallel: int = 1, funnel_model_parallel: int = 0,
    trace_sample_rate: float = DEFAULT_SAMPLE_RATE,
    trace_export: str | None = None,
    ready: threading.Event | None = None,
) -> None:
    """Serve whichever servable lives at ``servable_dir``: CTR models get
    ``:predict``; two-tower retrieval gets ``:encode_user``/``:encode_item``
    and — with ``item_corpus`` — ``:retrieve``; funnel servables
    (``funnel.json`` marker, deepfm_tpu/funnel) get ``/v1/recommend`` —
    sharded top-K retrieval into live-weight ranking as one
    version-consistent system.  All ride the bucketed micro-batching
    engine (serve/batcher.py), precompiled before the socket opens so the
    first request never pays a compile.

    ``reload_url`` (a publish root — local dir or object URL written by
    ``online/publisher.py``) turns on zero-downtime hot weight reload: the
    params ride the precompiled bucket executables as arguments, a
    HotSwapper polls for new versions every ``reload_interval_secs``, and
    swaps pass canary + drain before traffic sees them (serve/reload.py).
    For funnel servables the reload root must hold FunnelPublisher
    versions: ranking weights and the retrieval index swap as ONE payload
    (funnel/serve.py FunnelSwapper)."""
    import os

    from ..funnel.publish import is_funnel_servable
    from .export import _load_config, load_retrieval_servable, load_servable

    buckets = _parse_buckets(buckets)
    if is_funnel_servable(os.path.abspath(servable_dir)):
        from ..funnel.serve import serve_funnel

        if item_corpus:
            raise ValueError(
                "--item-corpus applies to two-tower servables; a funnel "
                "servable carries its own published index"
            )
        serve_funnel(
            os.path.abspath(servable_dir), port=port, host=host,
            model_name=model_name, buckets=buckets,
            max_wait_ms=max_wait_ms, max_queue_rows=max_queue_rows,
            reload_url=reload_url,
            reload_interval_secs=reload_interval_secs,
            top_k=funnel_top_k, return_n=funnel_return_n,
            retrieval=funnel_retrieval, oversample=funnel_oversample,
            pallas=funnel_pallas,
            data_parallel=funnel_data_parallel,
            model_parallel=funnel_model_parallel,
            trace_sample_rate=trace_sample_rate,
            trace_export=trace_export,
            ready=ready,
        )
        return
    cfg = _load_config(os.path.abspath(servable_dir))
    if reload_url and cfg.model.model_name == "two_tower":
        raise ValueError(
            "--reload-url supports CTR servables only (two-tower serving "
            "has no hot-swap path yet)"
        )
    # ONE observability registry + trace head per serving process: the
    # engine, the hot swapper and the handler all render into it, so
    # GET /metrics is the process's full picture.  Fresh requests are
    # head-sampled at the shipped default; propagated X-Trace-Ids are
    # always recorded (obs/trace.py DEFAULT_SAMPLE_RATE).
    registry = MetricsRegistry()
    tracer = Tracer("server", sample_rate=trace_sample_rate,
                    export_path=trace_export)
    if cfg.model.model_name == "two_tower":
        encode_user, encode_item, cfg = load_retrieval_servable(servable_dir)
        rscorer = RetrievalScorer(
            encode_user, encode_item, cfg, buckets=buckets,
            max_wait_ms=max_wait_ms, max_queue_rows=max_queue_rows,
            registry=registry,
        )
        compiles = rscorer.precompile()
        if item_corpus:
            n = rscorer.load_corpus(item_corpus)
            print(f"encoded item corpus: {n} items", file=sys.stderr)
        handler = make_retrieval_handler(rscorer, model_name, tracer=tracer)
        endpoint = "encode_user|encode_item|retrieve"
    else:
        if item_corpus:
            raise ValueError(
                f"--item-corpus only applies to two-tower servables; "
                f"{servable_dir!r} holds {cfg.model.model_name!r}"
            )
        reload_status = None
        if reload_url:
            from .reload import HotSwapper, load_swappable_servable

            predict, predict_with, holder, cfg = load_swappable_servable(
                servable_dir
            )
            swapper = HotSwapper(
                holder, predict_with, reload_url, cfg,
                interval_secs=reload_interval_secs, registry=registry,
            )
            # adopt any already-published version BEFORE the socket opens,
            # then poll in the background
            swapper.poll_once()
            swapper.start()
            reload_status = swapper.status
        else:
            predict, cfg = load_servable(servable_dir)
        scorer = MicroBatcher(
            predict, cfg.model.field_size, buckets=buckets,
            max_wait_ms=max_wait_ms, max_queue_rows=max_queue_rows,
            registry=registry,
        )
        compiles = scorer.precompile()

        def readiness():
            # the handler exists only after load + precompile, so those
            # legs are tautologically true; the live signal is the
            # reloader's circuit — open means the weight supply is broken
            # (store outage) and this worker may be serving stale scores
            doc = {"ready": True, "engine_compiled": True,
                   "weights_loaded": True}
            if reload_status is not None:
                st = reload_status()
                breaker = st.get("breaker") or {}
                doc["model_version"] = st.get("model_version")
                doc["reload_breaker"] = breaker.get("state", "closed")
                doc["ready"] = breaker.get("state") != "open"
            return doc

        handler = make_handler(scorer, model_name,
                               reload_status=reload_status,
                               readiness=readiness,
                               registry=registry, tracer=tracer)
        endpoint = "predict"
    print(f"precompiled bucket executables: {compiles}", file=sys.stderr)
    httpd = ScoringHTTPServer((host, port), handler)
    if ready is not None:
        ready.port = httpd.server_address[1]  # type: ignore[attr-defined]
        ready.set()
    print(
        f"serving {model_name} on http://{httpd.server_address[0]}:"
        f"{httpd.server_address[1]}/v1/models/{model_name}:{endpoint}",
        file=sys.stderr,
    )
    httpd.serve_forever()


def score_stdin(
    servable_dir: str, *, batch_size: int = 256,
    buckets=(8, 32, 128, 512),
) -> int:
    """libsvm or JSONL lines on stdin -> one probability per line.

    Lines buffer up to ``batch_size`` per flush; each flush scores through
    the bucketed engine with ``max_wait_ms=0`` (a pipeline has exactly one
    caller — coalescing across callers can't happen, so any admission wait
    would be pure added latency)."""
    from ..data.libsvm import parse_libsvm_line
    from .export import load_servable

    predict, cfg = load_servable(servable_dir)
    # a full flush is exactly batch_size rows: make that an exact bucket
    # shape, or every full flush would pad up to the next power of two
    # (256 -> 512 doubles the compute of the steady-state case)
    bucket_set = set(_parse_buckets(buckets)) | {int(batch_size)}
    scorer = MicroBatcher(
        predict, cfg.model.field_size, buckets=sorted(bucket_set),
        max_wait_ms=0.0,
    )
    count = 0
    buf_ids: list[list[int]] = []
    buf_vals: list[list[float]] = []

    def flush():
        nonlocal count
        if not buf_ids:
            return
        probs = scorer.score(
            np.asarray(buf_ids, np.int64), np.asarray(buf_vals, np.float32)
        )
        for p in probs:
            sys.stdout.write(f"{float(p):.6f}\n")
        sys.stdout.flush()  # pipeline consumers see results per batch
        count += len(buf_ids)
        buf_ids.clear()
        buf_vals.clear()

    try:
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            if line.startswith("{"):
                obj = json.loads(line)
                buf_ids.append(obj["feat_ids"])
                buf_vals.append(obj["feat_vals"])
            else:
                _, ids, vals = parse_libsvm_line(line)
                buf_ids.append(ids)
                buf_vals.append(vals)
            if len(buf_ids) >= batch_size:
                flush()
        flush()
    finally:
        scorer.close()  # in-process callers must not leak worker threads
    sys.stdout.flush()
    return count


def main(argv: list[str] | None = None) -> int:
    from ..core.platform import sanitize_backend

    sanitize_backend()
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--servable", required=True)
    ap.add_argument("--port", type=int, default=8501)
    ap.add_argument("--host", default="127.0.0.1",
                    help="bind address (0.0.0.0 for non-loopback clients)")
    ap.add_argument(
        "--item-corpus", default=None,
        help="two-tower only: JSONL item corpus "
             '({"id": N, "item_ids": [...], "item_vals": [...]} per line) '
             "encoded at startup to enable the :retrieve endpoint",
    )
    ap.add_argument("--model-name", default="deepfm")
    ap.add_argument(
        "--buckets", default="8,32,128,512",
        help="micro-batch bucket sizes (comma-separated, ascending): "
             "coalesced requests pad to the smallest bucket that fits; "
             "each bucket is one precompiled XLA executable",
    )
    ap.add_argument(
        "--max-wait-ms", type=float, default=2.0,
        help="admission timeout: max time a request waits for bucket-mates "
             "on an idle engine (under load the previous dispatch is the "
             "coalescing window and no extra wait happens)",
    )
    ap.add_argument(
        "--max-queue-rows", type=int, default=None,
        help="queue-depth bound in rows (default 16x the largest bucket); "
             "beyond it requests are shed with HTTP 503",
    )
    ap.add_argument(
        "--batch-size", type=int, default=256,
        help="stdin mode only: lines buffered per scoring flush",
    )
    ap.add_argument(
        "--workers", type=int, default=1,
        help="N>1: SO_REUSEPORT process pool — N independent server "
             "processes share the port, kernel load-balances connections "
             "(the TF-Serving worker-pool analog; crash-isolated, "
             "auto-restarted)",
    )
    ap.add_argument(
        "--stdin", action="store_true",
        help="score stdin lines (libsvm or JSONL) instead of serving HTTP",
    )
    ap.add_argument(
        "--reload-url", default=None,
        help="publish root (dir or object URL, online/publisher.py) to poll "
             "for new model versions; new weights hot-swap under the "
             "precompiled bucket executables with canary + drain — zero "
             "downtime, zero recompiles",
    )
    ap.add_argument(
        "--reload-interval", type=float, default=2.0,
        help="seconds between manifest polls when --reload-url is set",
    )
    ap.add_argument(
        "--funnel-top-k", type=int, default=0,
        help="funnel servables: candidates retrieved per user "
             "(0 = the servable's funnel.json default)",
    )
    ap.add_argument(
        "--funnel-return-n", type=int, default=0,
        help="funnel servables: ranked items returned per user "
             "(0 = the servable's funnel.json default)",
    )
    ap.add_argument(
        "--funnel-retrieval", default="",
        choices=("", "exact", "int8", "auto"),
        help="funnel retrieval tier: exact f32 scoring, int8 quantized "
             "scoring with exact f32 rescore of the oversampled "
             "shortlist, or auto (int8 once the index capacity crosses "
             "funnel/quant.AUTO_INT8_MIN_ROWS); '' = the servable's "
             "published retrieval section",
    )
    ap.add_argument(
        "--funnel-oversample", type=int, default=0,
        help="int8 shortlist width multiplier (K*oversample candidates "
             "survive the quantized pass into the exact rescore; "
             "0 = the servable's published value)",
    )
    ap.add_argument(
        "--funnel-pallas", default="", choices=("", "on", "off", "auto"),
        help="the fused Pallas score/top-k retrieval kernel: on | off | "
             "auto (TPU backends, compile-probe fallback); '' = auto",
    )
    ap.add_argument(
        "--funnel-dp", type=int, default=1,
        help="funnel mesh: request-batch shard factor (buckets must "
             "divide by it)",
    )
    ap.add_argument(
        "--funnel-mp", type=int, default=0,
        help="funnel mesh: index row-shard factor "
             "(0 = remaining devices / funnel-dp)",
    )
    ap.add_argument(
        "--trace-sample", type=float, default=DEFAULT_SAMPLE_RATE,
        help="head-based trace sampling rate for FRESH requests "
             "(propagated/client-supplied X-Trace-Ids are always "
             "recorded); 0 disables minting, 1 traces everything",
    )
    ap.add_argument(
        "--trace-export", default=None,
        help="optional JSONL file to append every finished trace to "
             "(offline correlation with the flight recorder)",
    )
    ap.add_argument(
        "--flight-dump", default=None,
        help="arm the flight-recorder termination dump: the event ring "
             "is written here as JSONL when SIGTERM lands or the process "
             "crashes (obs/flight.py; the live ring is always at "
             "GET /v1/flight)",
    )
    args = ap.parse_args(argv)
    if args.flight_dump:
        obs_flight.install(args.flight_dump)
        # no PreemptionGuard in a serve process — route SIGTERM through
        # the dump, then re-deliver with the default action (terminate)
        obs_flight.dump_on_signal()
    if args.stdin:
        score_stdin(args.servable, batch_size=args.batch_size,
                    buckets=args.buckets)
        return 0
    if args.workers > 1:
        serve_pool(
            args.servable, workers=args.workers, port=args.port,
            host=args.host, model_name=args.model_name,
            buckets=args.buckets, max_wait_ms=args.max_wait_ms,
            max_queue_rows=args.max_queue_rows,
            item_corpus=args.item_corpus,
            reload_url=args.reload_url,
            reload_interval_secs=args.reload_interval,
            funnel_top_k=args.funnel_top_k,
            funnel_return_n=args.funnel_return_n,
            funnel_retrieval=args.funnel_retrieval,
            funnel_oversample=args.funnel_oversample,
            funnel_pallas=args.funnel_pallas,
            funnel_data_parallel=args.funnel_dp,
            funnel_model_parallel=args.funnel_mp,
        )
        return 0
    serve_forever(
        args.servable, port=args.port, host=args.host,
        model_name=args.model_name, buckets=args.buckets,
        max_wait_ms=args.max_wait_ms, max_queue_rows=args.max_queue_rows,
        item_corpus=args.item_corpus,
        reload_url=args.reload_url,
        reload_interval_secs=args.reload_interval,
        funnel_top_k=args.funnel_top_k,
        funnel_return_n=args.funnel_return_n,
        funnel_retrieval=args.funnel_retrieval,
        funnel_oversample=args.funnel_oversample,
        funnel_pallas=args.funnel_pallas,
        funnel_data_parallel=args.funnel_dp,
        funnel_model_parallel=args.funnel_mp,
        trace_sample_rate=args.trace_sample,
        trace_export=args.trace_export,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
