"""Group-atomic hot swap: all members of a shard-group, or none.

``serve/reload.py`` swaps ONE process; a shard-group is several members
that must never serve different versions to the same traffic (the router
retries across members, so a half-swapped group would score one request's
retry on different weights than its first attempt).  The coordinator runs
the classic two-phase shape over the members' admin surface (worker.py):

1. **stage everywhere** — every member fetches, hash-verifies, and
   CANARIES the version off-traffic.  Any failure aborts the whole group
   (``/admin:abort`` to every member): nothing was ever live, the group
   stays on the old version and generation (``rollbacks_total``).
2. **commit everywhere** — each member atomically repoints its payload
   and adopts generation G+1 (drain-aware).  A commit can only fail if a
   member died between phases; then every already-committed member is
   ROLLED BACK (``/admin:rollback`` — members retain the pre-commit
   payload for exactly this) and the rest aborted, returning the whole
   group to generation G.

**Version-skew protection across the window**: between the first and last
member commit the group momentarily spans two generations — but the
router pins every request to one generation and members refuse
(409-skew-abort) rather than score a mismatched pin, so no REQUEST ever
observes the mixed state; the window only costs a few re-pinned retries.

**Respawn repair**: a member process that crashed and respawned restarts
at generation 0 serving the BASE servable — stale the moment the group
has ever swapped.  Every poll also runs :meth:`GroupSwapper.repair_once`:
lagging members (read off ``/readyz``) are staged+committed back to the
group's current version at the group's current generation (the member's
commit accepts the forward jump), so a restart costs seconds of staleness
behind an ejected router slot, never a permanently-stale member or a
wedged swap pipeline.

Store-facing discovery (``latest_manifest``) runs behind a circuit
breaker exactly like the single-process HotSwapper: an outage costs one
probe per cooldown while the old weights keep serving.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

from ...online.publisher import latest_manifest
from ...utils.retry import CircuitBreaker


class GroupSwapper:
    """Coordinate group-atomic version swaps for ONE shard-group.

    ``members`` are the members' base URLs (their worker.py admin surface).
    ``poll_once`` is the whole protocol; ``start`` polls on a background
    thread.  ``generation`` mirrors the members' committed group
    generation (they start at 0 and move in lockstep — any divergence is
    a protocol violation the members' successor check catches)."""

    def __init__(
        self,
        members: list[str],
        source: str,
        *,
        group: str = "g0",
        tenant: str | None = None,
        interval_secs: float = 2.0,
        admin_timeout_secs: float = 120.0,
        breaker: CircuitBreaker | None = None,
    ):
        if not members:
            raise ValueError("a shard-group needs at least one member")
        self.group = group
        # one coordinator per (group, TENANT): each tenant's publish root
        # is its own manifest stream, staged/committed onto that tenant's
        # per-member slot only — tenant A's swap (or rollback) is
        # structurally unable to touch tenant B's state (worker.py keys
        # generations and payloads by tenant).  None = the legacy
        # tenant-less protocol against single-tenant members.
        self.tenant = tenant
        self._members = list(members)
        self._source = source
        self._interval = float(interval_secs)
        self._timeout = float(admin_timeout_secs)
        self._breaker = breaker if breaker is not None else CircuitBreaker(
            failure_threshold=0.5, window=6, min_calls=3,
            cooldown_secs=max(5.0, 4.0 * self._interval),
            name=f"swap[{group}]",
        )
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.generation = 0
        self.version = 0
        self.swaps_total = 0
        self.rollbacks_total = 0
        self.repairs_total = 0
        self.poll_errors_total = 0
        self.polls_skipped_total = 0
        self.last_swap_ms: float | None = None
        self.last_error: str | None = None

    # -- member RPC ---------------------------------------------------------
    def _admin(self, member_url: str, verb: str, body: dict) -> dict:
        if self.tenant is not None:
            body = {**body, "tenant": self.tenant}
        req = urllib.request.Request(
            f"{member_url}/admin:{verb}",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self._timeout) as r:
            return json.load(r)

    def _admin_quiet(self, member_url: str, verb: str) -> bool:
        """Best-effort abort/rollback leg: a member that is DOWN needs no
        rollback (its restart re-loads the committed-on-disk servable at
        the old version), so failures here are recorded, not raised."""
        try:
            self._admin(member_url, verb, {})
            return True
        except Exception as e:
            # secondary failure on the cleanup leg: keep it visible (the
            # caller's primary error overwrites it, which is the right
            # precedence), never let it mask the abort/rollback sweep
            with self._lock:
                self.last_error = (
                    f"{verb} {member_url}: {type(e).__name__}: {e}"
                )
            return False

    # -- the protocol -------------------------------------------------------
    def swap_to(self, version: int) -> bool:
        """Stage+commit ``version`` across the group, or roll back.
        Returns True only when EVERY member committed."""
        version = int(version)
        staged: list[str] = []
        t0 = time.perf_counter()
        for m in self._members:
            try:
                self._admin(m, "stage", {"version": version,
                                         "source": self._source})
                staged.append(m)
            except Exception as e:
                for s in staged:
                    self._admin_quiet(s, "abort")
                with self._lock:
                    self.rollbacks_total += 1
                    self.last_error = (
                        f"stage {m}: {type(e).__name__}: {e} — group "
                        f"aborted at generation {self.generation}"
                    )
                return False
        new_gen = self.generation + 1
        committed: list[str] = []
        for m in self._members:
            try:
                self._admin(m, "commit", {"generation": new_gen,
                                          "version": version})
                committed.append(m)
            except Exception as e:
                # partial commit: un-commit the committed, abort the rest.
                # The FAILED member gets a rollback too: its commit may
                # have SUCCEEDED with only the response lost (a timeout
                # across the drain window) — left alone it would sit
                # AHEAD of the group and veto every future swap's
                # generation.  If it never committed, the rollback is a
                # refused no-op (_admin_quiet swallows the 409).
                self._admin_quiet(m, "rollback")
                for c in committed:
                    self._admin_quiet(c, "rollback")
                for s in staged:
                    if s not in committed:
                        self._admin_quiet(s, "abort")
                with self._lock:
                    self.rollbacks_total += 1
                    self.last_error = (
                        f"commit {m}: {type(e).__name__}: {e} — group "
                        f"rolled back to generation {self.generation}"
                    )
                return False
        with self._lock:
            self.generation = new_gen
            self.version = version
            self.swaps_total += 1
            self.last_swap_ms = round(1e3 * (time.perf_counter() - t0), 3)
            self.last_error = None
        return True

    def repair_once(self) -> int:
        """Re-converge members that drifted BEHIND the group's committed
        state — a respawned worker restarts at generation 0 serving the
        base servable, which is stale the moment the group has ever
        swapped.  Reads each member's ``/readyz`` (it carries
        ``model_version`` + ``group_generation``) and stages+commits the
        group's CURRENT version at the group's CURRENT generation on any
        lagging member (worker.commit accepts the forward jump).
        Returns how many members were repaired; unreachable members are
        left for the next poll (the router keeps them ejected)."""
        if self.version <= 0:
            return 0
        repaired = 0
        for m in self._members:
            try:
                req = urllib.request.Request(m + "/readyz")
                with urllib.request.urlopen(req, timeout=5) as r:
                    doc = json.load(r)
            except (urllib.error.URLError, OSError, ValueError):
                continue  # down or not ready: the next poll retries
            if self.tenant is not None:
                # per-tenant repair reads the readiness doc's tenants map
                # (worker.readiness): a respawned member restarts EVERY
                # tenant at generation 0, and each tenant's coordinator
                # re-converges its own slice
                td = (doc.get("tenants") or {}).get(self.tenant)
                if td is None:
                    continue  # member predates the tenant: next poll
                gen = int(td.get("generation", -1))
                ver = int(td.get("model_version", -1))
            else:
                gen = int(doc.get("group_generation", -1))
                ver = int(doc.get("model_version", -1))
            if ver == self.version and gen == self.generation:
                continue
            if gen > self.generation:
                # AHEAD of the group: a lost-response commit the failure
                # sweep could not reach — return it to the committed
                # group state (the member retains its pre-commit payload
                # for exactly this)
                if self._admin_quiet(m, "rollback"):
                    repaired += 1
                continue
            try:
                self._admin(m, "stage", {"version": self.version,
                                         "source": self._source})
                self._admin(m, "commit", {"generation": self.generation,
                                          "version": self.version})
                repaired += 1
            except Exception as e:
                with self._lock:
                    self.last_error = (
                        f"repair {m}: {type(e).__name__}: {e}"
                    )
        with self._lock:
            self.repairs_total += repaired
        return repaired

    def poll_once(self) -> bool:
        """Discover the latest committed version; swap the group to it.
        Also runs the member repair pass (``repair_once``) so a
        respawned member re-converges to the group's committed state
        instead of serving the stale base servable forever.  Never
        raises (the HotSwapper discipline: discovery failures feed the
        breaker; swap failures roll back and are counted)."""
        if not self._breaker.allow():
            with self._lock:
                self.polls_skipped_total += 1
            return False
        try:
            manifest = latest_manifest(self._source)
        except Exception as e:
            self._breaker.record_failure()
            with self._lock:
                self.poll_errors_total += 1
                self.last_error = f"poll: {type(e).__name__}: {e}"
            return False
        self._breaker.record_success()
        if manifest is None or manifest.version <= self.version:
            self.repair_once()
            return False
        return self.swap_to(manifest.version)

    # -- background polling -------------------------------------------------
    def start(self) -> "GroupSwapper":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"group-swapper-{self.group}",
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            self.poll_once()
            self._stop.wait(self._interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def status(self) -> dict:
        with self._lock:
            return {
                "group": self.group,
                "tenant": self.tenant,
                "members": len(self._members),
                "generation": self.generation,
                "version": self.version,
                "swaps_total": self.swaps_total,
                "rollbacks_total": self.rollbacks_total,
                "repairs_total": self.repairs_total,
                "poll_errors_total": self.poll_errors_total,
                "polls_skipped_total": self.polls_skipped_total,
                "breaker": self._breaker.status(),
                "last_swap_ms": self.last_swap_ms,
                "last_error": self.last_error,
            }
