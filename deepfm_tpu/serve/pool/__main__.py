"""Run the router-fronted shard-group pool.

    python -m deepfm_tpu.serve.pool --servable D --router \
        --groups 2 --group-dp 1 --group-mp 4 --port 8500 \
        [--reload-url PUBLISH_ROOT]

The supervisor process (this one) never initializes a jax backend: it
spawns one MEMBER PROCESS per shard-group (each re-executes this module
with ``--member-entry``, builds its serve mesh, loads the row-sharded
servable, and serves on ``member-port-base + index``), runs the router
front and — when ``--reload-url`` is given — one group-atomic
:class:`~.swap.GroupSwapper` per group.

**Crash handling**: each member process runs under
``utils/retry.run_with_restarts`` — a dead worker is respawned with
bounded EQUAL-jitter backoff (the resource under pressure gets an actual
rest), and the router keeps the respawning member ejected until its
``/readyz`` passes again (engine precompiled, weights loaded).

One member process per host is the deployment shape: the group's mesh
spans that host's devices and the exchange rides ICI; the CPU developer
topology gives every member process its own virtual device set.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import threading


def _load_tenants(arg: str):
    """Parse ``--tenants``: inline JSON, or ``@path`` to a JSON file.
    Returns validated TenantSpecs (deepfm_tpu/fleet)."""
    from ...fleet.registry import parse_tenants

    if not arg:
        return ()
    text = arg
    if arg.startswith("@"):
        with open(arg[1:]) as f:
            text = f.read()
    return parse_tenants(text)


def _parse_slo(arg: str):
    """Parse ``--slo``: inline JSON, or ``@path`` to a JSON file, with
    the keys of :class:`~...core.config.SloConfig`.  Unknown keys are an
    error here (operator CLI, not a forward-compatible config file)."""
    import dataclasses

    from ...core.config import SloConfig

    if not arg:
        return None
    text = arg
    if arg.startswith("@"):
        with open(arg[1:]) as f:
            text = f.read()
    d = json.loads(text)
    names = {f.name for f in dataclasses.fields(SloConfig)}
    unknown = sorted(set(d) - names)
    if unknown:
        raise SystemExit(
            f"--slo: unknown key(s) {unknown}; valid: {sorted(names)}"
        )
    return SloConfig(**d)


def _member_argv(args, group: str, index: int, port: int) -> list[str]:
    argv = [
        sys.executable, "-m", "deepfm_tpu.serve.pool", "--member-entry",
        "--servable", args.servable, "--group", group,
        "--member-port", str(port),
        "--group-dp", str(args.group_dp), "--group-mp", str(args.group_mp),
        "--buckets", args.buckets, "--max-wait-ms", str(args.max_wait_ms),
        "--model-name", args.model_name, "--host", args.host,
    ]
    if args.exchange:
        argv += ["--exchange", args.exchange]
    if args.slo:
        argv += ["--slo", args.slo]
    if args.reload_url:
        argv += ["--reload-url", args.reload_url]
    if args.tenants:
        argv += ["--tenants", args.tenants]
    if args.funnel_top_k:
        argv += ["--funnel-top-k", str(args.funnel_top_k)]
    if args.funnel_return_n:
        argv += ["--funnel-return-n", str(args.funnel_return_n)]
    if args.funnel_retrieval:
        argv += ["--funnel-retrieval", args.funnel_retrieval]
    if args.funnel_oversample:
        argv += ["--funnel-oversample", str(args.funnel_oversample)]
    if args.funnel_pallas:
        argv += ["--funnel-pallas", args.funnel_pallas]
    if args.flight_dump:
        # one timeline file per process: members suffix their group name
        argv += ["--flight-dump", f"{args.flight_dump}.{group}"]
    return argv


def _supervise_member(args, group: str, index: int, port: int,
                      stop: threading.Event) -> None:
    """One member's crash-restart loop: spawn, wait, raise on abnormal
    exit, respawn under the bounded equal-jitter schedule."""
    from ...utils.retry import RetryPolicy, run_with_restarts

    def spawn_and_wait() -> None:
        if stop.is_set():
            return
        proc = subprocess.Popen(_member_argv(args, group, index, port))
        try:
            while proc.poll() is None:
                if stop.wait(0.5):
                    proc.terminate()
                    proc.wait(timeout=30)
                    return
        finally:
            if proc.poll() is None:
                proc.kill()
        if proc.returncode != 0 and not stop.is_set():
            raise RuntimeError(
                f"member {group} exited with status {proc.returncode}"
            )

    try:
        run_with_restarts(
            spawn_and_wait,
            max_restarts=args.max_restarts,
            policy=RetryPolicy(
                max_attempts=args.max_restarts + 1,
                base_delay_secs=args.restart_backoff_secs,
                max_delay_secs=8 * args.restart_backoff_secs,
                jitter="equal",
            ),
            on_restart=lambda n, e, d: print(
                f"pool: member {group} died ({e}); respawn {n}/"
                f"{args.max_restarts} in {d:.1f}s", file=sys.stderr,
            ),
        )
    except Exception as e:
        print(f"pool: member {group} restart budget exhausted: {e}",
              file=sys.stderr)


def _run_member(args) -> int:
    from .worker import serve_member

    if args.flight_dump:
        from ...obs import flight as obs_flight

        obs_flight.install(args.flight_dump)
        # the supervisor tears members down with SIGTERM (terminate());
        # dump the timeline on the way out, then die as before
        obs_flight.dump_on_signal()
    serve_member(
        args.servable, group=args.group,
        data_parallel=args.group_dp, model_parallel=args.group_mp,
        group_index=0,  # a member process owns its host's whole device set
        model_name=args.model_name, host=args.host,
        port=args.member_port,
        buckets=tuple(int(x) for x in args.buckets.split(",")),
        max_wait_ms=args.max_wait_ms,
        exchange=args.exchange or None,
        source=args.reload_url or None,
        funnel_top_k=args.funnel_top_k,
        funnel_return_n=args.funnel_return_n,
        funnel_retrieval=args.funnel_retrieval,
        funnel_oversample=args.funnel_oversample,
        funnel_pallas=args.funnel_pallas,
        tenants=_load_tenants(args.tenants) or None,
        slo=_parse_slo(args.slo),
    )
    return 0


def _start_autoscaler(args, slo, router, shutdown, state_lock, groups,
                      start_group, stop_group) -> threading.Thread:
    """The elastic shard-group control loop (the execution half of
    serve/control/autoscale.py): every second, fold the router's
    aggregate utilization + worst-group p95 into the AutoScaler; on
    "up", spawn a member, wait out its ``/readyz`` gate, admit it to the
    ring; on "down", stop admitting to the emptiest group, wait its
    in-flight to zero, terminate it.  Runs OUTSIDE any jitted graph —
    pure host threads over HTTP; audit_control_plane pins that."""
    import time

    from ..control.autoscale import AutoScaler

    scaler = AutoScaler(
        min_groups=(slo.min_groups if slo else 1),
        max_groups=(slo.max_groups if slo else 4),
        up_util=(slo.scale_up_util if slo else 0.75),
        down_util=(slo.scale_down_util if slo else 0.25),
        slo_ms=(slo.deadline_ms if slo else 0.0),
        up_window_secs=(slo.scale_up_window_secs if slo else 5.0),
        down_window_secs=(slo.scale_down_window_secs if slo else 30.0),
        cooldown_secs=(slo.cooldown_secs if slo else 10.0),
    )
    largest = max(int(x) for x in args.buckets.split(","))

    def _ready(url: str, timeout_secs: float = 180.0) -> bool:
        import urllib.request

        deadline = time.monotonic() + timeout_secs
        while time.monotonic() < deadline and not shutdown.is_set():
            try:
                with urllib.request.urlopen(url + "/readyz",
                                            timeout=2) as r:
                    if json.load(r).get("ready"):
                        return True
            # da:allow[swallowed-exception] readiness poll: refused/reset while the group warms up IS the not-ready signal; the deadline bounds the loop
            except Exception:
                pass
            time.sleep(0.5)
        return False

    def _scale_up() -> None:
        with state_lock:
            used = {st["index"] for st in groups.values()}
        index = next(i for i in range(4096) if i not in used)
        name = f"g{index}"
        url = start_group(name, index)
        # stage -> ready -> admit: the new group takes ZERO traffic
        # until its engine precompiled and weights loaded (/readyz)
        if not _ready(url):
            print(f"pool: scale-up {name} never became ready; "
                  f"tearing it back down", file=sys.stderr)
            stop_group(name)
            scaler.note_scaled(time.monotonic())
            return
        router.add_group(name, [url])
        print(f"pool: scaled UP: admitted {name} at {url}",
              file=sys.stderr)
        scaler.note_scaled(time.monotonic())

    def _scale_down() -> None:
        live = router.group_names()
        with state_lock:
            candidates = [g for g in live if g in groups]
        if len(candidates) <= 1:
            return
        # the emptiest group drains fastest (graceful degradation:
        # admitted work always finishes)
        victim = min(candidates, key=router.group_inflight)
        router.remove_group(victim)           # stop admitting
        deadline = time.monotonic() + 60.0
        while (router.group_inflight(victim) > 0
               and time.monotonic() < deadline):
            time.sleep(0.1)                   # wait out in-flight
        stop_group(victim)                    # terminate
        print(f"pool: scaled DOWN: drained and removed {victim}",
              file=sys.stderr)
        scaler.note_scaled(time.monotonic())

    def _loop() -> None:
        while not shutdown.wait(1.0):
            try:
                snap = router.metrics_snapshot()
                gs = snap["groups"]
                n = len(gs) or 1
                # utilization: router-tracked in-flight rows against the
                # pool's one-big-dispatch-per-group capacity proxy
                util = (sum(g["inflight_rows"] for g in gs.values())
                        / (n * largest))
                p95s = [(g.get("latency_ms") or {}).get("p95")
                        for g in gs.values()]
                p95s = [p for p in p95s if p is not None]
                action = scaler.observe(
                    time.monotonic(), groups=n, util=util,
                    p95_ms=max(p95s) if p95s else None,
                )
                if action == "up":
                    _scale_up()
                elif action == "down":
                    _scale_down()
            except Exception as e:
                # the control loop must outlive any one bad sample
                print(f"pool: autoscale loop error: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)

    t = threading.Thread(target=_loop, daemon=True, name="autoscaler")
    t.start()
    return t


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="deepfm-serve-pool", description=__doc__.splitlines()[0]
    )
    ap.add_argument("--servable", required=True)
    ap.add_argument("--router", action="store_true",
                    help="run the consistent-hashing router front")
    ap.add_argument("--groups", type=int, default=1,
                    help="shard-group count (one member process each)")
    ap.add_argument("--group-dp", type=int, default=1,
                    help="data-parallel degree inside each group's mesh")
    ap.add_argument("--group-mp", type=int, default=0,
                    help="row-shard degree inside each group's mesh "
                         "(0 = auto: the member host's devices / dp)")
    ap.add_argument("--port", type=int, default=8500,
                    help="router bind port")
    ap.add_argument("--member-port-base", type=int, default=8601)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--model-name", default="deepfm")
    ap.add_argument("--buckets", default="8,32,128,512")
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--exchange", default="",
                    help="psum|alltoall (default: config 'auto' resolution)")
    ap.add_argument("--reload-url", default="",
                    help="publish root: each group gets a group-atomic "
                         "swap coordinator polling it")
    ap.add_argument("--reload-interval", type=float, default=2.0)
    ap.add_argument(
        "--tenants", default="",
        help="multi-tenant fleet (deepfm_tpu/fleet): inline JSON or "
             "@file — [{\"name\", \"source\", \"split_percent\", "
             "\"shadow_of\"}...].  Members serve every tenant from one "
             "executable set; the router splits traffic hash-stably and "
             "runs shadow challengers off the response path; each "
             "(group, tenant) gets its own group-atomic swap coordinator",
    )
    ap.add_argument("--shadow-sample", type=float, default=100.0,
                    help="percent of the incumbent's stream the shadow "
                         "challenger re-scores (hash-stable per key)")
    ap.add_argument("--shadow-queue", type=int, default=128,
                    help="bounded shadow queue depth; overflow sheds")
    ap.add_argument("--funnel-top-k", type=int, default=0,
                    help="funnel servables: candidates retrieved per user "
                         "(0 = the servable's funnel.json default)")
    ap.add_argument("--funnel-return-n", type=int, default=0,
                    help="funnel servables: ranked items returned per "
                         "user (0 = the servable's funnel.json default)")
    ap.add_argument("--funnel-retrieval", default="",
                    choices=("", "exact", "int8", "auto"),
                    help="funnel retrieval tier: exact | int8 (quantized "
                         "scoring + exact f32 rescore of the oversampled "
                         "shortlist) | auto; '' = the servable's "
                         "published retrieval section")
    ap.add_argument("--funnel-oversample", type=int, default=0,
                    help="int8 shortlist width multiplier "
                         "(0 = the servable's published value)")
    ap.add_argument("--funnel-pallas", default="",
                    choices=("", "on", "off", "auto"),
                    help="the fused Pallas score/top-k retrieval kernel: "
                         "on | off | auto; '' = auto")
    ap.add_argument(
        "--slo", default="",
        help="SLO control plane (serve/control/): inline JSON or @file "
             "with SloConfig keys (core/config.py) — deadline_ms turns "
             "on deadline-aware admission at every member and arms "
             "router hedging; retry_budget_pct/hedge_budget_pct cap the "
             "retry/hedge token buckets; shed_*_util set the priority "
             "shed ladder; min/max_groups + scale_*_util bound the "
             "autoscaler",
    )
    ap.add_argument(
        "--autoscale", action="store_true",
        help="elastic shard-groups: watch router utilization + SLO "
             "attainment, spawn a group (stage -> /readyz -> admit) on "
             "sustained breach, drain the emptiest on sustained slack "
             "(bounded by --slo min_groups/max_groups; requires "
             "--router)",
    )
    ap.add_argument(
        "--flywheel-log", default="",
        help="data flywheel (deepfm_tpu/flywheel): arm the router-side "
             "impression logger writing scored impressions into this "
             "segment-log root (dir or object URL); the join service "
             "tails it against a click log",
    )
    ap.add_argument("--flywheel-sample", type=float, default=1.0,
                    help="fraction of requests logged, hash-stable per "
                         "impression id (trace id, else routing key)")
    ap.add_argument("--flywheel-roll-bytes", type=int, default=1 << 20,
                    help="impression segment roll: size trigger")
    ap.add_argument("--flywheel-roll-age", type=float, default=10.0,
                    help="impression segment roll: age trigger seconds")
    ap.add_argument("--flywheel-queue", type=int, default=1024,
                    help="bounded impression queue; overflow drops "
                         "(counted), never blocks the serve path")
    ap.add_argument("--flywheel-join-out", default="",
                    help="the join service's output root: /v1/metrics "
                         "then reports its last committed checkpoint "
                         "(lag, pending window) next to the logger")
    ap.add_argument("--retry-limit", type=int, default=2)
    ap.add_argument("--eject-after", type=int, default=2)
    ap.add_argument("--health-interval", type=float, default=1.0)
    ap.add_argument("--max-restarts", type=int, default=10)
    ap.add_argument("--restart-backoff-secs", type=float, default=1.0)
    ap.add_argument(
        "--flight-dump", default="",
        help="arm the flight-recorder termination dump (obs/flight.py): "
             "the supervisor/router writes this JSONL on shutdown or "
             "crash, each member writes <path>.<group> on SIGTERM; the "
             "live rings stay at GET /v1/flight",
    )
    # internal: the re-exec member entry
    ap.add_argument("--member-entry", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--group", default="g0", help=argparse.SUPPRESS)
    ap.add_argument("--member-port", type=int, default=0,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.member_entry:
        return _run_member(args)

    # SIGTERM must tear the whole tree down: without a handler the
    # supervisor dies on the signal's default action and the member
    # processes ORPHAN onto init, still serving (observed live) — route
    # it through the same cleanup path as ^C
    import signal

    def _terminate(*_):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _terminate)

    if args.flight_dump:
        from ...obs import flight as obs_flight

        # SIGTERM raises KeyboardInterrupt (above) and unwinds through
        # the finally below, which dumps — so crash coverage (install's
        # excepthook) plus clean/killed shutdown both leave the timeline
        obs_flight.install(args.flight_dump)

    tenant_specs = _load_tenants(args.tenants)
    slo = _parse_slo(args.slo)
    if args.autoscale and not args.router:
        ap.error("--autoscale requires --router (the router aggregates "
                 "the utilization/SLO signal the scaler watches)")

    # per-group lifecycle state: the autoscaler stops ONE group's member
    # without touching its siblings, so each group owns its stop event,
    # supervisor thread and swap coordinators
    shutdown = threading.Event()
    state_lock = threading.Lock()
    groups: dict[str, dict] = {}

    def _start_swappers(g: str, url: str) -> list:
        # one group-atomic coordinator per (group, tenant-with-a-source):
        # each polls ITS tenant's manifest stream and converges only that
        # tenant's per-member slots
        out = []
        if tenant_specs:
            from .swap import GroupSwapper

            for spec in tenant_specs:
                if spec.source:
                    out.append(GroupSwapper(
                        [url], spec.source, group=g, tenant=spec.name,
                        interval_secs=args.reload_interval,
                    ).start())
        elif args.reload_url:
            from .swap import GroupSwapper

            out.append(GroupSwapper(
                [url], args.reload_url, group=g,
                interval_secs=args.reload_interval,
            ).start())
        return out

    def _start_group(g: str, index: int) -> str:
        """Spawn one supervised member process for group ``g``; returns
        its base URL (it is NOT ready yet — the member still has to load
        and precompile behind its /readyz gate)."""
        port = args.member_port_base + index
        stop = threading.Event()
        t = threading.Thread(
            target=_supervise_member, args=(args, g, index, port, stop),
            daemon=True, name=f"supervise-{g}",
        )
        t.start()
        url = f"http://{args.host}:{port}"
        with state_lock:
            groups[g] = {"stop": stop, "thread": t, "index": index,
                         "url": url, "swappers": _start_swappers(g, url)}
        return url

    def _stop_group(g: str) -> None:
        """Terminate one group's member process and coordinators (the
        caller already stopped admitting traffic and waited out the
        drain)."""
        with state_lock:
            st = groups.pop(g, None)
        if st is None:
            return
        for s in st["swappers"]:
            s.stop()
        st["stop"].set()
        st["thread"].join(timeout=40)

    for i in range(args.groups):
        _start_group(f"g{i}", i)
    with state_lock:
        urls = {g: [st["url"]] for g, st in groups.items()}
    print(f"pool: {args.groups} shard-group(s) at "
          f"{ {g: u[0] for g, u in urls.items()} }", file=sys.stderr)

    flywheel = None
    try:
        if args.router:
            from .router import Router, make_router_handler
            from ..server import ScoringHTTPServer

            split = shadow = None
            registry = None
            if tenant_specs:
                from ...fleet.registry import TenantRegistry
                from ...fleet.shadow import ShadowScorer
                from ...obs.metrics import MetricsRegistry

                reg = TenantRegistry(tenant_specs)
                split = reg.split()
                # one registry for router + shadows so GET /metrics on
                # the router shows every challenger's divergence
                # histogram alongside routing
                registry = MetricsRegistry()
                # EVERY configured challenger scores its incumbent's
                # stream — a validated-but-unwired shadow would read as
                # "no divergence" when it means "no measurement"
                shadow = [
                    ShadowScorer(
                        challenger, incumbent,
                        sample_percent=args.shadow_sample,
                        queue_depth=args.shadow_queue,
                        registry=registry,
                    )
                    for challenger, incumbent in reg.shadow_pairs()
                ]
            # the SLO control plane (serve/control/): shared retry
            # budget, tail hedging (needs a deadline to define "tail"),
            # and the shadow shed gate — all off without --slo
            retry_budget = hedge = shed_gate = None
            if slo is not None:
                from ..control.admission import LoadShedGate
                from ..control.hedge import HedgeController, TokenBudget

                retry_budget = TokenBudget(slo.retry_budget_pct / 100.0)
                shed_gate = LoadShedGate()
                if slo.deadline_ms > 0:
                    hedge = HedgeController(
                        slo_budget_ms=slo.deadline_ms,
                        after_pct=slo.hedge_after_pct,
                        budget=TokenBudget(slo.hedge_budget_pct / 100.0),
                    )
            if args.flywheel_log:
                from ...flywheel import ImpressionLogger

                if registry is None:
                    from ...obs.metrics import MetricsRegistry

                    registry = MetricsRegistry()
                flywheel = ImpressionLogger(
                    args.flywheel_log,
                    sample_rate=args.flywheel_sample,
                    queue_depth=args.flywheel_queue,
                    roll_bytes=args.flywheel_roll_bytes,
                    roll_age_secs=args.flywheel_roll_age,
                    join_output_url=args.flywheel_join_out,
                    registry=registry,
                ).start()
            router = Router(
                urls, model_name=args.model_name,
                retry_limit=args.retry_limit,
                eject_after=args.eject_after,
                probe_interval_secs=args.health_interval,
                split=split, shadow=shadow, registry=registry,
                retry_budget=retry_budget, hedge=hedge,
                shed_gate=shed_gate, flywheel=flywheel,
            ).start()
            if args.autoscale:
                _start_autoscaler(args, slo, router, shutdown,
                                  state_lock, groups,
                                  _start_group, _stop_group)
            httpd = ScoringHTTPServer(
                (args.host, args.port), make_router_handler(router)
            )
            print(
                f"pool router: serving {args.model_name} on "
                f"http://{args.host}:{httpd.server_address[1]}"
                f"/v1/models/{args.model_name}:predict",
                file=sys.stderr,
            )
            httpd.serve_forever()
        else:
            threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        shutdown.set()
        if flywheel is not None:
            # drain the queue and publish the tail segment — the last
            # impressions before shutdown still reach the join
            flywheel.stop()
        # stop every group's member + coordinators: signal all first,
        # then join, so teardown is parallel not serial
        with state_lock:
            snapshot = list(groups.items())
        for _g, st in snapshot:
            for s in st["swappers"]:
                s.stop()
            st["stop"].set()
        for _g, st in snapshot:
            st["thread"].join(timeout=40)
        if args.flight_dump:
            from ...obs import flight as obs_flight

            obs_flight.get_recorder().dump(reason="shutdown")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
