"""Distributed serving tier: router-fronted shard-group inference pool.

The training side scales with chips (parallel/spmd.py row-shards the
embedding tables and exchanges owned rows over lax.all_to_all, PR 5); this
package makes SERVING scale with hosts the same way.  Four modules:

* :mod:`.sharded` — the shard-group executable: embedding tables
  row-sharded over a serve-group mesh, the deduplicated all_to_all
  exchange running on the *predict* path inside the MicroBatcher's
  precompiled bucket executables (psum fallback preserved, jit-stable),
  weights riding as ARGUMENTS so a group swap is a jit cache hit.
* :mod:`.worker` — one shard-group member: the sharded scorer behind the
  serving HTTP surface plus the group-swap admin surface
  (``:stage``/``:commit``/``:rollback``/``:abort``) and generation-skew
  protection (a predict pinned to generation G is refused, never scored,
  by a member on G' != G).
* :mod:`.router` — the pool front: consistent hashing on the request key
  -> shard-group with a least-loaded tie-break, bounded
  retry-on-other-group, ``/healthz``-driven ejection and
  ``/readyz``-driven re-admission, group-generation pinning, and
  router-level ``/v1/metrics`` aggregation.
* :mod:`.swap` — group-atomic hot swap: a new published version commits
  across ALL members of a shard-group or rolls back, so no request is
  ever scored by mixed-version shards.

``python -m deepfm_tpu.serve.pool`` (see ``__main__.py``) runs the whole
tier: member processes supervised with bounded equal-jitter restarts
(utils/retry.run_with_restarts) under a router front.
"""

from .router import HashRing, Router, start_router  # noqa: F401
from .sharded import (  # noqa: F401
    ServeGroupContext,
    build_sharded_predict_with,
    load_sharded_servable,
    make_serve_context,
    stage_sharded_payload,
)
from .swap import GroupSwapper  # noqa: F401
from .worker import GroupMember, start_member  # noqa: F401
