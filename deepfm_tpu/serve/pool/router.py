"""The pool front: consistent hashing -> shard-group, health-driven
ejection, generation pinning, bounded cross-group retry.

Pure control plane — no jax anywhere in this module, so the router can
run in the supervisor process (serve/pool/__main__.py) or any sidecar.

* **Consistent hashing** (:class:`HashRing`): request key -> ordered
  candidate groups via a virtual-node ring (``replicas`` vnodes per
  group).  Removing one of ``n`` groups moves ONLY the keys that mapped
  to it (≈K/n of K keys); every other key keeps its group — the property
  the churn test pins.
* **Least-loaded tie-break**: among the first ``spread`` ring candidates
  that are healthy, the one with the fewest router-tracked in-flight
  rows wins (keys stay sticky under even load; a hot group sheds its
  overflow to its ring successor instead of queueing).
* **Bounded retry**: a failed forward (connection error, or a 5xx other
  than 503 — that one is the engine's backpressure signal, not a health
  verdict) marks the member toward ejection and tries the next candidate
  group, at most ``retry_limit`` extra groups; exhaustion answers 503.
* **Ejection / re-admission**: a background prober GETs every member's
  ``/healthz``; ``eject_after`` consecutive failures ejects the member
  (``ejections_total``).  An ejected member is probed on ``/readyz`` and
  re-admitted only when that passes (``readmissions_total``) — a
  respawning worker stays out of rotation until its engine has
  precompiled and its weights are loaded.
* **Generation pinning**: the router caches each group's generation
  (from readiness probes and responses) and pins every forwarded request
  to it via ``X-Pinned-Generation``.  A member mid-swap answers 409 (a
  skew abort, counted) instead of scoring; the router re-reads the
  generation and retries — so a client can never observe a response
  scored by mixed-version shards.  With a multi-tenant fleet the pin is
  keyed by (group, TENANT) — generations are per tenant, so tenant A
  mid-swap costs A a re-pin while B's pins stay valid.
* **Traffic splitting** (deepfm_tpu/fleet): with a :class:`TrafficSplit`
  attached, each request's tenant is either the explicit ``X-Tenant``
  header or the hash-stable split arm of its routing key — a key lands
  on the same arm across requests, router restarts and routers (the arm
  is a pure function of key + percentages, fleet/split.py), and a
  re-split moves only the boundary windows that shifted.  The chosen
  tenant rides the forward as ``X-Tenant``.
* **Shadow scoring** (fleet/shadow.py): with a shadow attached, a
  hash-stable sample of the incumbent tenant's answered requests is
  offered to the challenger OFF the response path — bounded queue,
  sheds under load, never adds latency; only the incumbent's answer was
  returned.  Score-divergence percentiles land in the registry
  (``deepfm_shadow_divergence``).
* **Metrics**: ``GET /v1/metrics`` aggregates per-group p50/p95/p99
  (router-measured, sliding window), requests/retries/skew-aborts/
  ejections/re-admissions, each group's exchange wire-bytes estimate
  (cached from readiness probes), and — with a fleet — a ``tenants``
  section (per-tenant requests/latency/split share + shadow stats).

SLO control plane (serve/control/, all optional):

* **Retry/hedge token budget** (:class:`~..control.hedge.TokenBudget`):
  every cross-group retry and every hedge spends one shared token;
  tokens accrue at a fraction of the live request rate.  Exhaustion
  FAILS FAST (503 + ``Retry-After``) — in a pool-wide brownout the
  router must not multiply offered load by the retry factor.
* **Hedged tail requests** (:class:`~..control.hedge.HedgeController`):
  when the first-choice group's live p95 breaches the SLO budget, a
  hedge to the next healthy candidate arms after an adaptive delay;
  first answer wins, the loser counts as cancelled.
* **Shadow shed gate** (:class:`~..control.admission.LoadShedGate`):
  smoothed member-backpressure signal; while high, shadow offers shed
  at the source (the first rung of the admission ladder).
"""

from __future__ import annotations

import hashlib
import json
import queue as _queue
import threading
import time
import urllib.error
import urllib.request

from ...obs import flight as obs_flight
from ...obs.metrics import MetricsRegistry
from ...obs.trace import DEFAULT_SAMPLE_RATE, Tracer, current_trace
from ..server import ScoringHTTPServer, _send_json, _send_text
from http.server import BaseHTTPRequestHandler


class HashRing:
    """Consistent-hash ring with virtual nodes.

    ``candidates(key)`` walks clockwise from the key's point and returns
    every distinct node in ring order — element 0 is the consistent
    primary; the rest are the deterministic failover order."""

    def __init__(self, nodes=(), *, replicas: int = 64):
        self._replicas = int(replicas)
        self._points: list[tuple[int, str]] = []
        self._nodes: set[str] = set()
        for n in nodes:
            self.add(n)

    @staticmethod
    def _hash(s: str) -> int:
        return int.from_bytes(hashlib.md5(s.encode()).digest()[:8], "big")

    def add(self, node: str) -> None:
        if node in self._nodes:
            return
        self._nodes.add(node)
        for i in range(self._replicas):
            self._points.append((self._hash(f"{node}#{i}"), node))
        self._points.sort()

    def remove(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        self._points = [(h, n) for h, n in self._points if n != node]

    def __len__(self) -> int:
        return len(self._nodes)

    def candidates(self, key: str, n: int | None = None) -> list[str]:
        if not self._points:
            return []
        want = len(self._nodes) if n is None else min(n, len(self._nodes))
        h = self._hash(key)
        # bisect to the key's point, then walk clockwise collecting
        # distinct nodes
        import bisect

        idx = bisect.bisect_left(self._points, (h, ""))
        out: list[str] = []
        for off in range(len(self._points)):
            node = self._points[(idx + off) % len(self._points)][1]
            if node not in out:
                out.append(node)
                if len(out) == want:
                    break
        return out


class _Member:
    __slots__ = ("url", "healthy", "fails", "inflight", "doc")

    def __init__(self, url: str):
        self.url = url
        self.healthy = True       # optimistic: probed immediately
        self.fails = 0
        self.inflight = 0
        self.doc: dict = {}       # last readiness doc (generation, wire est)


class Router:
    """Route predict requests across shard-groups (module docstring).

    ``groups`` maps group name -> list of member base URLs.  Thread-safe;
    ``start()`` launches the health prober, ``close()`` stops it."""

    def __init__(
        self,
        groups: dict[str, list[str]],
        *,
        model_name: str = "deepfm",
        retry_limit: int = 2,
        spread: int = 2,
        eject_after: int = 2,
        probe_interval_secs: float = 1.0,
        request_timeout_secs: float = 60.0,
        registry: MetricsRegistry | None = None,
        tracer: Tracer | None = None,
        split=None,
        shadow=None,
        retry_budget=None,
        hedge=None,
        shed_gate=None,
        flywheel=None,
    ):
        if not groups:
            raise ValueError("router needs at least one shard-group")
        self.model_name = model_name
        self._ring = HashRing(sorted(groups))
        self._members = {
            g: [_Member(u) for u in urls] for g, urls in groups.items()
        }
        # scale-down keeps the drained group's member records here so
        # ``group_inflight`` stays answerable while requests finish
        self._retired: dict[str, list[_Member]] = {}
        self._retry_limit = int(retry_limit)
        self._spread = max(1, int(spread))
        self._eject_after = max(1, int(eject_after))
        self._probe_interval = float(probe_interval_secs)
        self._timeout = float(request_timeout_secs)
        self._lock = threading.Lock()
        # generation pins keyed (group, tenant); tenant None is the
        # legacy tenant-less pin (single-tenant members).  Per-tenant
        # keys are learned from readiness probes' ``tenants`` map and
        # from member responses/409s
        self._generation: dict[tuple[str, str | None], int] = {}
        # all counters/latency live in the shared obs registry
        # (obs/metrics.py): /v1/metrics re-renders from it unchanged and
        # GET /metrics scrapes it directly
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        # the router is where a request enters the pool: it is the trace
        # HEAD (mints X-Trace-Id at the shipped sample rate, or adopts
        # the client's — always recorded); members inherit the decision
        # via the propagated headers
        self.tracer = tracer if tracer is not None else Tracer(
            "router", sample_rate=DEFAULT_SAMPLE_RATE)
        r = self.registry
        self._c_requests = r.counter(
            "deepfm_router_requests_total", "requests routed")
        self._c_retries = r.counter(
            "deepfm_router_retries_total", "cross-member retry attempts")
        self._c_skew = r.counter(
            "deepfm_router_skew_aborts_total",
            "409 generation-skew aborts observed")
        self._c_ejections = r.counter(
            "deepfm_router_ejections_total", "members ejected")
        self._c_readmissions = r.counter(
            "deepfm_router_readmissions_total", "members re-admitted")
        self._c_no_capacity = r.counter(
            "deepfm_router_no_capacity_total",
            "requests refused with no healthy shard-group")
        # family refs kept: add_group mints new label children at runtime
        self._f_group_requests = r.counter(
            "deepfm_router_group_requests_total",
            "requests answered per shard-group", labels=("group",))
        self._f_latency = r.histogram(
            "deepfm_router_group_latency_seconds",
            "router-measured member latency", labels=("group",))
        self._group_requests = {
            g: self._f_group_requests.labels(g) for g in groups
        }
        self._windows = {g: self._f_latency.labels(g) for g in groups}
        # SLO control plane (serve/control/), each optional: the shared
        # retry/hedge token budget, the tail-hedging controller, and the
        # shadow shed gate
        self._retry_budget = retry_budget
        self._hedge = hedge
        self._shed_gate = shed_gate
        # data flywheel (deepfm_tpu/flywheel): an optional
        # ImpressionLogger; answered requests are OFFERED after the
        # response is formed — hash-stable sampling, bounded queue,
        # drop-with-metric — so the serve path never waits on the log
        self._flywheel = flywheel
        self._c_budget_exhausted = r.counter(
            "deepfm_router_retry_budget_exhausted_total",
            "retries/hedges suppressed: shared token budget empty")
        # multi-tenant fleet (deepfm_tpu/fleet): the hash-stable split
        # picks each request's tenant (unless X-Tenant names one) and the
        # shadow(s) re-score a sampled slice of their incumbent's stream
        # off the response path.  Both optional; a split-less router is
        # the legacy single-tenant front unchanged.  ``shadow`` accepts
        # one ShadowScorer or a sequence — every configured challenger
        # gets its samples, not just the first.
        self._split = split
        self._shadows = ([] if shadow is None
                         else list(shadow) if isinstance(shadow, (list,
                                                                  tuple))
                         else [shadow])
        for sh in self._shadows:
            # each challenger re-scores through the same routing
            # machinery, addressed to ITSELF, with re-offering disabled
            sh.bind(lambda body, _c=sh.challenger: self.handle_predict(
                body, tenant=_c, _offer_shadow=False))
            if self._shed_gate is not None:
                # the shed ladder's first rung: while the gate reads
                # sustained member backpressure, offers shed at the
                # source (fleet/shadow.py counts them as "gated")
                sh.set_gate(self._shed_gate.allow_shadow)
        # tenant label cardinality is BOUNDED: only names the fleet
        # actually serves (split arms, shadow pairs, tenants learned from
        # member readiness probes) get metric children — an arbitrary
        # client X-Tenant string must not grow the registry or the
        # /v1/metrics payload without bound
        self._known_tenants: set[str] = set()
        if split is not None:
            self._known_tenants.update(split.arms())
        for sh in self._shadows:
            self._known_tenants.update((sh.challenger, sh.incumbent))
        self._tenant_requests = r.counter(
            "deepfm_router_tenant_requests_total",
            "requests routed per tenant", labels=("tenant",))
        self._tenant_latency = r.histogram(
            "deepfm_router_tenant_latency_seconds",
            "router-measured latency per tenant", labels=("tenant",))
        self._stop = threading.Event()
        self._prober: threading.Thread | None = None

    # registry-backed totals, read-compatible with the pre-registry attrs
    @property
    def requests_total(self) -> int:
        return int(self._c_requests.value)

    @property
    def retries_total(self) -> int:
        return int(self._c_retries.value)

    @property
    def skew_aborts_total(self) -> int:
        return int(self._c_skew.value)

    @property
    def ejections_total(self) -> int:
        return int(self._c_ejections.value)

    @property
    def readmissions_total(self) -> int:
        return int(self._c_readmissions.value)

    @property
    def no_capacity_total(self) -> int:
        return int(self._c_no_capacity.value)

    # -- health -------------------------------------------------------------
    def _get_json(self, url: str, timeout: float = 5.0) -> dict:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.load(r)

    def _probe_member(self, group: str, m: _Member) -> None:
        try:
            if m.healthy:
                self._get_json(m.url + "/healthz")
                # readiness carries generation + wire estimate (the
                # group_status merge, serve/server.py)
                doc = self._get_json(m.url + "/readyz")
            else:
                # ejected members must pass READINESS (engine compiled,
                # weights loaded) to re-enter rotation, not mere liveness
                doc = self._get_json(m.url + "/readyz")
            ok = bool(doc.get("ready", True))
        except Exception as e:
            # the failure IS the probe result; keep it observable on the
            # member record (surfaces in /v1/metrics while ejected)
            ok, doc = False, {"probe_error": f"{type(e).__name__}: {e}"}
        with self._lock:
            if ok:
                if not m.healthy:
                    self._c_readmissions.inc()
                    obs_flight.record("member_readmitted", group=group,
                                      url=m.url)
                m.healthy, m.fails, m.doc = True, 0, doc
                if "group_generation" in doc:
                    self._generation[(group, None)] = int(
                        doc["group_generation"]
                    )
                for t, td in (doc.get("tenants") or {}).items():
                    self._known_tenants.add(t)
                    if "generation" in td:
                        self._generation[(group, t)] = int(
                            td["generation"]
                        )
            else:
                m.fails += 1
                if m.healthy and m.fails >= self._eject_after:
                    m.healthy = False
                    self._c_ejections.inc()
                    obs_flight.record("member_ejected", group=group,
                                      url=m.url, via="probe",
                                      fails=m.fails)

    def probe_once(self) -> None:
        # snapshot under the lock: the autoscaler adds/removes groups
        # from another thread while this loop is mid-iteration
        with self._lock:
            live = [(g, list(ms)) for g, ms in self._members.items()]
        for g, members in live:
            for m in members:
                self._probe_member(g, m)

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            self.probe_once()
            self._stop.wait(self._probe_interval)

    def start(self) -> "Router":
        self.probe_once()  # populate generations before traffic
        if self._prober is None:
            self._prober = threading.Thread(
                target=self._probe_loop, daemon=True, name="router-prober"
            )
            self._prober.start()
        for sh in self._shadows:
            sh.start()
        return self

    def close(self) -> None:
        self._stop.set()
        for sh in self._shadows:
            sh.stop()
        if self._prober is not None:
            self._prober.join(timeout=10)
            self._prober = None

    # -- fleet control plane ------------------------------------------------
    def update_split(self, percentages: dict[str, float]) -> dict:
        """Re-split live traffic across the tenant arms.  Hash-stable
        minimal movement (fleet/split.py): only keys in the shifted
        boundary windows change arms.  Recorded to the flight timeline —
        a fleet incident shows WHEN the split moved."""
        if self._split is None:
            raise ValueError("router has no traffic split configured")
        # arm names must be tenants the fleet actually serves: a typo'd
        # re-split would hash that share of live keys onto an arm every
        # member 400s — refuse the operation, not the traffic
        unknown = sorted(set(percentages) - self._known_tenants)
        if unknown:
            raise ValueError(
                f"unknown tenant arm(s) {unknown}; the fleet serves "
                f"{sorted(self._known_tenants)}"
            )
        before = self._split.arms()
        after = self._split.set_percentages(percentages)
        obs_flight.record("split_change", subsystem="fleet",
                          before=before, after=after)
        return after

    # -- elastic topology ---------------------------------------------------
    def add_group(self, name: str, urls: list[str]) -> None:
        """Admit a new shard-group into rotation (the autoscaler's
        scale-up commit, AFTER the members' ``/readyz`` passed).
        Consistent hashing means only ≈K/n of K keys move to it; every
        other key keeps its group."""
        with self._lock:
            if name in self._members:
                raise ValueError(f"group {name!r} already routed")
            self._retired.pop(name, None)
            self._members[name] = [_Member(u) for u in urls]
            self._group_requests[name] = self._f_group_requests.labels(name)
            self._windows[name] = self._f_latency.labels(name)
            # a fresh ring (not in-place mutation): ``candidates`` reads
            # the point list lock-free, so the swap must be atomic
            self._ring = HashRing(sorted(self._members))
        obs_flight.record("group_added", subsystem="slo", group=name,
                          urls=list(urls))
        for m in self._members[name]:
            self._probe_member(name, m)

    def remove_group(self, name: str) -> None:
        """Stop admitting to a group (the autoscaler's scale-down start).
        In-flight requests on it finish normally — the member records
        move to the retired set so ``group_inflight`` keeps answering
        while the supervisor waits out the drain, then terminates the
        processes.  Never removes the last group."""
        with self._lock:
            if name not in self._members:
                raise ValueError(f"group {name!r} is not routed")
            if len(self._members) <= 1:
                raise ValueError("refusing to remove the last shard-group")
            self._retired[name] = self._members.pop(name)
            self._ring = HashRing(sorted(self._members))
            stale = [k for k in self._generation if k[0] == name]
            for k in stale:
                del self._generation[k]
        obs_flight.record("group_removed", subsystem="slo", group=name)

    def group_inflight(self, name: str) -> int:
        """Router-tracked in-flight rows on a group — live or retired
        (the drain monitor's signal)."""
        with self._lock:
            members = self._members.get(name) or self._retired.get(name, [])
            return sum(m.inflight for m in members)

    def group_names(self) -> list[str]:
        with self._lock:
            return sorted(self._members)

    # -- routing ------------------------------------------------------------
    @staticmethod
    def request_key(body: dict) -> str:
        """The routing key: an explicit top-level ``"key"`` when the
        client supplies one (sticky sessions / cache affinity), else a
        content hash of the instances — deterministic, so identical
        requests land on the same group."""
        if "key" in body:
            return str(body["key"])
        return hashlib.md5(
            json.dumps(body.get("instances", []), sort_keys=True).encode()
        ).hexdigest()

    def _healthy_members(self, group: str) -> list[_Member]:
        return [m for m in self._members.get(group, ()) if m.healthy]

    def _plan(self, key: str) -> list[str]:
        """Candidate groups in try-order: ring order, with the first
        ``spread`` healthy candidates re-ranked by in-flight load (the
        least-loaded tie-break), then the remaining ring order as
        failover."""
        ring = self._ring.candidates(key)
        healthy = [g for g in ring if self._healthy_members(g)]
        if not healthy:
            return []
        with self._lock:
            head = sorted(
                healthy[: self._spread],
                key=lambda g: sum(
                    m.inflight for m in self._healthy_members(g)
                ),
            )
        return head + [g for g in healthy if g not in head]

    def handle_predict(self, body: dict,
                       path: str | None = None,
                       tenant: str | None = None,
                       _offer_shadow: bool = True,
                       deadline_ms: float | None = None,
                       priority: str | None = None) -> tuple[int, dict]:
        """Route one predict (or funnel recommend — ``path`` overrides
        the default ``:predict`` member route; same pinning, ejection and
        retry discipline); returns ``(http_status, response_doc)``.  The
        member's response document passes through untouched (it already
        carries predictions — or the funnel's items + index_version —
        model_version, shard_group, tenant and group_generation) plus a
        ``router`` attribution section.

        ``tenant`` is the explicit X-Tenant selection; with none and a
        split attached, the request's hash-stable split arm decides.
        ``_offer_shadow=False`` marks the shadow worker's own re-scores
        (a challenger score must never re-offer itself).

        ``deadline_ms``/``priority`` are the client's SLO declaration
        (``X-Deadline-Ms``/``X-Priority``), forwarded to the member whose
        admission controller prices them; with a
        :class:`~..control.hedge.HedgeController` attached, a request
        whose first-choice group's live p95 breaches the SLO budget races
        a delayed hedge against the next healthy candidate."""
        target = path or f"/v1/models/{self.model_name}:predict"
        key = self.request_key(body)
        if tenant is None and self._split is not None:
            tenant = self._split.arm(key)
        rows = len(body.get("instances", []))
        plan = self._plan(key)
        self._c_requests.inc()
        if self._retry_budget is not None:
            # every routed request accrues fractional retry/hedge credit
            self._retry_budget.note_request()
        if (self._hedge is not None and self._hedge.budget is not None
                and self._hedge.budget is not self._retry_budget):
            # a hedge budget configured as its own bucket accrues too
            self._hedge.budget.note_request()
        if tenant is not None and tenant in self._known_tenants:
            # known tenants only: a client-invented X-Tenant string is
            # forwarded (the member 400s it) but never mints a metric
            # child — label cardinality stays bounded by the fleet config
            self._tenant_requests.labels(tenant).inc()
        # the request's trace context (set by the router handler): every
        # forward attempt becomes a span, and the SAME trace id rides the
        # propagation headers across retries — including the 409 re-pin
        # path, so one client request is one trace end-to-end
        tctx = current_trace()
        if not plan:
            self._c_no_capacity.inc()
            return 503, {"error": "no healthy shard-group"}
        kw = dict(
            target=target, payload=json.dumps(body).encode(), rows=rows,
            tenant=tenant, tctx=tctx, key=key, body=body,
            _offer_shadow=_offer_shadow, deadline_ms=deadline_ms,
            priority=priority,
        )
        groups = plan[: self._retry_limit + 1]
        delay = None
        if self._hedge is not None and len(groups) > 1:
            delay = self._hedge.plan(
                self._windows[groups[0]].snapshot().get("p95")
            )
        if delay is None:
            return self._route(groups, **kw)
        return self._route_hedged(groups, delay, **kw)

    def _route(self, groups: list[str], **kw) -> tuple[int, dict]:
        """Sequential failover over the candidate groups.  Every group
        past the first is a cross-group retry and spends one shared
        budget token first; an empty bucket FAILS FAST — retrying into a
        pool-wide brownout multiplies the offered load exactly when
        capacity is scarcest."""
        state = {"attempts": 0, "last_err": {"error": "exhausted"}}
        for i, group in enumerate(groups):
            if i > 0 and self._retry_budget is not None \
                    and not self._retry_budget.try_spend():
                self._c_budget_exhausted.inc()
                return 503, {
                    "error": "retry budget exhausted (pool-wide "
                             "brownout guard): failing fast",
                    "retry_after_s": 1.0,
                }
            out = self._try_group(group, state=state, **kw)
            if out is not None:
                return out
        return 503, state["last_err"]

    def _route_hedged(self, groups: list[str], delay: float,
                      **kw) -> tuple[int, dict]:
        """Race the primary plan against a delayed hedge on the next
        candidate.  The hedge fires only if the primary outlives
        ``delay`` AND the shared token budget grants it (≤ the budget
        ratio of recent request rate — bounded extra load by
        construction).  First answer wins; the loser's work is counted
        cancelled (nobody consumes it)."""
        resq: _queue.Queue = _queue.Queue()

        def run(subgroups: list[str], tag: str) -> None:
            try:
                resq.put((tag, self._route(subgroups, **kw)))
            except Exception as e:   # defensive: a leg must always report
                resq.put((tag, (500,
                                {"error": f"{type(e).__name__}: {e}"})))

        threading.Thread(target=run, args=(groups, "primary"),
                         daemon=True, name="route-primary").start()
        try:
            _, out = resq.get(timeout=max(0.001, delay))
            return out           # answered inside the hedge delay
        except _queue.Empty:
            pass
        if not self._hedge.try_fire():
            _, out = resq.get()  # budget empty: wait out the primary
            return out
        threading.Thread(target=run, args=([groups[1]], "hedge"),
                         daemon=True, name="route-hedge").start()
        first_tag, first = resq.get()
        if first[0] == 200:
            self._hedge.record_outcome(hedge_won=(first_tag == "hedge"))
            first[1].setdefault("router", {})["hedge"] = first_tag
            return first
        # the first arrival failed — the race is decided by the other leg
        second_tag, second = resq.get()
        winner_tag, winner = ((second_tag, second) if second[0] == 200
                              else (first_tag, first))
        self._hedge.record_outcome(
            hedge_won=(winner_tag == "hedge" and winner[0] == 200))
        if winner[0] == 200:
            winner[1].setdefault("router", {})["hedge"] = winner_tag
        return winner

    def _try_group(self, group: str, *, target: str, payload: bytes,
                   rows: int, tenant: str | None, tctx, state: dict,
                   key: str, body: dict, _offer_shadow: bool,
                   deadline_ms: float | None = None,
                   priority: str | None = None) -> tuple[int, dict] | None:
        """One candidate group's forward: least-loaded member pick plus
        one in-group re-pin retry.  Returns a terminal ``(status, doc)``
        or None — this group cannot answer, try the next candidate."""
        members = sorted(
            self._healthy_members(group), key=lambda m: m.inflight
        )
        if not members:
            return None
        m = members[0]
        # one in-group re-pin retry: a 409 means OUR generation was
        # stale (the group swapped under us), not that the group is bad
        for _pin_attempt in range(2):
            state["attempts"] += 1
            if state["attempts"] > 1:
                self._c_retries.inc()
            gen = self._generation.get((group, tenant))
            headers = {"Content-Type": "application/json"}
            if tenant is not None:
                headers["X-Tenant"] = tenant
            if gen is not None:
                headers["X-Pinned-Generation"] = str(gen)
            if deadline_ms is not None:
                # the member's admission controller prices the request
                # against this (made absolute on ITS clock at parse time)
                headers["X-Deadline-Ms"] = str(deadline_ms)
            if priority is not None:
                headers["X-Priority"] = priority
            if tctx is not None:
                headers.update(tctx.headers())
            req = urllib.request.Request(
                f"{m.url}{target}", data=payload, headers=headers,
            )
            t0 = time.perf_counter()
            with self._lock:
                m.inflight += rows
            try:
                with urllib.request.urlopen(
                    req, timeout=self._timeout
                ) as r:
                    doc = json.load(r)
                dt = time.perf_counter() - t0
                self._windows[group].observe(dt)
                self._group_requests[group].inc()
                if tenant is not None:
                    if tenant in self._known_tenants:
                        self._tenant_latency.labels(tenant).observe(
                            dt)
                with self._lock:
                    if "group_generation" in doc:
                        self._generation[(group, tenant)] = int(
                            doc["group_generation"]
                        )
                if self._shed_gate is not None:
                    self._shed_gate.note(False)
                if tctx is not None:
                    span_attrs = {"group": group,
                                  "attempt": state["attempts"],
                                  "status": 200}
                    if tenant is not None:
                        span_attrs["tenant"] = tenant
                    tctx.add_span(
                        "router.forward", t0, time.perf_counter(),
                        **span_attrs,
                    )
                doc["router"] = {"group": group,
                                 "attempts": state["attempts"]}
                if tenant is not None:
                    doc["router"]["tenant"] = tenant
                # shadow the incumbent's answered stream: a
                # hash-stable sample is re-scored by each challenger
                # off this path (bounded queue, sheds under load);
                # the response below is already the incumbent's and
                # never waits on it.  Gate on the tenant the member
                # REPORTS scoring — a split-less fleet routes
                # unkeyed traffic as tenant None, but the member
                # still scored its default tenant, and that default
                # may be a challenger's incumbent
                scored_by = doc.get("tenant", tenant)
                if _offer_shadow and "predictions" in doc:
                    for sh in self._shadows:
                        if scored_by == sh.incumbent:
                            sh.offer(key, body, doc["predictions"])
                    if self._flywheel is not None:
                        # scored impression into the flywheel log —
                        # same structural guarantee as the shadow
                        # offer above (and _offer_shadow=False marks
                        # a shadow re-score: never an impression)
                        self._flywheel.offer(
                            key=key,
                            trace_id=(tctx.trace_id
                                      if tctx is not None else ""),
                            tenant=scored_by or "",
                            model_version=int(
                                doc.get("model_version", -1)),
                            instances=body.get("instances", ()),
                            scores=doc["predictions"],
                            deadline_class=(
                                priority if priority is not None
                                else "deadline"
                                if deadline_ms is not None
                                else "default"),
                        )
                return 200, doc
            except urllib.error.HTTPError as e:
                try:
                    err = json.load(e)
                except (ValueError, OSError):
                    err = {"error": f"http {e.code}"}
                if tctx is not None:
                    tctx.add_span(
                        "router.forward", t0, time.perf_counter(),
                        group=group, attempt=state["attempts"],
                        status=e.code,
                    )
                if e.code == 409:
                    # generation skew: learn the member's live
                    # generation FOR THIS TENANT and retry once,
                    # same group (the 409 carries the tenant whose
                    # pin went stale — tenant A's swap never
                    # invalidates B's pins)
                    self._c_skew.inc()
                    with self._lock:
                        if "group_generation" in err:
                            self._generation[(group, tenant)] = int(
                                err["group_generation"]
                            )
                    state["last_err"] = err
                    continue
                if e.code in (400, 413):
                    # the client's fault: no retry can fix the body
                    return e.code, err
                if e.code == 504:
                    # the member ANSWERED: the deadline passed while the
                    # request sat queued (expiry-at-dequeue).  Not a
                    # health verdict, and not retryable — the deadline
                    # is equally gone on every other group
                    return e.code, err
                state["last_err"] = err
                if e.code >= 500 and e.code != 503:
                    # a server-side failure counts toward ejection
                    # exactly like a connection failure — a member
                    # whose engine 500s every predict must leave
                    # rotation at traffic speed.  503 is exempt: it
                    # is the engine's BACKPRESSURE signal (bounded
                    # queue shedding), and ejecting an overloaded-
                    # but-healthy member would amplify the overload
                    self._eject_on_traffic(group, m, f"http {e.code}")
                elif e.code == 503 and self._shed_gate is not None:
                    # backpressure feeds the shadow shed gate instead
                    self._shed_gate.note(True)
                return None  # 5xx/503: next group
            except Exception as e:
                # connection-level failure: count toward ejection so
                # a dead member leaves rotation at traffic speed, not
                # probe speed
                if tctx is not None:
                    tctx.add_span(
                        "router.forward", t0, time.perf_counter(),
                        group=group, attempt=state["attempts"],
                        status=type(e).__name__,
                    )
                self._eject_on_traffic(group, m, type(e).__name__)
                state["last_err"] = {"error": f"{type(e).__name__}: {e}"}
                return None
            finally:
                with self._lock:
                    m.inflight -= rows
        return None  # both pin attempts skewed: next group

    def _eject_on_traffic(self, group: str, m: _Member, why: str) -> None:
        with self._lock:
            m.fails += 1
            ejected = m.healthy and m.fails >= self._eject_after
            if ejected:
                m.healthy = False
        if ejected:
            self._c_ejections.inc()
            obs_flight.record("member_ejected", group=group, url=m.url,
                              via="traffic", reason=why)

    # -- observability ------------------------------------------------------
    def metrics_snapshot(self) -> dict:
        with self._lock:
            groups = {}
            for g, members in self._members.items():
                healthy = [m for m in members if m.healthy]
                doc = next((m.doc for m in members if m.doc), {})
                groups[g] = {
                    "members": len(members),
                    "healthy_members": len(healthy),
                    "inflight_rows": sum(m.inflight for m in members),
                    "generation": self._generation.get((g, None)),
                    "tenant_generations": {
                        t: gen
                        for (grp, t), gen in self._generation.items()
                        if grp == g and t is not None
                    },
                    "requests_total": int(self._group_requests[g].value),
                    "latency_ms": self._windows[g].snapshot(),
                    "exchange_wire_bytes_est": doc.get(
                        "exchange_wire_bytes_est"
                    ),
                    "exchange": doc.get("exchange"),
                    "mesh": doc.get("mesh"),
                }
            out = {
                "router": {
                    "model": self.model_name,
                    "groups": len(self._members),
                    "requests_total": self.requests_total,
                    "retries_total": self.retries_total,
                    "skew_aborts_total": self.skew_aborts_total,
                    "ejections_total": self.ejections_total,
                    "readmissions_total": self.readmissions_total,
                    "no_capacity_total": self.no_capacity_total,
                    "retry_limit": self._retry_limit,
                },
                "groups": groups,
            }
        # the SLO control plane's own gauges (each section present only
        # when that mechanism is attached)
        if self._retry_budget is not None:
            out["router"]["retry_budget"] = self._retry_budget.snapshot()
            out["router"]["retry_budget_exhausted_total"] = int(
                self._c_budget_exhausted.value)
        if self._hedge is not None:
            out["router"]["hedge"] = self._hedge.snapshot()
        if self._shed_gate is not None:
            out["router"]["shed_gate"] = self._shed_gate.snapshot()
        if self._flywheel is not None:
            # impression-logger counters, plus the join service's last
            # committed checkpoint when its output root is configured
            out["flywheel"] = self._flywheel.stats()
        # the fleet view: per-tenant split share, routed requests and
        # router-measured latency, plus the shadow challenger's stats
        if self._split is not None or self._shadows:
            tenants: dict[str, dict] = {}
            arms = self._split.arms() if self._split is not None else {}
            names = set(arms)
            names.update(
                k[0] for k in self._tenant_requests.children()
            )
            for t in sorted(names):
                tenants[t] = {
                    "split_percent": arms.get(t),
                    "requests_total": int(
                        self._tenant_requests.labels(t).value
                    ),
                    "latency_ms": self._tenant_latency.labels(
                        t).snapshot(),
                }
            for sh in self._shadows:
                tenants.setdefault(sh.challenger, {})[
                    "shadow"] = sh.stats()
            out["tenants"] = tenants
        return out


def make_router_handler(router: Router):
    predict_path = f"/v1/models/{router.model_name}:predict"
    recommend_path = "/v1/recommend"   # funnel members (funnel/serve.py)
    status_path = f"/v1/models/{router.model_name}"

    class RouterHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        disable_nagle_algorithm = True
        _send = _send_json
        _send_plain = _send_text

        def do_GET(self):  # noqa: N802
            if self.path == "/healthz":
                self._send(200, {"status": "alive", "role": "router"})
            elif self.path == "/metrics":
                self._send_plain(200, router.registry.render_prometheus())
            elif self.path == "/v1/trace/recent":
                self._send(200, {"traces": router.tracer.recent()})
            elif self.path == "/v1/flight":
                self._send(200, {"events": obs_flight.render_events()})
            elif self.path == "/readyz":
                snap = router.metrics_snapshot()
                ready = any(
                    g["healthy_members"] > 0
                    for g in snap["groups"].values()
                )
                self._send(200 if ready else 503,
                           {"ready": ready, "role": "router"})
            elif self.path == status_path:
                self._send(200, {
                    "model_version_status": [
                        {"version": "router", "state": "AVAILABLE"}
                    ],
                })
            elif self.path == "/v1/metrics":
                self._send(200, router.metrics_snapshot())
            else:
                self._send(404, {"error": f"unknown path {self.path!r}"})

        def do_POST(self):  # noqa: N802
            if self.path == "/admin:split":
                # live re-split of tenant traffic (hash-stable minimal
                # key movement, fleet/split.py); flight-recorded
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    body = json.loads(self.rfile.read(length))
                    arms = router.update_split(body["percentages"])
                except (ValueError, KeyError, TypeError) as e:
                    return self._send(400,
                                      {"error": f"{type(e).__name__}: {e}"})
                return self._send(200, {"arms": arms})
            if self.path not in (predict_path, recommend_path):
                return self._send(404,
                                  {"error": f"unknown path {self.path!r}"})
            # the trace head: mint an X-Trace-Id (or adopt the client's)
            # here, where the request enters the pool; handle_predict
            # propagates it to the member on every attempt
            name = ("recommend" if self.path == recommend_path
                    else "predict")
            ctx = router.tracer.begin(name, self.headers)
            token = router.tracer.activate(ctx)
            self._obs_status = None
            try:
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    body = json.loads(self.rfile.read(length))
                    body["instances"]
                except Exception as e:
                    return self._send(400,
                                      {"error": f"{type(e).__name__}: {e}"})
                deadline_ms = None
                hdr = self.headers.get("X-Deadline-Ms")
                if hdr is not None:
                    try:
                        deadline_ms = max(0.0, float(hdr))
                    except ValueError:
                        deadline_ms = None
                code, doc = router.handle_predict(
                    body,
                    path=recommend_path if self.path == recommend_path
                    else None,
                    # explicit tenant selection wins over the split arm
                    tenant=self.headers.get("X-Tenant"),
                    deadline_ms=deadline_ms,
                    priority=self.headers.get("X-Priority"),
                )
                # admission rejections carry a back-off hint; surface it
                # as the HTTP Retry-After header the member couldn't set
                # across the hop
                extra = None
                if code == 503 and isinstance(
                        doc.get("retry_after_s"), (int, float)):
                    extra = {"Retry-After":
                             max(1, int(doc["retry_after_s"] + 0.999))}
                self._send(code, doc, extra_headers=extra)
            finally:
                router.tracer.finish(ctx, token, status=self._obs_status)

        def log_message(self, fmt, *args):
            pass

    return RouterHandler


def start_router(
    groups: dict[str, list[str]],
    *,
    host: str = "127.0.0.1",
    port: int = 0,
    **router_kw,
) -> tuple[ScoringHTTPServer, str, Router]:
    """Router front on a daemon thread; returns (server, base_url,
    router).  Callers own shutdown (``server.shutdown();
    router.close()``)."""
    router = Router(groups, **router_kw).start()
    httpd = ScoringHTTPServer((host, port), make_router_handler(router))
    threading.Thread(
        target=httpd.serve_forever, daemon=True, name="pool-router"
    ).start()
    return httpd, f"http://{host}:{httpd.server_address[1]}", router
