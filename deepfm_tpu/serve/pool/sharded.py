"""The shard-group executable: row-sharded tables on the PREDICT path.

``parallel/spmd.py`` proved the layout for training: embedding tables
row-sharded over the mesh's ``model`` axis, rows assembled by the
deduplicated owned-rows-only all_to_all exchange (``parallel/embedding.py``,
with the jit-stable psum fallback on capacity overflow).  GSPMD's lesson
(arxiv 2105.04663) is that the same sharded computation applies to the
inference graph unchanged — this module is that application:

* ``build_sharded_predict_with`` returns a jitted
  ``predict_with(payload, feat_ids, feat_vals) -> prob`` whose tables live
  row-sharded across the serve-group mesh and whose lookups run the
  exchange *inside* the MicroBatcher's precompiled bucket executables.
* The payload rides as an ARGUMENT (the serve/reload.py discipline), so a
  group hot swap is a jit cache hit — no recompile, ever, mid-traffic.
  ``stage_sharded_payload`` commits a restored checkpoint to the mesh with
  the exact shardings the executables were lowered for.
* ``exchange="psum"`` keeps the dense zeros-plus-psum assembly available
  (the fallback strategy and the CPU-backend resolution of "auto"), and
  capacity overflow inside "alltoall" mode falls back to psum via
  ``lax.cond`` within the same executable — jit-stable, never wrong.

The trace-time contract (`analysis/trace_audit.audit_sharded_predict`)
holds every bucket's lowering to this module's claims: all_to_all present,
no dense row-tensor collective outside the fallback arm, payload leaves as
parameters (not baked constants), swap-is-a-cache-hit.
"""

from __future__ import annotations

import os
from typing import Any, Callable, NamedTuple

from ...core.config import Config

# serve-group meshes reuse the framework-wide axis names: ``data`` shards
# the request batch, ``model`` row-shards the tables (parallel/mesh.py)


class ServeGroupContext(NamedTuple):
    """Everything a shard-group member needs to build and feed the sharded
    predict: the padded config, the group mesh, the payload sharding
    pytrees, and the resolved exchange mode."""

    cfg: Config                # feature_size padded; mesh carries (dp, mp)
    true_feature_size: int     # pre-padding vocab (id clip bound)
    mesh: Any                  # jax.sharding.Mesh over the group's devices
    payload_specs: Any         # PartitionSpec pytree for {params, model_state}
    payload_shardings: Any     # NamedSharding pytree (device placement)
    exchange: str              # "psum" | "alltoall" (resolved, never "auto")


def build_serve_mesh(data_parallel: int, model_parallel: int,
                     devices=None, group_index: int = 0):
    """Mesh over one shard-group's device slice.

    Groups tile the host's device list: group g takes devices
    ``[g*dp*mp, (g+1)*dp*mp)`` laid out ``[data, model]`` with the model
    axis innermost (ICI-adjacent table shards, parallel/mesh.build_mesh's
    layout rationale).  Lets several in-process groups coexist on one
    virtual mesh — the test/bench topology — and maps 1:1 onto per-host
    device slices in a real pool."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from ...parallel.mesh import DATA_AXIS, MODEL_AXIS

    devices = jax.devices() if devices is None else list(devices)
    need = data_parallel * model_parallel
    lo = group_index * need
    if lo + need > len(devices):
        raise ValueError(
            f"group {group_index} needs devices [{lo}, {lo + need}) but only "
            f"{len(devices)} exist (dp={data_parallel} x mp={model_parallel})"
        )
    arr = np.asarray(devices[lo:lo + need]).reshape(
        data_parallel, model_parallel
    )
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def resolve_serve_exchange(cfg: Config, backend: str | None = None) -> str:
    """Serving's resolution of ``ModelConfig.shard_exchange``: same policy
    as training (``resolve_shard_exchange`` — alltoall over a real
    interconnect, psum on the CPU shared-memory mesh), with the predict
    path's one difference folded in: a singleton model axis has no rows to
    exchange, so the mode demotes to psum outright."""
    from ...parallel.embedding import resolve_shard_exchange

    if cfg.mesh.model_parallel <= 1:
        return "psum"
    mode = cfg.model.shard_exchange
    if mode != "auto":
        return mode
    return resolve_shard_exchange(cfg, backend=backend)


def make_serve_context(
    cfg: Config, mesh, *, exchange: str | None = None
) -> ServeGroupContext:
    """Derive the group's padded config and payload shardings by shape
    inference only (no table ever materializes here — the spmd.make_context
    discipline, applied to the serve payload tree)."""
    import jax
    from jax.sharding import NamedSharding

    from ...models.base import get_model
    from ...parallel.mesh import mesh_shape
    from ...parallel.spmd import _spec_for_leaf, _window_multiple, padded_vocab

    dp, mp = mesh_shape(mesh)
    true_vocab = cfg.model.feature_size
    pv = padded_vocab(true_vocab, mp, _window_multiple(cfg))
    cfg = cfg.with_overrides(
        model={"feature_size": pv},
        mesh={"data_parallel": dp, "model_parallel": mp},
    )
    model = get_model(cfg.model)
    params, model_state = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), cfg.model)
    )
    payload_shapes = {"params": params, "model_state": model_state}
    specs = jax.tree_util.tree_map_with_path(
        lambda p, s: _spec_for_leaf(p, s.shape, pv), payload_shapes
    )
    shardings = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), specs
    )
    mode = exchange if exchange is not None else resolve_serve_exchange(cfg)
    if mode not in ("psum", "alltoall"):
        raise ValueError(
            f"exchange must resolve to 'psum' or 'alltoall', got {mode!r}"
        )
    if mp <= 1:
        mode = "psum"  # nothing to exchange on a singleton model axis
    return ServeGroupContext(
        cfg=cfg, true_feature_size=true_vocab, mesh=mesh,
        payload_specs=specs, payload_shardings=shardings, exchange=mode,
    )


def abstract_serve_payload(ctx: ServeGroupContext) -> dict:
    """ShapeDtypeStruct payload pytree — for the lowering-only trace audit
    (nothing materializes)."""
    import jax

    from ...models.base import get_model

    model = get_model(ctx.cfg.model)
    params, model_state = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), ctx.cfg.model)
    )
    return {"params": params, "model_state": model_state}


def build_sharded_predict_with(ctx: ServeGroupContext) -> Callable:
    """The weight-parameterized sharded predict:
    ``predict_with(payload, feat_ids, feat_vals) -> prob``.

    Batch rows shard over the data axis, tables row-shard over the model
    axis, lookups assemble rows with the resolved exchange inside
    ``shard_map`` — one XLA executable per bucket shape, parameterized by
    the (sharded) weights.  Ids are clipped to the TRUE vocab before the
    lookup: identical semantics to the single-process scorer's clip-mode
    ``dense_lookup`` (bit-parity's precondition), and the padding rows
    [true, padded) can never be gathered."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ...core.compat import shard_map
    from ...models.base import get_model
    from ...ops.embedding import narrow_ids
    from ...parallel.embedding import make_sharded_lookup_fn
    from ...parallel.mesh import DATA_AXIS

    cfg = ctx.cfg
    model = get_model(cfg.model)
    lookup = make_sharded_lookup_fn(
        table_grad=cfg.model.table_grad,
        exchange=ctx.exchange,
        capacity=cfg.model.shard_exchange_capacity,
    )
    true_vocab = ctx.true_feature_size

    def local_predict(payload, feat_ids, feat_vals):
        logits, _ = model.apply(
            payload["params"], payload["model_state"],
            feat_ids, feat_vals, cfg=cfg.model, train=False,
            lookup_fn=lookup,
        )
        return jax.nn.sigmoid(logits)

    mapped = shard_map(
        local_predict,
        mesh=ctx.mesh,
        in_specs=(ctx.payload_specs, P(DATA_AXIS, None), P(DATA_AXIS, None)),
        out_specs=P(DATA_AXIS),
        check_vma=False,  # psum-assembled lookups defeat replication checks
    )

    @jax.jit
    def predict_with(payload, feat_ids, feat_vals):
        # clip-mode id semantics (dense_lookup parity) + int64->int32
        # narrowing while still replicated — before rows shard out
        ids = jnp.clip(feat_ids, 0, true_vocab - 1)
        ids = narrow_ids(ids, true_vocab, cfg.model.narrow_ids)
        return mapped(payload, ids, feat_vals)

    return predict_with


def _pad_tables(params: dict, padded_rows: int) -> dict:
    """Zero-pad every embedding table's row dim up to the mesh's padded
    vocab (restored servables carry the TRUE vocab; the row-shard layout
    needs ``rows % mp == 0``).  Pad rows are zeros and — with the id clip
    in the predict — never gathered."""
    import jax.numpy as jnp

    from ...parallel.spmd import TABLE_KEYS

    out = dict(params)
    for k in TABLE_KEYS:
        if k in out and out[k].shape[0] < padded_rows:
            t = out[k]
            pad = [(0, padded_rows - t.shape[0])] + [(0, 0)] * (t.ndim - 1)
            out[k] = jnp.pad(t, pad)
    return out


def stage_sharded_payload(
    ctx: ServeGroupContext, params: dict, model_state: dict
) -> dict:
    """Commit a restored (host-side, true-vocab) checkpoint to the group
    mesh: pad the tables to the mesh's row multiple and place every leaf
    with the context's shardings.  The EXPLICIT placement matters exactly
    as in serve/reload.py: the executables were lowered for committed
    sharded arguments, so a staged payload with matching shardings keeps
    every swap a cache hit."""
    import jax

    payload = {
        "params": _pad_tables(params, ctx.cfg.model.feature_size),
        "model_state": model_state,
    }
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), payload, ctx.payload_shardings
    )


def group_wire_bytes_est(ctx: ServeGroupContext, bucket: int) -> int:
    """Estimated exchange bytes per ``bucket``-row dispatch per shard —
    the router's observability number (parallel/embedding.py
    exchange_wire_bytes_est over the group's table widths)."""
    from ...parallel.embedding import exchange_wire_bytes_est

    dp = ctx.cfg.mesh.data_parallel
    mp = ctx.cfg.mesh.model_parallel
    n_local = max(1, bucket // max(1, dp)) * ctx.cfg.model.field_size
    widths = (1, ctx.cfg.model.embedding_size)  # fm_w, fm_v
    return exchange_wire_bytes_est(
        n_local, mp, ctx.cfg.model.shard_exchange_capacity, widths,
        exchange=ctx.exchange,
    )


def load_sharded_servable(
    directory: str | os.PathLike,
    mesh,
    *,
    exchange: str | None = None,
):
    """Load a CTR servable row-sharded over a serve-group mesh.

    Returns ``(predict, predict_with, holder, ctx)`` — the same quartet
    surface as ``serve.reload.load_swappable_servable`` so the worker,
    swap coordinator, and audits treat single-process and shard-group
    servables uniformly:

      * ``predict(ids, vals)`` — engine-facing closure reading the live
        payload from ``holder`` (what the MicroBatcher wraps);
      * ``predict_with(payload, ids, vals)`` — the jitted sharded predict
        with explicit weights (canary + audit path);
      * ``holder`` — :class:`~deepfm_tpu.serve.reload.SwappableParams`
        (drain-aware atomic swap);
      * ``ctx`` — the :class:`ServeGroupContext`.
    """
    import jax

    from ...models.base import get_model
    from ..export import _load_config, _restore_payload
    from ..reload import SwappableParams

    directory = os.path.abspath(directory)
    cfg = _load_config(directory)
    if cfg.model.model_name == "two_tower":
        raise ValueError(
            "shard-group serving supports CTR servables; two-tower "
            "retrieval has no sharded predict path yet"
        )
    if cfg.model.tiered_embeddings:
        raise ValueError(
            "tiered servables page rows through the slot-space cache "
            "(deepfm_tpu/tiered/serving.py); the shard-group pool serves "
            "resident row-sharded tables"
        )
    ctx = make_serve_context(cfg, mesh, exchange=exchange)
    model = get_model(cfg.model)  # TRUE-vocab model for the restore
    params, model_state = _restore_payload(
        directory, lambda: model.init(jax.random.PRNGKey(0), cfg.model)
    )
    payload = stage_sharded_payload(ctx, params, model_state)
    holder = SwappableParams(payload, version=0)
    predict_with = build_sharded_predict_with(ctx)

    def predict(feat_ids, feat_vals):
        payload, gen = holder.acquire()
        try:
            out = predict_with(payload, feat_ids, feat_vals)
            # block before release (serve/reload.py): the generation must
            # not drain while the sharded executable is still running
            jax.block_until_ready(out)
            return out
        finally:
            holder.release(gen)

    return predict, predict_with, holder, ctx
