"""One shard-group member: the sharded scorer behind HTTP + swap admin.

A member owns one serve-group mesh slice (sharded.py), the micro-batching
engine over the sharded predict (every bucket a precompiled executable,
weights as arguments), and the member half of the group-atomic swap
protocol (swap.py drives it):

    POST /admin:stage    {"version": V[, "source": URL]}
        fetch + verify (param hash, spec compatibility) + CANARY the
        version against the live executables; hold it staged off-traffic.
    POST /admin:commit   {"generation": G, "version": V}
        atomically repoint the payload to the staged version and adopt
        group generation G (drain-aware: returns with all traffic on the
        new weights).  The previous payload is retained for one
        generation so a failed group commit can roll back.
    POST /admin:rollback
        swap back to the retained previous payload/generation.
    POST /admin:abort
        drop the staged payload (nothing was ever live).

**Generation-skew protection**: the router pins each request to one group
generation via the ``X-Pinned-Generation`` header; a member serving a
different generation answers 409 (a *skew abort*) instead of scoring —
so no request is ever scored by mixed-version shards, even mid-commit or
via a cross-member retry.

The HTTP surface extends ``serve/server.py``'s handler (same
``:predict``/``:predict_binary``/``/healthz``/``/readyz``/``/v1/metrics``
routes): predict responses carry ``shard_group`` + ``group_generation``
alongside ``model_version``, and ``/v1/metrics`` gains the ``router``
section (the ``group_status`` schema documented on ``make_handler``).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Callable

import numpy as np

from ...obs import flight as obs_flight
from ...obs.metrics import MetricsRegistry
from ...obs.trace import DEFAULT_SAMPLE_RATE, Tracer
from ..batcher import MicroBatcher
from ..server import ScoringHTTPServer, make_handler
from .sharded import group_wire_bytes_est, load_sharded_servable


class SwapProtocolError(RuntimeError):
    """A stage/commit/rollback call arrived out of protocol order (no
    staged payload, wrong generation, nothing to roll back) — mapped to
    HTTP 409 so the coordinator can tell protocol misuse from the 4xx/5xx
    of a genuinely failed verb."""


def _canary_batch(cfg, rows: int):
    """Zeros plus spread in-vocab ids (the HotSwapper probe construction):
    any non-finite or out-of-range probability fails the staged version."""
    f = cfg.model.field_size
    ids = np.zeros((rows, f), np.int64)
    if rows > 1:
        ids[1:] = np.linspace(
            0, max(0, cfg.model.feature_size - 1), (rows - 1) * f,
            dtype=np.int64,
        ).reshape(rows - 1, f)
    return ids, np.ones((rows, f), np.float32)


class GroupMember:
    """The in-process shard-group member (thread- or process-hosted).

    ``mesh`` spans this member's device slice; the tables live row-sharded
    on it and every predict runs the resolved exchange inside the bucket
    executables.  All swap-protocol state (staged payload, retained
    previous payload, group generation) is guarded by one lock; scoring
    never takes it (the holder's own drain machinery serializes swaps
    against in-flight dispatches)."""

    def __init__(
        self,
        servable_dir: str,
        mesh,
        *,
        group: str = "g0",
        member: str = "m0",
        buckets=(8, 32, 128, 512),
        max_wait_ms: float = 2.0,
        max_queue_rows: int | None = None,
        exchange: str | None = None,
        source: str | None = None,
        staging_dir: str | None = None,
        funnel_top_k: int = 0,
        funnel_return_n: int = 0,
        precompile: bool = True,
        registry: MetricsRegistry | None = None,
    ):
        from ...funnel.publish import is_funnel_servable
        from ...parallel.mesh import mesh_shape

        # one obs registry + trace tail per member process: the engine
        # renders into it and the handler serves GET /metrics from it
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        # router-propagated trace ids are always recorded (the head
        # decided); only direct member traffic is sampled locally
        self.tracer = Tracer(f"worker:{group}/{member}",
                             sample_rate=DEFAULT_SAMPLE_RATE)
        self.funnel = is_funnel_servable(os.path.abspath(servable_dir))
        if self.funnel:
            # a funnel member serves /v1/recommend: the retrieval index
            # row-shards over this member's mesh and ranking runs the
            # live weights — staged/committed as ONE payload through the
            # same group-atomic swap protocol as CTR weights
            from ...funnel.serve import FunnelScorer

            self._scorer = FunnelScorer(
                servable_dir, mesh, top_k=funnel_top_k,
                return_n=funnel_return_n, buckets=buckets,
                max_wait_ms=max_wait_ms, max_queue_rows=max_queue_rows,
                precompile=False, name=f"recommend[{group}/{member}]",
                registry=self.registry,
            )
            ctx = self._scorer.ctx
            holder = self._scorer.holder
            predict_with = None
            dp, _ = mesh_shape(mesh)
        else:
            self._scorer = None
            predict, predict_with, holder, ctx = load_sharded_servable(
                servable_dir, mesh, exchange=exchange
            )
            dp = ctx.cfg.mesh.data_parallel
        bad = [b for b in buckets if int(b) % dp != 0]
        if bad:
            raise ValueError(
                f"bucket sizes {bad} are not divisible by the group's "
                f"data_parallel={dp} — every dispatch shape must shard "
                f"evenly over the serve mesh"
            )
        self.group = group
        self.member = member
        self.ctx = ctx
        self._holder = holder
        self._predict_with = predict_with
        self._source = source
        # per-MEMBER staging: in-process members of one group must not
        # share an artifact cache, or one member's fetch would satisfy a
        # sibling's stage and mask its own store path (the chaos tests
        # script per-member store faults through exactly this seam)
        self._staging = staging_dir or os.path.join(
            tempfile.gettempdir(),
            f"deepfm_pool_{os.getpid()}_{group}_{member}",
        )
        os.makedirs(self._staging, exist_ok=True)
        if self.funnel:
            self.engine = self._scorer.engine
            self._canary = None  # the FunnelScorer canaries its own stages
        else:
            self.engine = MicroBatcher(
                predict, ctx.cfg.model.field_size, buckets=buckets,
                max_wait_ms=max_wait_ms, max_queue_rows=max_queue_rows,
                name=f"predict[{group}/{member}]",
                registry=self.registry,
            )
            self._canary = _canary_batch(ctx.cfg, int(sorted(buckets)[0]))
        self._lock = threading.Lock()
        self.generation = 0
        self._staged = None          # (payload, manifest)
        self._prev = None            # (payload, version, generation)
        self.skew_aborts_total = 0
        self.swaps_total = 0
        self.rollbacks_total = 0
        self.stage_failures_total = 0
        if precompile:
            # the funnel scorer brackets its warm-up so compile time never
            # lands in the serving metrics
            self.compile_secs = (self._scorer.precompile() if self.funnel
                                 else self.engine.precompile())

    # -- serving surface ----------------------------------------------------
    @property
    def version(self) -> int:
        return self._holder.version

    def reload_status(self) -> dict:
        with self._lock:
            return {
                "model_version": self._holder.version,
                "swaps_total": self.swaps_total,
                "rollbacks_total": self.rollbacks_total,
                "stage_failures_total": self.stage_failures_total,
                "staged_version": (
                    None if self._staged is None
                    else self._staged[1].version
                ),
            }

    def group_status(self) -> dict:
        """The ``group_status`` document (schema: serve/server.py
        make_handler) — predict responses, ``/readyz``, and the
        ``router`` metrics section all serve this."""
        if self.funnel:
            from ...funnel.index import funnel_wire_bytes_est
            from ...parallel.mesh import mesh_shape

            dp, mp = mesh_shape(self.ctx.mesh)
            return {
                "shard_group": self.group,
                "member": self.member,
                "group_generation": self.generation,
                "exchange": "funnel",   # candidate-pack all_gather merge
                "mesh": [dp, mp],
                "exchange_wire_bytes_est": funnel_wire_bytes_est(
                    self.ctx, max(self.engine.buckets)
                ),
                "skew_aborts_total": self.skew_aborts_total,
            }
        cfg = self.ctx.cfg
        return {
            "shard_group": self.group,
            "member": self.member,
            "group_generation": self.generation,
            "exchange": self.ctx.exchange,
            "mesh": [cfg.mesh.data_parallel, cfg.mesh.model_parallel],
            "exchange_wire_bytes_est": group_wire_bytes_est(
                self.ctx, max(self.engine.buckets)
            ),
            "skew_aborts_total": self.skew_aborts_total,
        }

    def readiness(self) -> dict:
        return {
            "ready": True, "engine_compiled": True, "weights_loaded": True,
            "model_version": self._holder.version,
        }

    # -- swap protocol (member half; swap.py is the coordinator) ------------
    def stage(self, version: int, source: str | None = None) -> dict:
        """Fetch, verify, and canary version ``version``; hold it staged.
        Raises on any verification failure (the artifact never goes
        live); the coordinator maps that to a group-wide abort."""
        import jax

        from ...models.base import get_model
        from ...online.publisher import param_tree_hash, resolve_version
        from ..export import _load_config, _restore_payload
        from .sharded import stage_sharded_payload

        root = source or self._source
        if not root:
            raise ValueError(
                "no publish root: member has no configured source and the "
                "stage request named none"
            )
        if self.funnel:
            # the FunnelScorer owns funnel staging: resolve + verify BOTH
            # hashes (rank weights + index) + canary both stages; the
            # staged object is the combined payload, so the group commit
            # below swaps weights and index atomically
            try:
                payload, manifest = self._scorer.stage_version(
                    root, int(version), self._staging
                )
            except Exception as e:
                with self._lock:
                    self.stage_failures_total += 1
                obs_flight.record(
                    "swap_stage_failed", subsystem="pool",
                    group=self.group, member=self.member,
                    version=int(version),
                    error=f"{type(e).__name__}: {e}",
                )
                raise
            with self._lock:
                self._staged = (payload, manifest)
            obs_flight.record(
                "swap_stage", subsystem="pool", group=self.group,
                member=self.member, version=manifest.version,
            )
            with self._lock:
                return {"staged_version": manifest.version,
                        "group_generation": self.generation}
        try:
            manifest, local = resolve_version(root, int(version),
                                              self._staging)
            served_cfg = _load_config(local)
            if (served_cfg.model.field_size
                    != self.ctx.cfg.model.field_size):
                raise ValueError(
                    f"version {version} has field_size "
                    f"{served_cfg.model.field_size}, group serves "
                    f"{self.ctx.cfg.model.field_size} — not hot-swappable"
                )
            model = get_model(served_cfg.model)
            params, model_state = _restore_payload(
                local,
                lambda: model.init(jax.random.PRNGKey(0), served_cfg.model),
            )
            got = param_tree_hash(params, model_state)
            if manifest.param_hash and got != manifest.param_hash:
                raise ValueError(
                    f"version {version} param hash mismatch (manifest "
                    f"{manifest.param_hash[:12]}…, staged {got[:12]}…) — "
                    f"torn or corrupted artifact"
                )
            payload = stage_sharded_payload(self.ctx, params, model_state)
            # canary through the LIVE bucket executables (same jit cache)
            probs = np.asarray(self._predict_with(payload, *self._canary))
            if not np.isfinite(probs).all():
                raise ValueError(
                    f"canary probe produced non-finite scores "
                    f"({int((~np.isfinite(probs)).sum())}/{probs.size} bad)"
                )
            if ((probs < 0.0) | (probs > 1.0)).any():
                raise ValueError(
                    "canary probe produced out-of-range scores"
                )
        except Exception as e:
            with self._lock:
                self.stage_failures_total += 1
            obs_flight.record(
                "swap_stage_failed", subsystem="pool", group=self.group,
                member=self.member, version=int(version),
                error=f"{type(e).__name__}: {e}",
            )
            raise
        with self._lock:
            self._staged = (payload, manifest)
        obs_flight.record(
            "swap_stage", subsystem="pool", group=self.group,
            member=self.member, version=manifest.version,
        )
        with self._lock:
            return {"staged_version": manifest.version,
                    "group_generation": self.generation}

    def commit(self, generation: int, version: int,
               drain_timeout_secs: float = 30.0) -> dict:
        """Swap the staged payload live and adopt ``generation``.  The
        old payload is retained for one generation (rollback window).

        ``generation`` must move FORWARD (> the member's current) but
        need not be the immediate successor: a respawned member restarts
        at generation 0 with the base servable, and the coordinator's
        repair pass (swap.py) catches it up by committing the group's
        CURRENT generation — a jump.  Replays and regressions (<=) stay
        protocol errors."""
        with self._lock:
            generation = int(generation)
            if self._staged is None:
                raise SwapProtocolError(
                    f"commit without a staged payload (member at "
                    f"generation {self.generation})"
                )
            payload, manifest = self._staged
            if manifest.version != int(version):
                raise SwapProtocolError(
                    f"commit names version {version} but staged is "
                    f"{manifest.version}"
                )
            if generation <= self.generation:
                raise SwapProtocolError(
                    f"commit generation {generation} does not advance "
                    f"the member's {self.generation}"
                )
            prev = (self._holder.get(), self._holder.version,
                    self.generation, self._holder.manifest)
            # adopt the generation BEFORE the payload swap: the swap
            # installs the new weights immediately and then blocks on the
            # drain (up to drain_timeout_secs) — a request pinned to the
            # OLD generation arriving in that window must already be
            # refused, not scored on the new weights under an old label
            self.generation = generation
            drained = self._holder.swap(
                payload, version=manifest.version, manifest=manifest,
                drain_timeout_secs=drain_timeout_secs,
            )
            self._prev = prev
            self._staged = None
            self.swaps_total += 1
            obs_flight.record(
                "swap_commit", subsystem="pool", group=self.group,
                member=self.member, generation=self.generation,
                version=self._holder.version, drained=bool(drained),
            )
            return {"group_generation": self.generation,
                    "model_version": self._holder.version,
                    "drained": bool(drained)}

    def rollback(self) -> dict:
        """Return to the retained pre-commit payload and generation (the
        group coordinator's answer to a partial commit)."""
        with self._lock:
            if self._prev is None:
                raise SwapProtocolError("nothing to roll back")
            payload, ver, gen, manifest = self._prev
            # same ordering as commit: generation first, then the payload.
            # The manifest rides along: a rolled-back funnel member must
            # keep reporting the LIVE index's version/occupancy, not the
            # boot servable's
            self.generation = gen
            self._holder.swap(payload, version=ver, manifest=manifest)
            self._prev = None
            self.rollbacks_total += 1
            obs_flight.record(
                "swap_rollback", subsystem="pool", group=self.group,
                member=self.member, generation=gen, version=ver,
            )
            return {"group_generation": self.generation,
                    "model_version": self._holder.version}

    def abort(self) -> dict:
        with self._lock:
            had = self._staged is not None
            self._staged = None
            gen = self.generation
        if had:
            obs_flight.record("swap_abort", subsystem="pool",
                              group=self.group, member=self.member,
                              generation=gen)
        return {"aborted": had, "group_generation": gen}

    def close(self) -> None:
        self.engine.close()


def make_member_handler(member: GroupMember, model_name: str):
    """The member HTTP surface: serve/server.py's handler (predict,
    health, metrics — with the group_status extension) plus the swap
    admin routes and the generation-skew gate."""
    base = make_handler(
        member.engine, model_name,
        reload_status=member.reload_status,
        readiness=member.readiness,
        group_status=member.group_status,
        registry=member.registry,
        tracer=member.tracer,
    )
    predict_paths = {
        f"/v1/models/{model_name}:predict",
        f"/v1/models/{model_name}:predict_binary",
    }
    if getattr(member, "funnel", False):
        # the funnel scoring route rides the same generation-skew gate:
        # a pinned recommend must never score across a group commit
        from ...funnel.serve import RECOMMEND_PATH

        predict_paths = predict_paths | {RECOMMEND_PATH}
    admin: dict[str, Callable[[dict], dict]] = {
        "/admin:stage": lambda b: member.stage(
            b["version"], b.get("source")
        ),
        "/admin:commit": lambda b: member.commit(
            b["generation"], b["version"]
        ),
        "/admin:rollback": lambda b: member.rollback(),
        "/admin:abort": lambda b: member.abort(),
    }

    class MemberHandler(base):
        def do_POST(self):  # noqa: N802
            if self.path in admin:
                return self._do_admin(admin[self.path])
            if self.path in predict_paths:
                pinned = self.headers.get("X-Pinned-Generation")
                if pinned is not None:
                    try:
                        want = int(pinned)
                    except ValueError:
                        self._drain_body()
                        return self._send(
                            400, {"error": f"bad X-Pinned-Generation "
                                           f"{pinned!r}"}
                        )
                    if want != member.generation:
                        # the skew abort: refuse, never score — the
                        # router re-pins and retries
                        member.skew_aborts_total += 1
                        obs_flight.record(
                            "skew_abort", subsystem="pool",
                            group=member.group, member=member.member,
                            pinned_generation=want,
                            group_generation=member.generation,
                        )
                        self._drain_body()
                        return self._send(409, {
                            "error": "generation skew",
                            "pinned_generation": want,
                            "shard_group": member.group,
                            "group_generation": member.generation,
                        })
                if (getattr(member, "funnel", False)
                        and self.path == "/v1/recommend"):
                    # recommend rides the same trace tail as predict:
                    # adopt the router-propagated X-Trace-Id (or the
                    # client's) so the funnel spans join the one trace
                    ctx = member.tracer.begin("recommend", self.headers)
                    token = member.tracer.activate(ctx)
                    self._obs_status = None
                    try:
                        return self._do_recommend()
                    finally:
                        member.tracer.finish(ctx, token,
                                             status=self._obs_status)
            return super().do_POST()

        def _do_recommend(self):
            from ...funnel.serve import handle_recommend

            try:
                length = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(length))
            except Exception as e:
                return self._send(400,
                                  {"error": f"{type(e).__name__}: {e}"})
            code, doc = handle_recommend(member._scorer, req)
            if code == 200:
                # group attribution alongside the atomic version pair
                doc["shard_group"] = member.group
                doc["group_generation"] = member.generation
            self._send(code, doc)

        def _drain_body(self):
            # an early reject must still consume the request body, or the
            # unread bytes desynchronize the HTTP/1.1 keep-alive framing
            # (the next request line would be parsed out of this body)
            length = int(self.headers.get("Content-Length", "0") or 0)
            while length > 0:
                chunk = self.rfile.read(min(length, 1 << 16))
                if not chunk:
                    break
                length -= len(chunk)

        def _do_admin(self, fn):
            try:
                length = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(length) or b"{}")
            except Exception as e:
                return self._send(400,
                                  {"error": f"{type(e).__name__}: {e}"})
            try:
                doc = fn(body)
            except SwapProtocolError as e:
                return self._send(409, {"error": str(e)})
            except (ValueError, KeyError, TypeError) as e:
                return self._send(400,
                                  {"error": f"{type(e).__name__}: {e}"})
            except Exception as e:
                return self._send(500,
                                  {"error": f"{type(e).__name__}: {e}"})
            self._send(200, doc)

    return MemberHandler


def start_member(
    servable_dir: str,
    mesh,
    *,
    group: str = "g0",
    member: str = "m0",
    model_name: str = "deepfm",
    host: str = "127.0.0.1",
    port: int = 0,
    **member_kw,
) -> tuple[ScoringHTTPServer, str, GroupMember]:
    """In-process member on a daemon thread (the test/bench topology; the
    process-pool CLI wraps ``serve_member`` instead).  Returns
    ``(server, base_url, member)``; callers own shutdown
    (``server.shutdown(); member.close()``)."""
    gm = GroupMember(servable_dir, mesh, group=group, member=member,
                     **member_kw)
    httpd = ScoringHTTPServer(
        (host, port), make_member_handler(gm, model_name)
    )
    threading.Thread(
        target=httpd.serve_forever, daemon=True,
        name=f"pool-member-{group}-{member}",
    ).start()
    url = f"http://{host}:{httpd.server_address[1]}"
    return httpd, url, gm


def serve_member(
    servable_dir: str,
    *,
    group: str,
    member: str = "m0",
    data_parallel: int = 1,
    model_parallel: int = 0,
    group_index: int = 0,
    model_name: str = "deepfm",
    host: str = "127.0.0.1",
    port: int = 0,
    ready: threading.Event | None = None,
    **member_kw,
) -> None:
    """Blocking process entry (serve/pool/__main__.py forks one per
    member): build the group mesh over this member's device slice, load
    the sharded servable, announce, serve until killed."""
    import sys

    import jax

    from .sharded import build_serve_mesh

    if model_parallel <= 0:
        model_parallel = max(1, len(jax.devices()) // max(1, data_parallel))
    mesh = build_serve_mesh(data_parallel, model_parallel,
                            group_index=group_index)
    gm = GroupMember(servable_dir, mesh, group=group, member=member,
                     **member_kw)
    httpd = ScoringHTTPServer((host, port),
                              make_member_handler(gm, model_name))
    if ready is not None:
        ready.port = httpd.server_address[1]  # type: ignore[attr-defined]
        ready.set()
    print(
        f"pool member {group}/{member}: serving {model_name} on "
        f"http://{host}:{httpd.server_address[1]} "
        f"(mesh [{data_parallel},{model_parallel}], "
        f"exchange {gm.ctx.exchange})",
        file=sys.stderr,
    )
    httpd.serve_forever()
