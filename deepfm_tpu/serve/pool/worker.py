"""One shard-group member: the sharded scorer behind HTTP + swap admin.

A member owns one serve-group mesh slice (sharded.py), the micro-batching
engine over the sharded predict (every bucket a precompiled executable,
weights as arguments), and the member half of the group-atomic swap
protocol (swap.py drives it):

    POST /admin:stage    {"version": V[, "source": URL]}
        fetch + verify (param hash, spec compatibility) + CANARY the
        version against the live executables; hold it staged off-traffic.
    POST /admin:commit   {"generation": G, "version": V}
        atomically repoint the payload to the staged version and adopt
        group generation G (drain-aware: returns with all traffic on the
        new weights).  The previous payload is retained for one
        generation so a failed group commit can roll back.
    POST /admin:rollback
        swap back to the retained previous payload/generation.
    POST /admin:abort
        drop the staged payload (nothing was ever live).

**Generation-skew protection**: the router pins each request to one group
generation via the ``X-Pinned-Generation`` header; a member serving a
different generation answers 409 (a *skew abort*) instead of scoring —
so no request is ever scored by mixed-version shards, even mid-commit or
via a cross-member retry.

**Multi-tenant members** (deepfm_tpu/fleet): a member can serve N model
variants — *tenants* — from ONE set of precompiled bucket executables,
because the weights ride the jitted predict as ARGUMENTS.  Each tenant
gets its own payload holder, its own coalescing engine (per-tenant
queues: one tenant's burst cannot pad another's dispatches), its own
generation, and its own swap-protocol state; the executables are shared
(pinned by the ``audit_multitenant`` trace contract).  Requests select a
tenant via the ``X-Tenant`` header (default: the member's first tenant),
admin verbs carry an optional ``"tenant"`` field, and the generation-skew
gate is keyed by (tenant, generation) — tenant A's hot swap can never
roll back, skew-abort, or contaminate tenant B.

The HTTP surface extends ``serve/server.py``'s handler (same
``:predict``/``:predict_binary``/``/healthz``/``/readyz``/``/v1/metrics``
routes): predict responses carry ``shard_group`` + ``group_generation`` +
``tenant`` alongside ``model_version``, ``/readyz`` carries the
per-tenant ``tenants`` map the router pins generations from, and
``/v1/metrics`` gains the ``router`` section plus a ``tenants`` section
(the ``group_status`` schema documented on ``make_handler``).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Callable

import numpy as np

from ...fleet.registry import DEFAULT_TENANT, TenantSpec, parse_tenants
from ...obs import flight as obs_flight
from ...obs.metrics import MetricsRegistry
from ...obs.trace import DEFAULT_SAMPLE_RATE, Tracer
from ..batcher import MicroBatcher
from ..reload import SwappableParams
from ..server import ScoringHTTPServer, make_handler
from .sharded import group_wire_bytes_est, load_sharded_servable


class SwapProtocolError(RuntimeError):
    """A stage/commit/rollback call arrived out of protocol order (no
    staged payload, wrong generation, nothing to roll back) — mapped to
    HTTP 409 so the coordinator can tell protocol misuse from the 4xx/5xx
    of a genuinely failed verb."""


class _TenantState:
    """One tenant's slice of a member: its payload holder, its coalescing
    engine, its generation, and its swap-protocol state — everything
    EXCEPT the executables, which every same-spec tenant shares (the
    fleet's point).  A plain container: all mutation happens in
    GroupMember methods under the member lock."""

    __slots__ = ("name", "source", "holder", "engine", "generation",
                 "staged", "prev", "skew_aborts_total", "swaps_total",
                 "rollbacks_total", "stage_failures_total")

    def __init__(self, name: str, source: str | None):
        self.name = name
        self.source = source or ""
        self.holder = None           # SwappableParams
        self.engine = None           # MicroBatcher
        self.generation = 0
        self.staged = None           # (payload, manifest)
        self.prev = None             # (payload, version, gen, manifest)
        self.skew_aborts_total = 0
        self.swaps_total = 0
        self.rollbacks_total = 0
        self.stage_failures_total = 0


class _TenantDispatch:
    """The engine facade ``make_handler`` scores through: each handler
    thread selects its tenant (``X-Tenant`` header — MemberHandler does
    it before delegating) and score calls land on that tenant's
    coalescing engine.  ``metrics_snapshot`` keeps the pinned
    single-engine schema (the default tenant's engine);
    ``tenants_snapshot`` is the ``tenants``-section hook
    (serve/server.py)."""

    # the handler passes X-Deadline-Ms / X-Priority kwargs through to
    # the tenant engine (serve/server.py _slo_kwargs gates on this)
    supports_deadline = True

    def __init__(self, member: "GroupMember"):
        self._member = member

    def _engine(self):
        return self._member._tenant().engine

    def score(self, ids, vals, **kw):
        return self._engine().score(ids, vals, **kw)

    def score_instances(self, instances, **kw):
        return self._engine().score_instances(instances, **kw)

    def metrics_snapshot(self) -> dict:
        return self._member.engine.metrics_snapshot()

    def __getattr__(self, attr):
        # funnel members: the ``funnel`` /v1/metrics section rides the
        # same hasattr hook (serve/server.py) — forward it from the
        # FunnelScorer; absent on CTR members so the hook stays off
        if attr == "funnel_snapshot" and self._member._scorer is not None:
            return self._member._scorer.funnel_snapshot
        raise AttributeError(attr)

    def tenants_snapshot(self) -> dict:
        return self._member.tenants_snapshot()


def _canary_batch(cfg, rows: int):
    """Zeros plus spread in-vocab ids (the HotSwapper probe construction):
    any non-finite or out-of-range probability fails the staged version."""
    f = cfg.model.field_size
    ids = np.zeros((rows, f), np.int64)
    if rows > 1:
        ids[1:] = np.linspace(
            0, max(0, cfg.model.feature_size - 1), (rows - 1) * f,
            dtype=np.int64,
        ).reshape(rows - 1, f)
    return ids, np.ones((rows, f), np.float32)


class GroupMember:
    """The in-process shard-group member (thread- or process-hosted).

    ``mesh`` spans this member's device slice; the tables live row-sharded
    on it and every predict runs the resolved exchange inside the bucket
    executables.  All swap-protocol state (staged payload, retained
    previous payload, group generation) is guarded by one lock; scoring
    never takes it (the holder's own drain machinery serializes swaps
    against in-flight dispatches)."""

    def __init__(
        self,
        servable_dir: str,
        mesh,
        *,
        group: str = "g0",
        member: str = "m0",
        buckets=(8, 32, 128, 512),
        max_wait_ms: float = 2.0,
        max_queue_rows: int | None = None,
        exchange: str | None = None,
        source: str | None = None,
        staging_dir: str | None = None,
        funnel_top_k: int = 0,
        funnel_return_n: int = 0,
        funnel_retrieval: str = "",
        funnel_oversample: int = 0,
        funnel_pallas: str = "",
        precompile: bool = True,
        registry: MetricsRegistry | None = None,
        tenants=None,
        slo=None,
    ):
        from ...funnel.publish import is_funnel_servable
        from ...parallel.mesh import mesh_shape

        # one obs registry + trace tail per member process: the engine
        # renders into it and the handler serves GET /metrics from it
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        # router-propagated trace ids are always recorded (the head
        # decided); only direct member traffic is sampled locally
        self.tracer = Tracer(f"worker:{group}/{member}",
                             sample_rate=DEFAULT_SAMPLE_RATE)
        self.funnel = is_funnel_servable(os.path.abspath(servable_dir))
        specs = parse_tenants(tenants) if tenants else ()
        if specs and self.funnel:
            raise ValueError(
                "multi-tenant serving supports CTR servables; a funnel "
                "member serves its one published funnel"
            )
        if self.funnel:
            # a funnel member serves /v1/recommend: the retrieval index
            # row-shards over this member's mesh and ranking runs the
            # live weights — staged/committed as ONE payload through the
            # same group-atomic swap protocol as CTR weights
            from ...funnel.serve import FunnelScorer

            # a funnel member with an SLO gets its admission controller
            # built FIRST: the scorer wires it into its engine (deadline
            # pricing + the shed ladder on /v1/recommend) and — for int8
            # retrieval — compiles the degraded-oversample executable
            # the ladder's level-2 narrows to
            self.admission = None
            if slo is not None:
                from ..control.admission import AdmissionController
                from ..control.cost import BucketCostModel

                self.admission = AdmissionController(
                    BucketCostModel(buckets),
                    deadline_ms=slo.deadline_ms,
                    shed_shadow_util=slo.shed_shadow_util,
                    degrade_util=slo.degrade_util,
                    shed_predict_util=slo.shed_predict_util,
                    degrade_floor_pct=slo.degrade_floor_pct,
                    name=f"recommend[{group}/{member}]",
                    registry=self.registry,
                )
            self._scorer = FunnelScorer(
                servable_dir, mesh, top_k=funnel_top_k,
                return_n=funnel_return_n, retrieval=funnel_retrieval,
                oversample=funnel_oversample, pallas=funnel_pallas,
                buckets=buckets,
                max_wait_ms=max_wait_ms, max_queue_rows=max_queue_rows,
                admission=self.admission,
                precompile=False, name=f"recommend[{group}/{member}]",
                registry=self.registry,
            )
            ctx = self._scorer.ctx
            holder = self._scorer.holder
            predict_with = None
            dp, _ = mesh_shape(mesh)
        else:
            self._scorer = None
            predict, predict_with, holder, ctx = load_sharded_servable(
                servable_dir, mesh, exchange=exchange
            )
            dp = ctx.cfg.mesh.data_parallel
        bad = [b for b in buckets if int(b) % dp != 0]
        if bad:
            raise ValueError(
                f"bucket sizes {bad} are not divisible by the group's "
                f"data_parallel={dp} — every dispatch shape must shard "
                f"evenly over the serve mesh"
            )
        self.group = group
        self.member = member
        self.ctx = ctx
        self._predict_with = predict_with
        self._source = source
        # per-MEMBER staging: in-process members of one group must not
        # share an artifact cache, or one member's fetch would satisfy a
        # sibling's stage and mask its own store path (the chaos tests
        # script per-member store faults through exactly this seam)
        self._staging = staging_dir or os.path.join(
            tempfile.gettempdir(),
            f"deepfm_pool_{os.getpid()}_{group}_{member}",
        )
        os.makedirs(self._staging, exist_ok=True)
        # tenant table (deepfm_tpu/fleet): a member ALWAYS serves >= 1
        # tenant; a pool launched without a fleet config is a one-tenant
        # fleet named DEFAULT_TENANT and the tenant-less wire surface
        # (no X-Tenant header, no "tenant" admin field) maps onto it
        if not specs:
            specs = (TenantSpec(name=DEFAULT_TENANT, source=source or ""),)
        self._lock = threading.Lock()
        # ONE device-dispatch lock across every tenant engine: the tenant
        # engines coalesce independently (per-tenant queues), but their
        # dispatches land on the SAME device set, where two concurrent
        # multi-device collective programs can interleave per-device
        # executions and deadlock on XLA:CPU (the shared-executor hazard
        # the elastic drill isolates with member subprocesses).  The
        # devices run one program at a time productively anyway, so
        # serializing at dispatch costs nothing real — and the canary in
        # stage() takes the same lock so a swap never races live traffic
        # onto the executor either.
        self._dispatch_lock = threading.Lock()
        self._selected = threading.local()
        self._tenants: dict[str, _TenantState] = {}
        self._default = specs[0].name
        self.skew_aborts_total = 0
        self.swaps_total = 0
        self.rollbacks_total = 0
        self.stage_failures_total = 0
        # the `tenant` label on the obs registry (PR 10): per-tenant
        # lifecycle events alongside the per-engine serving families
        self._tenant_events = self.registry.counter(
            "deepfm_pool_tenant_events_total",
            "per-tenant member lifecycle events",
            labels=("tenant", "event"))
        # ONE admission controller across every tenant engine (``slo`` is
        # a core.config.SloConfig): the tenants share the same bucket
        # executables and the same devices, so one cost model prices all
        # of them and one shed ladder answers for the member's queue
        # pressure.  Funnel members built theirs above, before the
        # scorer, so it rides inside the FunnelScorer's engine.
        if not self.funnel:
            self.admission = None
            if slo is not None:
                from ..control.admission import AdmissionController
                from ..control.cost import BucketCostModel

                self.admission = AdmissionController(
                    BucketCostModel(buckets),
                    deadline_ms=slo.deadline_ms,
                    shed_shadow_util=slo.shed_shadow_util,
                    degrade_util=slo.degrade_util,
                    shed_predict_util=slo.shed_predict_util,
                    degrade_floor_pct=slo.degrade_floor_pct,
                    name=f"predict[{group}/{member}]",
                    registry=self.registry,
                )
        if self.funnel:
            ts = _TenantState(specs[0].name, specs[0].source or source)
            ts.holder = holder
            ts.engine = self._scorer.engine
            self._tenants[ts.name] = ts
            self._canary = None  # the FunnelScorer canaries its own stages
        else:
            self._canary = _canary_batch(ctx.cfg, int(sorted(buckets)[0]))
            base_payload = holder.get()
            multi = len(specs) > 1
            for i, spec in enumerate(specs):
                ts = _TenantState(spec.name, spec.source or source)
                # tenant 0 adopts the loader's holder (the boot payload);
                # the rest hold the SAME base payload — immutable device
                # arrays, so N tenants cost nothing until they diverge by
                # swapping their own versions in
                ts.holder = (holder if i == 0
                             else SwappableParams(base_payload, version=0))
                ts.engine = MicroBatcher(
                    self._tenant_predict(ts.holder),
                    ctx.cfg.model.field_size, buckets=buckets,
                    max_wait_ms=max_wait_ms, max_queue_rows=max_queue_rows,
                    name=(f"predict[{group}/{member}/{spec.name}]" if multi
                          else f"predict[{group}/{member}]"),
                    registry=self.registry,
                    admission=self.admission,
                )
                self._tenants[ts.name] = ts
        self.engine = self._tenants[self._default].engine
        self._holder = self._tenants[self._default].holder
        self.dispatch = _TenantDispatch(self)
        if precompile:
            # the funnel scorer brackets its warm-up so compile time never
            # lands in the serving metrics.  Tenant 0's precompile builds
            # the shared bucket executables; every further tenant's is a
            # jit cache hit (same specs, payload as argument) — the
            # near-zero marginal cost BENCH_MULTITENANT measures
            if self.funnel:
                self.compile_secs = self._scorer.precompile()
            else:
                self.tenant_compile_secs = {
                    name: ts.engine.precompile()
                    for name, ts in self._tenants.items()
                }
                self.compile_secs = self.tenant_compile_secs[self._default]

    def _tenant_predict(self, holder) -> Callable:
        """Engine-facing closure for one tenant's holder over the SHARED
        jitted predict (the load_sharded_servable closure, per tenant)."""
        import jax

        predict_with = self._predict_with

        def predict(feat_ids, feat_vals):
            payload, gen = holder.acquire()
            try:
                # one multi-device program on the executor at a time
                # (see _dispatch_lock): per-tenant queues coalesce
                # concurrently, dispatches serialize
                with self._dispatch_lock:
                    out = predict_with(payload, feat_ids, feat_vals)
                    # block before release (serve/reload.py): the
                    # generation must not drain while the executable is
                    # still running
                    # da:allow[blocking-under-lock] _dispatch_lock exists to serialize device dispatch (one multi-device program on the executor at a time); the wait IS the lock's purpose
                    jax.block_until_ready(out)
                return out
            finally:
                holder.release(gen)

        return predict

    # -- tenant selection (per handler thread) ------------------------------
    def tenant_names(self) -> list[str]:
        return list(self._tenants)

    def _tenant(self, name: str | None = None) -> _TenantState:
        key = name if name is not None else self.selected_tenant()
        try:
            return self._tenants[key]
        except KeyError:
            raise ValueError(
                f"unknown tenant {key!r} (member serves "
                f"{list(self._tenants)})"
            ) from None

    def select_tenant(self, name: str | None) -> None:
        """Pin the calling thread's tenant (the handler sets it from the
        X-Tenant header for the request's duration; None = default)."""
        self._selected.name = name

    def selected_tenant(self) -> str:
        return getattr(self._selected, "name", None) or self._default

    # -- serving surface ----------------------------------------------------
    @property
    def generation(self) -> int:
        """The DEFAULT tenant's generation (legacy single-tenant surface;
        per-tenant generations ride ``readiness()['tenants']``)."""
        return self._tenants[self._default].generation

    @generation.setter
    def generation(self, value: int) -> None:
        self._tenants[self._default].generation = int(value)

    @property
    def version(self) -> int:
        return self._holder.version

    def reload_status(self) -> dict:
        ts = self._tenant()
        with self._lock:
            return {
                "model_version": ts.holder.version,
                "tenant": ts.name,
                "swaps_total": self.swaps_total,
                "rollbacks_total": self.rollbacks_total,
                "stage_failures_total": self.stage_failures_total,
                "staged_version": (
                    None if ts.staged is None
                    else ts.staged[1].version
                ),
            }

    def tenants_snapshot(self) -> dict:
        """The ``tenants`` section of ``/v1/metrics`` (served through the
        ``tenants_snapshot`` hook, serve/server.py make_handler).
        Lock-free like ``readiness`` — a metrics scrape must not queue
        behind a commit's swap drain."""
        out = {}
        for ts in self._tenants.values():
            staged = ts.staged
            doc = {
                "generation": ts.generation,
                "model_version": ts.holder.version,
                "source": ts.source,
                "staged_version": (None if staged is None
                                   else staged[1].version),
                "skew_aborts_total": ts.skew_aborts_total,
                "swaps_total": ts.swaps_total,
                "rollbacks_total": ts.rollbacks_total,
            }
            if hasattr(ts.engine, "metrics_snapshot"):
                doc["engine"] = ts.engine.metrics_snapshot()
            out[ts.name] = doc
        return out

    def group_status(self) -> dict:
        """The ``group_status`` document (schema: serve/server.py
        make_handler) — predict responses, ``/readyz``, and the
        ``router`` metrics section all serve this.  ``tenant`` and
        ``group_generation`` describe the handler thread's SELECTED
        tenant (the request's, via X-Tenant; the default tenant
        elsewhere)."""
        ts = self._tenant()
        if self.funnel:
            from ...funnel.index import funnel_wire_bytes_est
            from ...parallel.mesh import mesh_shape

            dp, mp = mesh_shape(self.ctx.mesh)
            return {
                "shard_group": self.group,
                "member": self.member,
                "tenant": ts.name,
                "group_generation": ts.generation,
                "exchange": "funnel",   # candidate-pack all_gather merge
                "mesh": [dp, mp],
                "exchange_wire_bytes_est": funnel_wire_bytes_est(
                    self.ctx, max(self.engine.buckets)
                ),
                "skew_aborts_total": self.skew_aborts_total,
            }
        cfg = self.ctx.cfg
        return {
            "shard_group": self.group,
            "member": self.member,
            "tenant": ts.name,
            "group_generation": ts.generation,
            "exchange": self.ctx.exchange,
            "mesh": [cfg.mesh.data_parallel, cfg.mesh.model_parallel],
            "exchange_wire_bytes_est": group_wire_bytes_est(
                self.ctx, max(self.engine.buckets)
            ),
            "skew_aborts_total": self.skew_aborts_total,
        }

    def readiness(self) -> dict:
        # the per-tenant map is what the router pins generations from and
        # what the per-tenant swap coordinator's repair pass reads.
        # Lock-FREE: commit() holds the member lock across the swap drain
        # (up to 30 s), and a /readyz that stalls that long ejects a
        # healthy mid-swap member from the router.  The tenant table
        # never mutates after __init__, and slightly-stale ints are
        # exactly what a probe racing a commit should see
        tenants = {
            name: {"generation": ts.generation,
                   "model_version": ts.holder.version}
            for name, ts in self._tenants.items()
        }
        doc = {
            "ready": True, "engine_compiled": True, "weights_loaded": True,
            "model_version": self._holder.version,
            "tenants": tenants,
        }
        if self.funnel:
            doc["retrieval_mode"] = self._scorer.ctx.retrieval_mode
        return doc

    # -- swap protocol (member half; swap.py is the coordinator) ------------
    def stage(self, version: int, source: str | None = None,
              tenant: str | None = None) -> dict:
        """Fetch, verify, and canary version ``version`` for ``tenant``
        (default: the member's first tenant); hold it staged on that
        tenant's slot.  Raises on any verification failure (the artifact
        never goes live); the coordinator maps that to a group-wide
        abort."""
        import jax

        from ...core.config import tenant_spec_divergence
        from ...models.base import get_model
        from ...online.publisher import param_tree_hash, resolve_version
        from ..export import _load_config, _restore_payload
        from .sharded import stage_sharded_payload

        ts = self._tenant(tenant)
        root = source or ts.source or self._source
        if not root:
            raise ValueError(
                f"no publish root: tenant {ts.name!r} has no configured "
                f"source and the stage request named none"
            )
        if self.funnel:
            # the FunnelScorer owns funnel staging: resolve + verify BOTH
            # hashes (rank weights + index) + canary both stages; the
            # staged object is the combined payload, so the group commit
            # below swaps weights and index atomically
            try:
                payload, manifest = self._scorer.stage_version(
                    root, int(version), self._staging
                )
            except Exception as e:
                with self._lock:
                    self.stage_failures_total += 1
                    ts.stage_failures_total += 1
                obs_flight.record(
                    "swap_stage_failed", subsystem="pool",
                    group=self.group, member=self.member, tenant=ts.name,
                    version=int(version),
                    error=f"{type(e).__name__}: {e}",
                )
                raise
            with self._lock:
                ts.staged = (payload, manifest)
            obs_flight.record(
                "swap_stage", subsystem="pool", group=self.group,
                member=self.member, tenant=ts.name,
                version=manifest.version,
            )
            with self._lock:
                return {"staged_version": manifest.version,
                        "tenant": ts.name,
                        "group_generation": ts.generation}
        try:
            # staging cache keyed per TENANT: two tenants publishing the
            # same version NUMBER from different roots must not satisfy
            # each other's fetch (the param-hash check would reject the
            # reused bytes forever on remote roots)
            manifest, local = resolve_version(
                root, int(version), os.path.join(self._staging, ts.name)
            )
            served_cfg = _load_config(local)
            # the runtime half of the fleet's spec gate: a republished
            # tenant whose model section diverged on ANY executable-spec
            # field is refused here, at stage time, with the fields named
            # — never discovered as a mid-traffic recompile
            import dataclasses as _dc

            diff = tenant_spec_divergence(
                _dc.asdict(self.ctx.cfg.model),
                _dc.asdict(served_cfg.model),
            )
            if diff:
                raise ValueError(
                    f"version {version} diverges from the group's "
                    f"executable spec on {diff} — not hot-swappable onto "
                    f"shared executables "
                    f"(core.config.EXECUTABLE_SPEC_FIELDS)"
                )
            model = get_model(served_cfg.model)
            params, model_state = _restore_payload(
                local,
                lambda: model.init(jax.random.PRNGKey(0), served_cfg.model),
            )
            got = param_tree_hash(params, model_state)
            if manifest.param_hash and got != manifest.param_hash:
                raise ValueError(
                    f"version {version} param hash mismatch (manifest "
                    f"{manifest.param_hash[:12]}…, staged {got[:12]}…) — "
                    f"torn or corrupted artifact"
                )
            payload = stage_sharded_payload(self.ctx, params, model_state)
            # canary through the LIVE bucket executables (same jit
            # cache), serialized with serving dispatches (_dispatch_lock)
            with self._dispatch_lock:
                probs = np.asarray(
                    self._predict_with(payload, *self._canary)
                )
            if not np.isfinite(probs).all():
                raise ValueError(
                    f"canary probe produced non-finite scores "
                    f"({int((~np.isfinite(probs)).sum())}/{probs.size} bad)"
                )
            if ((probs < 0.0) | (probs > 1.0)).any():
                raise ValueError(
                    "canary probe produced out-of-range scores"
                )
        except Exception as e:
            with self._lock:
                self.stage_failures_total += 1
                ts.stage_failures_total += 1
            self._tenant_events.labels(ts.name, "stage_failed").inc()
            obs_flight.record(
                "swap_stage_failed", subsystem="pool", group=self.group,
                member=self.member, tenant=ts.name, version=int(version),
                error=f"{type(e).__name__}: {e}",
            )
            raise
        with self._lock:
            ts.staged = (payload, manifest)
        obs_flight.record(
            "swap_stage", subsystem="pool", group=self.group,
            member=self.member, tenant=ts.name, version=manifest.version,
        )
        with self._lock:
            return {"staged_version": manifest.version,
                    "tenant": ts.name,
                    "group_generation": ts.generation}

    def commit(self, generation: int, version: int,
               drain_timeout_secs: float = 30.0,
               tenant: str | None = None) -> dict:
        """Swap ``tenant``'s staged payload live and adopt ``generation``
        on that tenant.  The old payload is retained for one generation
        (rollback window).  Generations are PER TENANT: committing tenant
        A moves only A's generation, drains only A's holder, and can
        never roll back or relabel tenant B's traffic.

        ``generation`` must move FORWARD (> the tenant's current) but
        need not be the immediate successor: a respawned member restarts
        at generation 0 with the base servable, and the coordinator's
        repair pass (swap.py) catches it up by committing the group's
        CURRENT generation — a jump.  Replays and regressions (<=) stay
        protocol errors."""
        ts = self._tenant(tenant)
        with self._lock:
            generation = int(generation)
            if ts.staged is None:
                raise SwapProtocolError(
                    f"commit without a staged payload (tenant {ts.name!r} "
                    f"at generation {ts.generation})"
                )
            payload, manifest = ts.staged
            if manifest.version != int(version):
                raise SwapProtocolError(
                    f"commit names version {version} but tenant "
                    f"{ts.name!r} staged {manifest.version}"
                )
            if generation <= ts.generation:
                raise SwapProtocolError(
                    f"commit generation {generation} does not advance "
                    f"tenant {ts.name!r}'s {ts.generation}"
                )
            prev = (ts.holder.get(), ts.holder.version,
                    ts.generation, ts.holder.manifest)
            # adopt the generation BEFORE the payload swap: the swap
            # installs the new weights immediately and then blocks on the
            # drain (up to drain_timeout_secs) — a request pinned to the
            # OLD generation arriving in that window must already be
            # refused, not scored on the new weights under an old label
            ts.generation = generation
            drained = ts.holder.swap(
                payload, version=manifest.version, manifest=manifest,
                drain_timeout_secs=drain_timeout_secs,
            )
            ts.prev = prev
            ts.staged = None
            ts.swaps_total += 1
            self.swaps_total += 1
            self._tenant_events.labels(ts.name, "swap").inc()
            obs_flight.record(
                "swap_commit", subsystem="pool", group=self.group,
                member=self.member, tenant=ts.name,
                generation=ts.generation,
                version=ts.holder.version, drained=bool(drained),
            )
            return {"group_generation": ts.generation,
                    "tenant": ts.name,
                    "model_version": ts.holder.version,
                    "drained": bool(drained)}

    def rollback(self, tenant: str | None = None) -> dict:
        """Return ``tenant`` to its retained pre-commit payload and
        generation (the group coordinator's answer to a partial commit).
        Strictly tenant-scoped: rolling back tenant A leaves every other
        tenant's payload, generation and in-flight traffic untouched."""
        ts = self._tenant(tenant)
        with self._lock:
            if ts.prev is None:
                raise SwapProtocolError(
                    f"nothing to roll back for tenant {ts.name!r}"
                )
            payload, ver, gen, manifest = ts.prev
            # same ordering as commit: generation first, then the payload.
            # The manifest rides along: a rolled-back funnel member must
            # keep reporting the LIVE index's version/occupancy, not the
            # boot servable's
            ts.generation = gen
            ts.holder.swap(payload, version=ver, manifest=manifest)
            ts.prev = None
            ts.rollbacks_total += 1
            self.rollbacks_total += 1
            self._tenant_events.labels(ts.name, "rollback").inc()
            obs_flight.record(
                "swap_rollback", subsystem="pool", group=self.group,
                member=self.member, tenant=ts.name, generation=gen,
                version=ver,
            )
            return {"group_generation": ts.generation,
                    "tenant": ts.name,
                    "model_version": ts.holder.version}

    def abort(self, tenant: str | None = None) -> dict:
        ts = self._tenant(tenant)
        with self._lock:
            had = ts.staged is not None
            ts.staged = None
            gen = ts.generation
        if had:
            obs_flight.record("swap_abort", subsystem="pool",
                              group=self.group, member=self.member,
                              tenant=ts.name, generation=gen)
        return {"aborted": had, "tenant": ts.name, "group_generation": gen}

    def close(self) -> None:
        closed = set()
        for ts in self._tenants.values():
            if id(ts.engine) not in closed:
                closed.add(id(ts.engine))
                ts.engine.close()


def make_member_handler(member: GroupMember, model_name: str):
    """The member HTTP surface: serve/server.py's handler (predict,
    health, metrics — with the group_status extension) plus the swap
    admin routes, per-request tenant selection (``X-Tenant``), and the
    (tenant, generation)-keyed skew gate."""
    base = make_handler(
        member.dispatch, model_name,
        reload_status=member.reload_status,
        readiness=member.readiness,
        group_status=member.group_status,
        registry=member.registry,
        tracer=member.tracer,
    )
    predict_paths = {
        f"/v1/models/{model_name}:predict",
        f"/v1/models/{model_name}:predict_binary",
    }
    if getattr(member, "funnel", False):
        # the funnel scoring route rides the same generation-skew gate:
        # a pinned recommend must never score across a group commit
        from ...funnel.serve import RECOMMEND_PATH

        predict_paths = predict_paths | {RECOMMEND_PATH}
    admin: dict[str, Callable[[dict], dict]] = {
        "/admin:stage": lambda b: member.stage(
            b["version"], b.get("source"), tenant=b.get("tenant")
        ),
        "/admin:commit": lambda b: member.commit(
            b["generation"], b["version"], tenant=b.get("tenant")
        ),
        "/admin:rollback": lambda b: member.rollback(
            tenant=b.get("tenant")
        ),
        "/admin:abort": lambda b: member.abort(tenant=b.get("tenant")),
    }

    class MemberHandler(base):
        def do_POST(self):  # noqa: N802
            if self.path in admin:
                return self._do_admin(admin[self.path])
            if self.path in predict_paths:
                # tenant selection: the header picks which payload scores
                # this request; the member thread stays pinned to it for
                # the request's duration (group_status/reload_status read
                # it when assembling the response attribution)
                tenant = self.headers.get("X-Tenant")
                if tenant is not None and tenant not in member._tenants:
                    self._drain_body()
                    return self._send(400, {
                        "error": f"unknown tenant {tenant!r}",
                        "tenants": member.tenant_names(),
                    })
                member.select_tenant(tenant)
                try:
                    return self._do_predict_selected(tenant)
                finally:
                    member.select_tenant(None)
                    self._attrib_tenant = None
            return super().do_POST()

        def _send(self, code, doc, extra_headers=None):
            # post-score attribution guard (JSON predict/recommend): the
            # response labels (tenant, generation, model_version) are
            # read at assembly time, AFTER scoring — if this tenant's
            # generation moved between the pin gate and here (a commit
            # or rollback landed mid-request), the label is ambiguous:
            # the scores may be the pre-swap payload's under the
            # post-swap label.  Refuse with a 409 (the router re-pins
            # and retries; the retry scores AND labels on one
            # generation) instead of sending a mislabeled response.
            # The binary path keeps the documented at-most-one-behind
            # header attribution (serve/server.py make_handler).
            t = getattr(self, "_attrib_tenant", None)
            if t is not None and code == 200:
                live = member._tenant(t).generation
                if live != self._attrib_generation:
                    # lock-free like the gate's 409 (see above): this
                    # fires exactly while commit() holds the member lock
                    member.skew_aborts_total += 1
                    member._tenant(t).skew_aborts_total += 1
                    obs_flight.record(
                        "skew_abort", subsystem="pool", phase="response",
                        group=member.group, member=member.member,
                        tenant=t,
                        pinned_generation=self._attrib_generation,
                        group_generation=live,
                    )
                    return super()._send(409, {
                        "error": "generation moved mid-request",
                        "shard_group": member.group,
                        "tenant": t,
                        "group_generation": live,
                    })
            return super()._send(code, doc, extra_headers=extra_headers)

        def _do_predict_selected(self, tenant):
            resolved = tenant or member.selected_tenant()
            pinned = self.headers.get("X-Pinned-Generation")
            if pinned is not None:
                try:
                    want = int(pinned)
                except ValueError:
                    self._drain_body()
                    return self._send(
                        400, {"error": f"bad X-Pinned-Generation "
                                       f"{pinned!r}"}
                    )
                live = member._tenant(resolved).generation
                if want != live:
                    # the skew abort: refuse, never score — the router
                    # re-pins and retries.  Keyed by (tenant,
                    # generation): tenant A mid-commit cannot make
                    # tenant B's correctly-pinned requests abort.
                    # Counters bump WITHOUT the member lock: commit()
                    # holds it across the swap drain (up to 30 s), and a
                    # refusal must stay fast exactly then (a lost
                    # increment under a counter race is acceptable; a
                    # 30 s 409 is not)
                    member.skew_aborts_total += 1
                    member._tenant(resolved).skew_aborts_total += 1
                    obs_flight.record(
                        "skew_abort", subsystem="pool",
                        group=member.group, member=member.member,
                        tenant=resolved, pinned_generation=want,
                        group_generation=live,
                    )
                    self._drain_body()
                    return self._send(409, {
                        "error": "generation skew",
                        "pinned_generation": want,
                        "shard_group": member.group,
                        "tenant": resolved,
                        "group_generation": live,
                    })
            # arm the post-score attribution guard (_send above): snapshot
            # the generation the gate admitted under; a mid-request swap
            # makes the response's label ambiguous and must 409, not send
            self._attrib_generation = member._tenant(resolved).generation
            self._attrib_tenant = resolved
            if (getattr(member, "funnel", False)
                    and self.path == "/v1/recommend"):
                # recommend rides the same trace tail as predict:
                # adopt the router-propagated X-Trace-Id (or the
                # client's) so the funnel spans join the one trace
                ctx = member.tracer.begin("recommend", self.headers)
                token = member.tracer.activate(ctx)
                self._obs_status = None
                try:
                    return self._do_recommend()
                finally:
                    member.tracer.finish(ctx, token,
                                         status=self._obs_status)
            return super().do_POST()

        def _do_recommend(self):
            from ...funnel.serve import handle_recommend

            try:
                length = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(length))
            except Exception as e:
                return self._send(400,
                                  {"error": f"{type(e).__name__}: {e}"})
            code, doc = handle_recommend(member._scorer, req)
            if code == 200:
                # group attribution alongside the atomic version pair
                doc["shard_group"] = member.group
                doc["tenant"] = member.selected_tenant()
                doc["group_generation"] = member.generation
            self._send(code, doc)

        def _drain_body(self):
            # an early reject must still consume the request body, or the
            # unread bytes desynchronize the HTTP/1.1 keep-alive framing
            # (the next request line would be parsed out of this body)
            length = int(self.headers.get("Content-Length", "0") or 0)
            while length > 0:
                chunk = self.rfile.read(min(length, 1 << 16))
                if not chunk:
                    break
                length -= len(chunk)

        def _do_admin(self, fn):
            try:
                length = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(length) or b"{}")
            except Exception as e:
                return self._send(400,
                                  {"error": f"{type(e).__name__}: {e}"})
            try:
                doc = fn(body)
            except SwapProtocolError as e:
                return self._send(409, {"error": str(e)})
            except (ValueError, KeyError, TypeError) as e:
                return self._send(400,
                                  {"error": f"{type(e).__name__}: {e}"})
            except Exception as e:
                return self._send(500,
                                  {"error": f"{type(e).__name__}: {e}"})
            self._send(200, doc)

    return MemberHandler


def start_member(
    servable_dir: str,
    mesh,
    *,
    group: str = "g0",
    member: str = "m0",
    model_name: str = "deepfm",
    host: str = "127.0.0.1",
    port: int = 0,
    **member_kw,
) -> tuple[ScoringHTTPServer, str, GroupMember]:
    """In-process member on a daemon thread (the test/bench topology; the
    process-pool CLI wraps ``serve_member`` instead).  Returns
    ``(server, base_url, member)``; callers own shutdown
    (``server.shutdown(); member.close()``)."""
    gm = GroupMember(servable_dir, mesh, group=group, member=member,
                     **member_kw)
    httpd = ScoringHTTPServer(
        (host, port), make_member_handler(gm, model_name)
    )
    threading.Thread(
        target=httpd.serve_forever, daemon=True,
        name=f"pool-member-{group}-{member}",
    ).start()
    url = f"http://{host}:{httpd.server_address[1]}"
    return httpd, url, gm


def serve_member(
    servable_dir: str,
    *,
    group: str,
    member: str = "m0",
    data_parallel: int = 1,
    model_parallel: int = 0,
    group_index: int = 0,
    model_name: str = "deepfm",
    host: str = "127.0.0.1",
    port: int = 0,
    ready: threading.Event | None = None,
    **member_kw,
) -> None:
    """Blocking process entry (serve/pool/__main__.py forks one per
    member): build the group mesh over this member's device slice, load
    the sharded servable, announce, serve until killed."""
    import sys

    import jax

    from .sharded import build_serve_mesh

    if model_parallel <= 0:
        model_parallel = max(1, len(jax.devices()) // max(1, data_parallel))
    mesh = build_serve_mesh(data_parallel, model_parallel,
                            group_index=group_index)
    gm = GroupMember(servable_dir, mesh, group=group, member=member,
                     **member_kw)
    httpd = ScoringHTTPServer((host, port),
                              make_member_handler(gm, model_name))
    if ready is not None:
        ready.port = httpd.server_address[1]  # type: ignore[attr-defined]
        ready.set()
    print(
        f"pool member {group}/{member}: serving {model_name} on "
        f"http://{host}:{httpd.server_address[1]} "
        f"(mesh [{data_parallel},{model_parallel}], "
        f"exchange {gm.ctx.exchange})",
        file=sys.stderr,
    )
    httpd.serve_forever()
