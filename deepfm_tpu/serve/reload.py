"""Zero-downtime hot weight reload under the micro-batching engine.

``serve/export.py``'s ``load_servable`` closes over the parameters, so they
compile into the predict executable as constants — fast, but a new version
means a recompile.  This module splits that: the jitted function takes the
parameter payload as an ARGUMENT, so the per-bucket executables the
:class:`~deepfm_tpu.serve.batcher.MicroBatcher` precompiled are
parameterized by weights.  Swapping to version N+1 with identical
shapes/dtypes/shardings is a jit *cache hit* — the GSPMD lesson (pick the
executables once, keep them; arxiv 2105.04663) carried across the
train→serve boundary.

The swap protocol (:class:`HotSwapper.poll_once`):

1. **poll** the publish root (``online/publisher.py``) for a manifest newer
   than the live version — torn versions are unobservable (marker-last);
2. **stage**: restore the new payload host-side, verify the manifest's
   ``param_hash`` (a corrupted download can never go live) and that every
   leaf's shape/dtype matches the live payload (different shapes would need
   new executables — refused, not recompiled mid-traffic);
3. **canary**: score a probe batch through the *new* payload on the live
   executables and require finite in-range probabilities — a NaN/Inf model
   is rolled back before any request sees it;
4. **swap**: atomically repoint the payload reference
   (:meth:`SwappableParams.swap`) and **drain** — wait until every dispatch
   that acquired the old payload has completed, so when the swap returns,
   all traffic is on the new weights.  In-flight requests finish on the old
   version; no request ever fails because of a swap.

``status()`` feeds ``/v1/metrics``: live version, weight staleness
(now − manifest publish time), swap/rollback counters, last swap latency.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Callable

import jax
import numpy as np

from ..core.config import Config
from ..models.base import get_model
from ..obs import flight as obs_flight
from ..obs.metrics import MetricsRegistry
from ..online.publisher import (
    fetch_version,
    latest_manifest,
    param_tree_hash,
)
from ..utils.retry import CircuitBreaker
from .export import _load_config, _restore_payload


class SwappableParams:
    """The live parameter payload behind an atomic, drain-aware swap.

    Scoring threads ``acquire()`` the payload (tagging themselves with the
    current generation) and ``release()`` when their dispatch completes;
    ``swap()`` installs a new payload and blocks until every holder of an
    older generation has released — the moment it returns, no executable is
    running on the old weights."""

    def __init__(self, payload, *, version: int = 0, manifest=None):
        self._cond = threading.Condition()
        self._payload = payload
        self._gen = 0
        self._inflight: dict[int, int] = {}
        self.version = int(version)
        self.manifest = manifest

    def acquire(self):
        with self._cond:
            self._inflight[self._gen] = self._inflight.get(self._gen, 0) + 1
            return self._payload, self._gen

    def release(self, gen: int) -> None:
        with self._cond:
            left = self._inflight.get(gen, 0) - 1
            if left <= 0:
                self._inflight.pop(gen, None)
            else:
                self._inflight[gen] = left
            self._cond.notify_all()

    def get(self):
        with self._cond:
            return self._payload

    def swap(self, payload, *, version: int, manifest=None,
             drain_timeout_secs: float = 30.0) -> bool:
        """Install ``payload`` and drain old-generation dispatches.

        Returns True when the drain completed; False on timeout (the swap
        itself still happened — new dispatches already run the new
        weights; a wedged old dispatch can only return stale scores, never
        torn ones, since it holds its own payload reference)."""
        with self._cond:
            old_gen = self._gen
            self._payload = payload
            self._gen += 1
            self.version = int(version)
            self.manifest = manifest
            deadline = time.monotonic() + drain_timeout_secs
            while any(g <= old_gen for g in self._inflight):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True


def build_predict_with(model, cfg: Config) -> Callable:
    """The weight-parameterized jitted predict:
    ``predict_with(payload, feat_ids, feat_vals) -> prob``.

    Params ride as an ARGUMENT (not a closure constant), so the per-bucket
    executables are shared across weight versions and a hot swap is a jit
    cache hit.  Single source of truth: the servable loader below and the
    trace-time audit (analysis/trace_audit.py, which lowers this function
    with abstract payloads to prove the cache-hit/no-transfer contracts)
    both build the jitted function HERE."""

    @jax.jit
    def predict_with(payload, feat_ids, feat_vals):
        logits, _ = model.apply(
            payload["params"], payload["model_state"],
            feat_ids, feat_vals, cfg=cfg.model, train=False,
        )
        return jax.nn.sigmoid(logits)

    return predict_with


def load_swappable_servable(
    directory: str | os.PathLike,
) -> tuple[Callable, Callable, SwappableParams, Config]:
    """Load a CTR servable for hot reload.

    Returns ``(predict, predict_with, holder, cfg)``:
      * ``predict(ids, vals)`` — the engine-facing closure (same surface
        ``MicroBatcher`` wraps) reading the live payload from ``holder``;
      * ``predict_with(payload, ids, vals)`` — the underlying jitted
        function with explicit weights (the canary path scores candidate
        payloads through it without touching live traffic);
      * ``holder`` — the :class:`SwappableParams` the :class:`HotSwapper`
        swaps;
      * ``cfg`` — the servable Config.
    """
    directory = os.path.abspath(directory)
    cfg = _load_config(directory)
    if cfg.model.model_name == "two_tower":
        raise ValueError(
            "hot reload supports CTR servables; two-tower retrieval "
            "serving does not take --reload-url yet"
        )
    model = get_model(cfg.model)
    params, model_state = _restore_payload(
        directory, lambda: model.init(jax.random.PRNGKey(0), cfg.model)
    )
    # device-committed once: jit arguments transfer per call unless already
    # placed, and the whole point is that a swap costs a pointer, not a
    # recompile or a per-request host->device copy.  The EXPLICIT device
    # matters: uncommitted arrays key the jit cache differently than the
    # committed ones Orbax restores, and a committedness mismatch between
    # the boot payload and a staged version would turn the swap into a
    # recompile
    payload = jax.device_put(
        {"params": params, "model_state": model_state}, jax.devices()[0]
    )
    holder = SwappableParams(payload, version=0)
    predict_with = build_predict_with(model, cfg)

    def predict(feat_ids, feat_vals):
        payload, gen = holder.acquire()
        try:
            out = predict_with(payload, feat_ids, feat_vals)
            # block before release: async dispatch would otherwise let the
            # generation drain while the executable is still running, making
            # the swap's "all traffic on new weights" claim a lie
            jax.block_until_ready(out)
            return out
        finally:
            holder.release(gen)

    return predict, predict_with, holder, cfg


class HotSwapper:
    """Poll a publish root and swap new versions under live executables.

    The store-facing half of every poll (manifest discovery, artifact
    fetch) runs behind a circuit breaker: a store outage opens the circuit
    after ``breaker`` sees enough failures, polls are then *skipped* (one
    probe per cooldown instead of a full retry storm per tick) while the
    old weights keep serving, and the first successful probe closes it
    again.  Breaker state is surfaced in ``status()`` → ``/v1/metrics``'s
    ``reload.breaker`` and flips ``/readyz`` while open."""

    def __init__(
        self,
        holder: SwappableParams,
        predict_with: Callable,
        reload_source: str,
        cfg: Config,
        *,
        interval_secs: float = 2.0,
        canary_rows: int = 8,
        staging_dir: str | None = None,
        drain_timeout_secs: float = 30.0,
        breaker: CircuitBreaker | None = None,
        registry: MetricsRegistry | None = None,
    ):
        self._holder = holder
        self._predict_with = predict_with
        self._source = reload_source
        self._cfg = cfg
        self._interval = float(interval_secs)
        self._drain_timeout = float(drain_timeout_secs)
        self._staging = staging_dir or os.path.join(
            tempfile.gettempdir(), f"deepfm_reload_{os.getpid()}"
        )
        os.makedirs(self._staging, exist_ok=True)
        # canary probe: zero rows plus spread in-vocab ids — any row going
        # non-finite fails the version
        n = max(1, int(canary_rows))
        f = cfg.model.field_size
        ids = np.zeros((n, f), np.int64)
        if n > 1:
            ids[1:] = np.linspace(
                0, max(0, cfg.model.feature_size - 1), (n - 1) * f,
                dtype=np.int64,
            ).reshape(n - 1, f)
        self._canary = (ids, np.ones((n, f), np.float32))
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # cooldown spans several poll ticks so an open circuit actually
        # rests the store instead of probing every interval
        self._breaker = breaker if breaker is not None else CircuitBreaker(
            failure_threshold=0.5, window=6, min_calls=3,
            cooldown_secs=max(5.0, 4.0 * self._interval), name="reload",
        )
        # counters live in the obs registry (labels make the reload
        # section scrape-able); status() re-renders the pinned JSON
        # schema from the same values
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        events = self.registry.counter(
            "deepfm_reload_events_total",
            "hot-reload lifecycle events by kind", labels=("event",))
        self._c_swaps = events.labels("swap")
        self._c_rollbacks = events.labels("rollback")
        self._c_poll_errors = events.labels("poll_error")
        self._c_polls_skipped = events.labels("poll_skipped")
        self._g_version = self.registry.gauge(
            "deepfm_reload_model_version", "live served model version")
        self._g_staleness = self.registry.gauge(
            "deepfm_reload_weight_staleness_seconds",
            "now minus the live manifest's publish time")
        self.registry.on_collect(self._refresh_gauges)
        self.last_swap_ms: float | None = None
        self.last_check_unix: float | None = None
        self.last_error: str | None = None

    # registry-backed totals (read-compatible with the pre-registry attrs)
    @property
    def swaps_total(self) -> int:
        return int(self._c_swaps.value)

    @property
    def rollbacks_total(self) -> int:
        return int(self._c_rollbacks.value)

    @property
    def poll_errors_total(self) -> int:
        return int(self._c_poll_errors.value)

    @property
    def polls_skipped_total(self) -> int:
        return int(self._c_polls_skipped.value)

    def _refresh_gauges(self) -> None:
        self._g_version.set(self._holder.version)
        manifest = self._holder.manifest
        if manifest is not None:
            self._g_staleness.set(
                max(0.0, time.time() - manifest.created_unix)
            )

    # -- one poll/swap cycle ------------------------------------------------
    def poll_once(self) -> bool:
        """Check for a newer committed version; stage+canary+swap it.
        Returns True when a swap happened.  Never raises: a bad VERSION is
        rolled back (``rollbacks_total``); a failure merely *discovering or
        fetching* versions (a flaky list/read, no candidate staged) is a
        poll error (``poll_errors_total``) feeding the circuit breaker —
        conflating the two would make transient store hiccups read as
        failing canaries.  While the breaker is open the poll is skipped
        outright (``polls_skipped_total``): an outage costs one probe per
        cooldown, not a retry storm per tick, and old weights keep
        serving."""
        with self._lock:
            self.last_check_unix = time.time()
        if not self._breaker.allow():
            self._c_polls_skipped.inc()
            return False
        try:
            manifest = latest_manifest(self._source)
        except Exception as e:
            self._breaker.record_failure()
            self._c_poll_errors.inc()
            with self._lock:
                self.last_error = f"poll: {type(e).__name__}: {e}"
            return False
        if manifest is None or manifest.version <= self._holder.version:
            self._breaker.record_success()
            return False
        try:
            local = fetch_version(
                self._source, manifest.version, self._staging
            )
        except Exception as e:
            # store-facing fetch: an outage here is a poll error + breaker
            # food, NOT a rollback — nothing was ever a swap candidate
            self._breaker.record_failure()
            self._c_poll_errors.inc()
            with self._lock:
                self.last_error = f"stage: {type(e).__name__}: {e}"
            return False
        self._breaker.record_success()
        try:
            payload = self._stage(manifest, local)
            self._canary_check(payload)
            t0 = time.perf_counter()
            drained = self._holder.swap(
                payload, version=manifest.version, manifest=manifest,
                drain_timeout_secs=self._drain_timeout,
            )
            self._c_swaps.inc()
            with self._lock:
                self.last_swap_ms = round(
                    1e3 * (time.perf_counter() - t0), 3
                )
                self.last_error = (
                    None if drained else "drain timeout (swap still applied)"
                )
            obs_flight.record(
                "swap_commit", subsystem="reload",
                version=manifest.version, drained=bool(drained),
            )
            return True
        except Exception as e:
            self._c_rollbacks.inc()
            with self._lock:
                self.last_error = f"{type(e).__name__}: {e}"
            obs_flight.record(
                "swap_rollback", subsystem="reload",
                version=manifest.version,
                error=f"{type(e).__name__}: {e}",
            )
            return False

    def _purge_staged(self, local: str) -> None:
        """Drop a corruption-shaped artifact from the version-keyed staging
        cache: fetch_version skips present dirs, so a torn copy left in
        place would make every future poll re-fail on it forever."""
        if os.path.abspath(local).startswith(
                os.path.abspath(self._staging) + os.sep):
            import shutil

            shutil.rmtree(local, ignore_errors=True)

    def _stage(self, manifest, local: str):
        """Restore the (already fetched) version host-side, verify
        integrity + compatibility, and commit it to device — all before any
        traffic can touch it."""
        try:
            # failures in this block are corruption-shaped (a torn fetch
            # that raced a publisher rebuild: missing config, unreadable
            # payload, wrong bytes) — purge the cached copy so the next
            # poll re-fetches.  Semantic refusals below (field size, tree
            # shape, canary) keep the cache: re-downloading an artifact
            # that is whole but incompatible would be pure churn.
            served_cfg = _load_config(local)
            model = get_model(served_cfg.model)
            params, model_state = _restore_payload(
                local,
                lambda: model.init(jax.random.PRNGKey(0), served_cfg.model),
            )
            got = param_tree_hash(params, model_state)
            if manifest.param_hash and got != manifest.param_hash:
                raise ValueError(
                    f"version {manifest.version} param hash mismatch "
                    f"(manifest {manifest.param_hash[:12]}…, staged "
                    f"{got[:12]}…) — torn or corrupted artifact"
                )
        except Exception:
            self._purge_staged(local)
            raise
        if served_cfg.model.field_size != self._cfg.model.field_size:
            raise ValueError(
                f"version {manifest.version} has field_size "
                f"{served_cfg.model.field_size}, engine serves "
                f"{self._cfg.model.field_size} — not hot-swappable"
            )
        new = {"params": params, "model_state": model_state}
        live = self._holder.get()
        live_leaves = jax.tree_util.tree_flatten_with_path(live)[0]
        new_leaves = jax.tree_util.tree_flatten_with_path(new)[0]
        live_specs = {
            jax.tree_util.keystr(p): (tuple(x.shape), str(x.dtype))
            for p, x in live_leaves
        }
        new_specs = {
            jax.tree_util.keystr(p): (tuple(x.shape), str(x.dtype))
            for p, x in new_leaves
        }
        if live_specs != new_specs:
            diff = sorted(
                set(live_specs.items()) ^ set(new_specs.items())
            )[:4]
            raise ValueError(
                f"version {manifest.version} parameter tree differs from "
                f"the live executables' (first diffs: {diff}) — swapping "
                f"would need a recompile; redeploy instead"
            )
        # same explicit placement as the boot payload: committedness is part
        # of the jit cache key (see load_swappable_servable)
        return jax.device_put(new, jax.devices()[0])

    def _canary_check(self, payload) -> None:
        probs = np.asarray(self._predict_with(payload, *self._canary))
        if not np.isfinite(probs).all():
            raise ValueError(
                f"canary probe produced non-finite scores "
                f"({int((~np.isfinite(probs)).sum())}/{probs.size} bad)"
            )
        if ((probs < 0.0) | (probs > 1.0)).any():
            raise ValueError("canary probe produced out-of-range scores")

    # -- background polling -------------------------------------------------
    def start(self) -> "HotSwapper":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="hot-swapper"
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            self.poll_once()
            self._stop.wait(self._interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # -- observability ------------------------------------------------------
    def status(self) -> dict:
        manifest = self._holder.manifest
        with self._lock:
            out = {
                "model_version": self._holder.version,
                "reload_source": self._source,
                "reload_interval_secs": self._interval,
                "swaps_total": self.swaps_total,
                "rollbacks_total": self.rollbacks_total,
                "poll_errors_total": self.poll_errors_total,
                "polls_skipped_total": self.polls_skipped_total,
                "breaker": self._breaker.status(),
                "last_swap_ms": self.last_swap_ms,
                "last_check_unix": self.last_check_unix,
                "last_error": self.last_error,
            }
        if manifest is not None:
            out["model_step"] = manifest.step
            out["published_unix"] = manifest.created_unix
            out["weight_staleness_secs"] = round(
                max(0.0, time.time() - manifest.created_unix), 3
            )
        return out
