from .export import (  # noqa: F401
    export_servable,
    load_retrieval_servable,
    load_servable,
    write_predictions,
)
from .server import Scorer, score_stdin, serve_forever  # noqa: F401
