from .export import export_servable, load_servable, write_predictions  # noqa: F401
