from .batcher import MicroBatcher, OverloadedError  # noqa: F401
from .export import (  # noqa: F401
    export_servable,
    load_batching_servable,
    load_retrieval_servable,
    load_servable,
    write_predictions,
)
from .reload import (  # noqa: F401
    HotSwapper,
    SwappableParams,
    load_swappable_servable,
)
from .server import Scorer, score_stdin, serve_forever  # noqa: F401
