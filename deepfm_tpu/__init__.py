"""deepfm_tpu — a TPU-native distributed CTR-training framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
``aws-samples/deepfm-tensorflow-distributed-training-on-amazon-sagemaker``:
DeepFM-family models, sharded embedding tables over a device mesh (the
parameter-server capability), SPMD data parallelism (the Horovod capability),
a streaming TFRecord data plane (File/Pipe-mode capability), checkpoint/
export/infer tasks, and a multi-host launcher.
"""

__version__ = "0.1.0"

from .core.config import Config, DataConfig, MeshConfig, ModelConfig, OptimizerConfig, RunConfig  # noqa: F401
