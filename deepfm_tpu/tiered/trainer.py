"""Tiered training driver: pipeline → pager → paged step, plus the
streaming checkpoint and the publisher flush barrier.

``TieredTrainer`` owns the four moving parts (cold tier, host tier,
pager, jitted paged step) and exposes the same rhythm as the resident
loops: ``train_batch`` per host batch, ``save``/``restore`` for
crash-resume, ``flush`` as the consistency barrier the online publisher
calls before writing a manifest.

Checkpointing STREAMS the tiers instead of gathering: dirty hot records
write back to the host tier (fixed-shape jitted gathers), dirty host rows
flush to cold-tier page overlays, and a small metadata record (cold
snapshot + rest-params leaves + step/rng) commits atomically — bytes
moved scale with DIRTY rows, not table size, and peak RSS stays bounded
by one page, attacking the measured 322 s / 2.4×-RSS resident save path
(docs/BENCH_LARGE_VOCAB.json).  Restore is cache-COLD by design: the hot
and host tiers refill on demand, and training converges to bit-identical
losses (tests/test_tiered.py).
"""

from __future__ import annotations

import json
import os
import queue
import threading

import numpy as np

from ..core.config import Config
from ..train.step import LAZY_TABLE_KEYS, TrainState, create_train_state
from .host import HostTier
from .pager import DevicePager
from .step import (
    PagedHot,
    PagedState,
    init_hot,
    make_paged_train_step,
    make_readback,
)
from .store import ColdTier, RecordLayout

_META = "tiered_meta.json"
_LEAVES = "tiered_leaves.npz"


def _next_pow2(n: int) -> int:
    return 1 << max(0, int(n - 1).bit_length())


def resolve_tiered(cfg: Config) -> dict:
    """Config → concrete tier sizes (0 = auto, core/config.py flags).

    Hot capacity must hold at least one batch's unique rows (B·F worst
    case) with slack for reuse; auto doubles that and rounds to a power
    of two.  The staging pack is one batch's worst-case miss count."""
    bf = cfg.data.batch_size * cfg.model.field_size
    capacity = cfg.model.tiered_hot_slots or _next_pow2(2 * bf)
    stage_rows = cfg.model.tiered_stage_rows or bf
    host_rows = cfg.model.tiered_host_rows or max(
        8 * capacity, cfg.model.tiered_page_rows
    )
    return {
        "capacity": int(capacity),
        "stage_rows": int(stage_rows),
        "host_rows": int(host_rows),
        "page_rows": int(cfg.model.tiered_page_rows),
    }


def _check_cfg(cfg: Config) -> None:
    if cfg.model.fused_kernel != "off":
        raise ValueError(
            "tiered embeddings require fused_kernel='off' (the fused "
            "kernel gathers a resident table)"
        )
    if cfg.optimizer.name.lower() != "adam":
        raise ValueError(
            "tiered embeddings co-evict lazy-Adam moments; optimizer "
            f"must be Adam, got {cfg.optimizer.name!r}"
        )


def _rest_template(cfg: Config) -> TrainState:
    """A resident TrainState at a TINY vocabulary: every non-table leaf
    (MLP, fm_b, bn, optimizer state for those, rng) has its real shape —
    tables never depend on it — so it serves as the restore template
    without materializing the real table."""
    small = cfg.with_overrides(
        model={"feature_size": 2},
        optimizer={"lazy_embedding_updates": True},
    )
    return create_train_state(small)


def _split_rest(cfg: Config, state: TrainState):
    """(rest params, tables, rest_opt, lazy moments) from a resident
    lazy TrainState."""
    keys = [k for k in LAZY_TABLE_KEYS if k in state.params]
    if not keys:
        raise ValueError(
            f"tiered embeddings need {LAZY_TABLE_KEYS} tables; "
            f"{cfg.model.model_name!r} has {sorted(state.params)}"
        )
    rest = {k: v for k, v in state.params.items() if k not in keys}
    tables = {k: state.params[k] for k in keys}
    if not (isinstance(state.opt_state, tuple) and len(state.opt_state) == 2
            and hasattr(state.opt_state[1], "m")):
        raise ValueError(
            "tiered embeddings continue the LAZY optimizer layout; build "
            "the source state with lazy_embedding_updates=True"
        )
    rest_opt, lazy = state.opt_state
    return rest, tables, rest_opt, lazy, keys


def _widths(cfg: Config, keys) -> dict[str, int]:
    return {
        k: (1 if k == "fm_w" else cfg.model.embedding_size) for k in keys
    }


class TieredTrainer:
    def __init__(
        self,
        cfg: Config,
        cold: ColdTier,
        *,
        rest,
        model_state,
        rest_opt,
        rng,
        step0: int = 0,
        capacity: int,
        stage_rows: int,
        host_rows: int,
    ):
        import jax.numpy as jnp

        _check_cfg(cfg)
        self.cfg = cfg
        self.cold = cold
        sizes = resolve_tiered(cfg)
        self.capacity = capacity or sizes["capacity"]
        bf = cfg.data.batch_size * cfg.model.field_size
        if self.capacity < bf:
            raise ValueError(
                f"tiered_hot_slots={self.capacity} cannot hold one batch's "
                f"id stream (batch_size*field_size={bf})"
            )
        self.host = HostTier(cold, host_rows or sizes["host_rows"])
        self._readback = make_readback()
        self.pager = DevicePager(
            capacity=self.capacity,
            layout=cold.layout,
            host=self.host,
            stage_rows=stage_rows or sizes["stage_rows"],
            readback_fn=self._readback,
            vocab=cfg.model.feature_size,
        )
        if self.host.max_request_rows() < self.pager.stage_rows:
            raise ValueError(
                f"host tier of {self.host.capacity} rows (serviceable "
                f"window {self.host.max_request_rows()}) cannot satisfy a "
                f"full {self.pager.stage_rows}-row miss pack; raise "
                f"tiered_host_rows"
            )
        self._step = make_paged_train_step(cfg, self.capacity)
        self.state = PagedState(
            step=jnp.asarray(step0, jnp.int32),
            rest=rest,
            model_state=model_state,
            rest_opt=rest_opt,
            hot=init_hot(cold.layout.widths, self.capacity),
            rng=rng,
        )
        self.history: list[dict] = []   # per-step paging/hit-rate curve
        self._last_stats = self.pager.stats()
        # advisory ahead-of-time cold→host prefetch fed by the input
        # pipeline's id stream (data/pipeline.py DevicePrefetcher observer)
        self._prefetch_q: queue.Queue = queue.Queue(maxsize=64)
        self._prefetch_dropped = 0
        self._prefetch_stop = threading.Event()
        self._prefetch_thread = threading.Thread(
            target=self._prefetch_worker, daemon=True
        )
        self._prefetch_thread.start()

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_resident_state(
        cls, cfg: Config, state: TrainState, cold_root: str, *,
        retry=None, **sizes,
    ) -> "TieredTrainer":
        """Seed the cold tier from a fully-resident lazy TrainState (bulk
        import as base segments — the ranged-read format) and continue it
        paged.  The parity suite's entry point."""
        rest, tables, rest_opt, lazy, keys = _split_rest(cfg, state)
        layout = RecordLayout(_widths(cfg, keys))
        rt = resolve_tiered(cfg)
        cold = ColdTier(
            cold_root, rows=cfg.model.feature_size, layout=layout,
            page_rows=rt["page_rows"], retry=retry,
        )
        cold.import_dense(
            {k: np.asarray(tables[k]) for k in keys},
            {k: np.asarray(lazy.m[k]) for k in keys},
            {k: np.asarray(lazy.v[k]) for k in keys},
        )
        return cls(
            cfg, cold, rest=rest, model_state=state.model_state,
            rest_opt=rest_opt, rng=state.rng, step0=int(state.step),
            capacity=sizes.get("capacity", 0),
            stage_rows=sizes.get("stage_rows", 0),
            host_rows=sizes.get("host_rows", 0),
        )

    @classmethod
    def create_virtual(
        cls, cfg: Config, cold_root: str, *, init_fn=None, retry=None,
        **sizes,
    ) -> "TieredTrainer":
        """Fresh giant-vocab trainer: the table never materializes — cold
        pages come from ``init_fn(page) -> [rows, width]`` (default: page-
        seeded normal rows, zero moments) until first written back."""
        _check_cfg(cfg)
        template = _rest_template(cfg)
        rest, _, rest_opt, _, keys = _split_rest(cfg, template)
        layout = RecordLayout(_widths(cfg, keys))
        rt = resolve_tiered(cfg)

        if init_fn is None:
            init_fn = default_init_fn(cfg, layout, rt["page_rows"])
        cold = ColdTier(
            cold_root, rows=cfg.model.feature_size, layout=layout,
            page_rows=rt["page_rows"], init_fn=init_fn, retry=retry,
        )
        return cls(
            cfg, cold, rest=rest, model_state=template.model_state,
            rest_opt=rest_opt, rng=template.rng, step0=0,
            capacity=sizes.get("capacity", 0),
            stage_rows=sizes.get("stage_rows", 0),
            host_rows=sizes.get("host_rows", 0),
        )

    # -- training ----------------------------------------------------------
    def train_batch(self, batch: dict) -> dict:
        """One optimizer step on a host batch ({feat_ids, feat_vals,
        label}).  Translation + miss paging happen here, between
        dispatches; the step itself is the jit-stable slot-space
        executable."""
        slot_ids, staging = self.pager.translate(
            batch["feat_ids"], self.state.hot
        )
        jb = {
            "slot_ids": slot_ids,
            "feat_vals": np.asarray(batch["feat_vals"], np.float32),
            "label": np.asarray(batch["label"], np.float32),
        }
        self.state, metrics = self._step(
            self.state, jb, staging["slots"], staging["stage"]
        )
        now = self.pager.stats()
        cold = self.cold.stats()
        prev = self._last_stats
        self.history.append({
            "step": int(now["steps"]),
            "hit_rate_step": round(
                (now["hits"] - prev["hits"])
                / max(1, now["probe_unique"] - prev["probe_unique"]), 6),
            "staged_bytes": now["stage_bytes"] - prev["stage_bytes"],
            "writeback_bytes": (
                now["writeback_bytes"] - prev["writeback_bytes"]),
            "cold_read_bytes_total": cold["cold_read_bytes"],
            "cold_write_bytes_total": cold["cold_write_bytes"],
        })
        self._last_stats = now
        return metrics

    # -- id-stream prefetch (data/pipeline.py observer hook) ---------------
    def observer(self):
        """``DevicePrefetcher(observer=...)`` callable: sees each host
        batch ``depth`` batches before the step consumes it and pushes its
        ids to the cold→host prefetcher."""
        return lambda batch: self.prefetch_ids(batch.get("feat_ids"))

    def prefetch_ids(self, ids) -> None:
        if ids is None:
            return
        try:
            self._prefetch_q.put_nowait(np.asarray(ids).reshape(-1))
        except queue.Full:
            # advisory: a saturated prefetcher drops lookahead, the miss
            # path still faults the rows in synchronously
            self._prefetch_dropped += 1

    def _prefetch_worker(self) -> None:
        while not self._prefetch_stop.is_set():
            try:
                ids = self._prefetch_q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                ids = np.clip(ids, 0, self.cfg.model.feature_size - 1)
                self.host.prefetch(ids)
            except Exception:
                import logging

                logging.getLogger(__name__).warning(
                    "tiered prefetch failed (cold tier down?); misses "
                    "will fault in synchronously", exc_info=True,
                )

    # -- consistency barrier / checkpoint ----------------------------------
    def flush(self) -> dict:
        """Write every dirty row+moment hot→host→cold and return the
        cold tier's consistent-read snapshot — the barrier the online
        publisher runs BEFORE writing a manifest, so a serving reader
        pinned to the manifest's page_versions sees exactly this step's
        rows."""
        self.pager.writeback_all(self.state.hot)
        self.host.flush()
        snap = self.cold.snapshot()
        snap["step"] = int(self.state.step)
        return snap

    def save(self, directory: str) -> dict:
        """Streaming paged checkpoint: flush tiers + commit small
        metadata (cold snapshot, rest leaves, step/rng).  No full-table
        gather ever happens."""
        import jax

        os.makedirs(directory, exist_ok=True)
        snap = self.flush()
        leaves = jax.tree_util.tree_leaves(
            (self.state.rest, self.state.model_state, self.state.rest_opt,
             self.state.rng)
        )
        arrs = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
        tmp = os.path.join(directory, _LEAVES + ".tmp")
        with open(tmp, "wb") as f:
            np.savez(f, **arrs)
        os.replace(tmp, os.path.join(directory, _LEAVES))
        meta = {"step": int(self.state.step), "cold": snap}
        tmp = os.path.join(directory, _META + ".tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f, indent=1)
        os.replace(tmp, os.path.join(directory, _META))  # commit point
        return meta

    @classmethod
    def restore(
        cls, cfg: Config, directory: str, *, cold_root: str | None = None,
        init_fn=None, virtual: bool = False, retry=None, **sizes,
    ) -> "TieredTrainer":
        """Resume from a paged checkpoint, cache-COLD: tiers refill on
        demand; training continues bit-identically (tests/test_tiered.py).
        ``cold_root`` overrides the recorded root (e.g. the store moved);
        ``virtual=True`` reinstates the default page initializer for a
        trainer created via :meth:`create_virtual` (pages never written
        back still come from the initializer)."""
        import jax

        with open(os.path.join(directory, _META)) as f:
            meta = json.load(f)
        snap = meta["cold"]
        template = _rest_template(cfg)
        rest_t, _, rest_opt_t, _, keys = _split_rest(cfg, template)
        tpl = (rest_t, template.model_state, rest_opt_t, template.rng)
        flat, treedef = jax.tree_util.tree_flatten(tpl)
        with np.load(os.path.join(directory, _LEAVES)) as z:
            loaded = [z[f"leaf_{i}"] for i in range(len(z.files))]
        if len(loaded) != len(flat):
            raise ValueError(
                f"paged checkpoint has {len(loaded)} leaves, template "
                f"expects {len(flat)} — config drift since save?"
            )
        rest, model_state, rest_opt, rng = jax.tree_util.tree_unflatten(
            treedef, loaded
        )
        # a trainer created via ``create_virtual`` must restore with the
        # SAME initializer (``virtual=True`` or an explicit ``init_fn``);
        # seeded-from-resident stores restore with neither — a missing
        # page is then loudly a KeyError.
        layout = RecordLayout({k: int(w) for k, w in snap["widths"].items()})
        if init_fn is None and virtual:
            init_fn = default_init_fn(cfg, layout, int(snap["page_rows"]))
        cold = ColdTier(
            cold_root or snap["root"],
            rows=int(snap["rows"]), layout=layout,
            page_rows=int(snap["page_rows"]),
            pages_per_segment=int(snap["pages_per_segment"]),
            init_fn=init_fn, retry=retry,
            page_versions={int(p): int(v)
                           for p, v in snap["page_versions"].items()},
        )
        return cls(
            cfg, cold, rest=rest, model_state=model_state,
            rest_opt=rest_opt, rng=rng, step0=int(meta["step"]),
            capacity=sizes.get("capacity", 0),
            stage_rows=sizes.get("stage_rows", 0),
            host_rows=sizes.get("host_rows", 0),
        )

    # -- introspection -----------------------------------------------------
    def export_tables(self) -> tuple[dict, dict, dict]:
        """Flush, then materialize (rows, m, v) — SMALL vocabs only (the
        parity suite's ground-truth reconstruction)."""
        self.flush()
        return self.cold.export_dense()

    def paging_snapshot(self) -> dict:
        out = {"pager": self.pager.stats(), "host": self.host.stats(),
               "cold": self.cold.stats()}
        out["prefetch_dropped"] = self._prefetch_dropped
        return out

    def close(self) -> None:
        self._prefetch_stop.set()
        self._prefetch_thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def default_init_fn(cfg: Config, layout: RecordLayout, page_rows: int):
    """Page-seeded virtual initializer: N(0, glorot-ish) rows, zero
    moments.  Deterministic per page (crash-resume safe) WITHOUT ever
    materializing the table; not bit-equal to the resident glorot init —
    giant-vocab runs have no resident twin to match."""
    k = cfg.model.embedding_size
    rows = cfg.model.feature_size
    std_v = float(np.sqrt(2.0 / (rows + k)))
    std_w = float(np.sqrt(2.0 / (rows + 1)))
    seed = cfg.run.seed

    def init_fn(page: int) -> np.ndarray:
        eff = min(page_rows, rows - page * page_rows)
        rng = np.random.default_rng((seed, page))
        out = np.zeros((eff, layout.width), np.float32)
        for key in layout.keys:
            w = layout.widths[key]
            std = std_w if w == 1 else std_v
            out[:, layout.value_slice(key)] = rng.normal(
                0.0, std, (eff, w)
            ).astype(np.float32)
        return out

    return init_fn
