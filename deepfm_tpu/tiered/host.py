"""Host tier: pinned-host-memory backing store between HBM and the cold tier.

One preallocated ``[capacity, record_width]`` f32 buffer (the pinned-host
emulation on non-TPU backends; on a TPU-VM the allocation is the
host-pinned region the runtime DMAs from) holds row records faulted in
from the cold tier and rows written back from the device cache.  Rows are
the residency unit; cold fetches are PAGE-granular (one ranged read
services every missing row of that page) and dirty evictions/flushes are
page-granular read-modify-write against the cold tier's COW overlays.

Concurrency: the pager's synchronous miss path and the input pipeline's
ahead-of-time id-stream prefetcher share this tier.  The lock is dropped
around cold-tier I/O so a prefetch stalled on a dead store never blocks a
hit, and an in-flight page set + condition variable deduplicates
concurrent fetches of the same page (the second caller waits, then reads
the first caller's rows).
"""

from __future__ import annotations

import threading

import numpy as np

from .store import ColdTier


class HostTier:
    def __init__(self, cold: ColdTier, capacity_rows: int):
        if capacity_rows < cold.page_rows:
            raise ValueError(
                f"host tier capacity {capacity_rows} below one page "
                f"({cold.page_rows} rows) cannot make progress"
            )
        self.cold = cold
        self.capacity = int(capacity_rows)
        width = cold.layout.width
        self._buf = np.zeros((self.capacity, width), np.float32)
        self._idx_of: dict[int, int] = {}          # global row -> buf index
        self._row_at = np.full(self.capacity, -1, np.int64)
        self._dirty = np.zeros(self.capacity, bool)
        self._use = np.zeros(self.capacity, np.int64)
        self._clock = 0
        self._free = list(range(self.capacity - 1, -1, -1))
        self._cond = threading.Condition()
        self._inflight: set[int] = set()           # pages being cold-fetched
        self._stats = {
            "host_hits": 0, "host_misses": 0, "host_evictions": 0,
            "host_flushed_rows": 0, "prefetched_rows": 0,
        }

    # -- read path ---------------------------------------------------------
    def max_request_rows(self) -> int:
        """Largest single-call row set the tier can serve: one eviction
        chunk (``capacity // 16``) must remain displaceable or a fill
        could evict its own rows and loop forever."""
        return self.capacity - max(1, self.capacity // 16)

    def get_records(self, rows: np.ndarray) -> np.ndarray:
        """Records for ``rows`` (unique, in-range), faulting misses in from
        the cold tier page-by-page.  Blocks while the cold tier is down —
        the training-side stall-then-resume behavior."""
        rows = np.asarray(rows, np.int64)
        if rows.size > self.max_request_rows():
            raise ValueError(
                f"one request of {rows.size} rows exceeds the host tier's "
                f"serviceable window ({self.max_request_rows()} of "
                f"{self.capacity} rows) — eviction would displace the "
                f"request's own rows; raise tiered_host_rows"
            )
        first = True
        while True:
            self._ensure(rows, prefetch=not first)
            first = False
            with self._cond:
                # a concurrent writer's eviction may race the fault-in;
                # re-ensure until every row is present at gather time
                if any(int(r) not in self._idx_of for r in rows):
                    continue
                self._clock += 1
                idx = np.fromiter(
                    (self._idx_of[int(r)] for r in rows), np.int64, len(rows)
                )
                self._use[idx] = self._clock
                return self._buf[idx].copy()

    def prefetch(self, rows: np.ndarray) -> int:
        """Make ``rows`` resident without returning them (the id-stream
        prefetch hook).  Returns how many rows were actually fetched."""
        rows = np.unique(np.asarray(rows, np.int64))
        rows = rows[(rows >= 0) & (rows < self.cold.rows)]
        n = self._ensure(rows, prefetch=True)
        with self._cond:
            self._stats["prefetched_rows"] += n
        return n

    def _ensure(self, rows: np.ndarray, *, prefetch: bool) -> int:
        """Fault the missing subset of ``rows`` in.  Lock dropped around
        cold reads; concurrent fetches of one page deduplicate via the
        in-flight set."""
        fetched = 0
        while True:
            with self._cond:
                missing = [int(r) for r in rows if int(r) not in self._idx_of]
                if not prefetch:
                    # newer-than-everything-older use stamp: rows inserted
                    # by THIS fill can only be evicted once strictly older
                    # residents are exhausted — which the request-size
                    # window (max_request_rows) guarantees never happens
                    # mid-fill, so a fill cannot displace its own rows
                    self._clock += 1
                    self._stats["host_hits"] += len(rows) - len(missing)
                    self._stats["host_misses"] += len(missing)
                    prefetch = True  # count only the first pass
                if not missing:
                    return fetched
                pages = {r // self.cold.page_rows for r in missing}
                mine = sorted(pages - self._inflight)
                if not mine:
                    # someone else is fetching every page we need
                    self._cond.wait(timeout=0.5)
                    continue
                self._inflight.update(mine)
            try:
                got = {}
                for page in mine:
                    got[page] = self.cold.read_page(page)  # no lock held
            finally:
                with self._cond:
                    self._inflight.difference_update(mine)
                    self._cond.notify_all()
            with self._cond:
                for page, recs in got.items():
                    lo = page * self.cold.page_rows
                    want = [r for r in missing
                            if r // self.cold.page_rows == page
                            and r not in self._idx_of]
                    for r in want:
                        # da:allow[blocking-under-lock] eviction flush I/O deliberately runs under the lock (see _alloc_locked): a victim slot must not be reused until its dirty rows hit the cold tier — stall, never corrupt
                        i = self._alloc_locked()
                        self._buf[i] = recs[r - lo]
                        self._idx_of[r] = i
                        self._row_at[i] = r
                        self._dirty[i] = False
                        self._use[i] = self._clock
                        fetched += 1

    # -- write path --------------------------------------------------------
    def put_records(self, rows: np.ndarray, recs: np.ndarray) -> None:
        """Absorb device-evicted (or checkpoint-flushed) dirty records.
        Rows the tier already dropped are re-inserted — the device copy is
        the freshest version wherever it exists."""
        rows = np.asarray(rows, np.int64)
        with self._cond:
            self._clock += 1
            for r, rec in zip(rows, recs):
                r = int(r)
                i = self._idx_of.get(r)
                if i is None:
                    # da:allow[blocking-under-lock] same eviction-under-lock contract as _ensure: the flush to the cold tier must complete before the slot is recycled
                    i = self._alloc_locked()
                    self._idx_of[r] = i
                    self._row_at[i] = r
                self._buf[i] = rec
                self._dirty[i] = True
                self._use[i] = self._clock

    def _alloc_locked(self) -> int:
        """One free buffer index; evicts (approximate-)LRU rows when full,
        flushing dirty victims' pages to the cold tier first.  Caller
        holds the lock; the flush I/O runs under it too — eviction under a
        dead cold tier stalls the writer, never corrupts."""
        if self._free:
            return self._free.pop()
        live = np.flatnonzero(self._row_at >= 0)
        n_evict = max(1, self.capacity // 16)
        order = live[np.argpartition(self._use[live], n_evict)[:n_evict]]
        dirty = order[self._dirty[order]]
        if dirty.size:
            self._flush_indices_locked(dirty)
        for i in order:
            del self._idx_of[int(self._row_at[i])]
            self._row_at[i] = -1
            self._dirty[i] = False
            self._free.append(int(i))
        self._stats["host_evictions"] += int(order.size)
        return self._free.pop()

    def _flush_indices_locked(self, idx: np.ndarray) -> None:
        """Read-modify-write the dirty rows at ``idx`` into their cold
        pages (grouped, one overlay write per touched page)."""
        rows = self._row_at[idx]
        order = np.argsort(rows)
        idx, rows = idx[order], rows[order]
        pages = rows // self.cold.page_rows
        for page in np.unique(pages):
            sel = pages == page
            recs = self.cold.read_page(int(page))
            recs[rows[sel] - int(page) * self.cold.page_rows] = \
                self._buf[idx[sel]]
            self.cold.write_page(int(page), recs)
            self._stats["host_flushed_rows"] += int(sel.sum())
        self._dirty[idx] = False

    def flush(self) -> int:
        """Write EVERY dirty row back to the cold tier (checkpoint /
        publish barrier).  Returns rows flushed."""
        with self._cond:
            dirty = np.flatnonzero(self._dirty & (self._row_at >= 0))
            before = self._stats["host_flushed_rows"]
            if dirty.size:
                # da:allow[blocking-under-lock] checkpoint/publish barrier: the stop-the-world flush IS the semantics — concurrent writers must observe all-dirty-rows-durable, not a torn snapshot
                self._flush_indices_locked(dirty)
            return self._stats["host_flushed_rows"] - before

    def stats(self) -> dict:
        with self._cond:
            out = dict(self._stats)
            out["host_resident_rows"] = len(self._idx_of)
        return out
