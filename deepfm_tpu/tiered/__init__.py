"""Tiered giant-vocab embedding store: HBM hot cache ← host ← object store.

Production CTR vocabularies are 10⁸–10⁹ rows; a fully-resident table (and
its two Adam moments) cannot live in device memory, and
``docs/BENCH_LARGE_VOCAB.json`` shows the resident design already straining
at 10M rows.  This package pages embedding rows through three tiers:

* **hot** — a fixed-capacity device-resident cache of rows *plus their
  lazy-Adam moments* (the lazy step only ever touches seen rows, so rows
  and moments co-evict as one record; ``step.py``).  The steady-state
  train step is ONE jit-stable executable over slot space: batch ids are
  translated to cache slots on the host, and the deduped unique-id stream
  (the same structure as PR 5's exchange plan) is the cache-probe key
  stream — slot ids are bounded by the capacity, so the packed single-key
  sort (``ops/embedding.py``) always engages.
* **host** — a pinned-host-memory backing store (``host.py``) with an
  async double-buffered staging path: misses resolved between steps fill
  one staging buffer while the device consumes the other, and a
  background prefetcher fed by the input pipeline's id stream
  (``data/pipeline.py`` ``DevicePrefetcher(observer=...)``) pulls
  upcoming rows cold→host before the step needs them.
* **cold** — the existing object store (``store.py``): immutable base
  segments read with HTTP ``Range`` GETs (a row page never downloads a
  whole segment) plus copy-on-write page overlays for dirty writeback,
  all under the PR 3 retry/fault discipline — a cold-tier outage stalls
  training (which resumes) and leaves serving stale-but-alive on
  hot/host-resident rows.

Checkpointing streams tiers instead of gathering (``trainer.py``
``save``/``restore``): dirty rows+moments write back hot→host→cold and a
small metadata record commits — no full-table host gather, attacking the
measured 322 s / 2.4× peak-RSS resident save path.  The same flush
composes with the online publisher so a served snapshot is consistent
(``online/publisher.py`` ``tiered=``).
"""

from .host import HostTier
from .pager import DevicePager
from .serving import TieredScorer
from .step import (
    PagedHot,
    PagedState,
    make_paged_predict,
    make_paged_train_step,
    make_readback,
)
from .store import ColdTier, RecordLayout
from .trainer import TieredTrainer, resolve_tiered

__all__ = [
    "ColdTier",
    "DevicePager",
    "HostTier",
    "PagedHot",
    "PagedState",
    "RecordLayout",
    "TieredScorer",
    "TieredTrainer",
    "make_paged_predict",
    "make_paged_train_step",
    "make_readback",
    "resolve_tiered",
]
