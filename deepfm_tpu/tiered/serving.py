"""Read-only tiered serving: score against a paged snapshot.

``TieredScorer`` is the serve-side twin of the trainer's pager: a small
device-resident row cache (VALUES only — moments never leave the training
tiers), its own host tier, and a cold tier pinned to a CONSISTENT
``page_versions`` snapshot (the one the publisher's manifest recorded
after the trainer's flush barrier), so a live trainer flushing new
overlays never tears the rows this scorer reads.

Degradation contract (the PR 3 story): the cold tier is the only remote
dependency.  While it is down, every request touching hot/host-resident
rows keeps answering — stale-but-serving; only requests forcing a cold
fault fail (fail-FAST retry policy — serving never stalls a request on a
dead store), counted in ``paging_snapshot()["cold_errors"]``.  The chaos
drill (tests/test_tiered_chaos.py) kills the store for 10 s mid
train+serve and asserts zero failed predicts on resident rows.

Implements the engine protocol ``serve/server.py`` handlers expect
(``score_instances`` / ``metrics_snapshot``); ``/v1/metrics`` picks up
the paging gauges through the generic ``paging_snapshot`` hook.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from ..core.config import Config
from .host import HostTier
from .pager import SlotMap
from .step import make_paged_predict
from .store import ColdTier, RecordLayout


class TieredScorer:
    def __init__(
        self,
        cfg: Config,
        cold: ColdTier,
        *,
        rest,
        model_state,
        capacity: int = 0,
        host_rows: int = 0,
    ):
        import jax.numpy as jnp

        from .trainer import resolve_tiered

        sizes = resolve_tiered(cfg)
        self.cfg = cfg
        self.cold = cold
        self.capacity = int(capacity or sizes["capacity"])
        self.host = HostTier(cold, int(host_rows or sizes["host_rows"]))
        self._rest = rest
        self._model_state = model_state
        self._predict = make_paged_predict(cfg)
        self._lock = threading.Lock()
        self._map = SlotMap(self.capacity)
        self._hot = {
            k: jnp.zeros((self.capacity,) if w == 1 else (self.capacity, w),
                         jnp.float32)
            for k, w in cold.layout.widths.items()
        }
        self._stats = {
            "requests": 0, "scored_rows": 0, "hits": 0, "misses": 0,
            "evictions": 0, "cold_errors": 0, "refill_bytes": 0,
        }

    @classmethod
    def from_publish(
        cls, root: str, staging_dir: str, *, version: int | None = None,
        cold_root: str | None = None, init_fn=None, retry=None,
        capacity: int = 0, host_rows: int = 0,
    ) -> "TieredScorer":
        """Build a scorer from a ``ModelPublisher.publish_tiered`` version:
        the manifest's ``extra["tiered"]`` snapshot pins ``page_versions``
        (consistent reads forever), the version artifact supplies the
        config + rest params.  ``retry`` should stay fail-fast — serving
        never stalls a request on a dead cold tier."""
        import jax

        from ..online import publisher as pub
        from .trainer import _rest_template, _split_rest

        manifest = (pub.read_manifest(root, version) if version is not None
                    else pub.latest_manifest(root))
        if manifest is None:
            raise FileNotFoundError(f"no committed versions under {root}")
        snap = manifest.extra.get("tiered")
        if not snap:
            raise ValueError(
                f"version {manifest.version} under {root} is not a tiered "
                f"publish (no extra['tiered'] snapshot)"
            )
        art = pub.fetch_version(root, manifest.version, staging_dir)
        cfg = Config.from_json(os.path.join(art, "config.json"))
        template = _rest_template(cfg)
        rest_t, *_ = _split_rest(cfg, template)
        tpl = (rest_t, template.model_state)
        flat, treedef = jax.tree_util.tree_flatten(tpl)
        with np.load(os.path.join(art, "rest_leaves.npz")) as z:
            loaded = [z[f"leaf_{i}"] for i in range(len(z.files))]
        if len(loaded) != len(flat):
            raise ValueError(
                f"tiered artifact has {len(loaded)} rest leaves, template "
                f"expects {len(flat)}"
            )
        rest, model_state = jax.tree_util.tree_unflatten(treedef, loaded)
        layout = RecordLayout(
            {k: int(w) for k, w in snap["widths"].items()}
        )
        cold = ColdTier(
            cold_root or snap["root"], rows=int(snap["rows"]),
            layout=layout, page_rows=int(snap["page_rows"]),
            pages_per_segment=int(snap["pages_per_segment"]),
            init_fn=init_fn, retry=retry,
            page_versions={int(p): int(v)
                           for p, v in snap["page_versions"].items()},
        )
        return cls(cfg, cold, rest=rest, model_state=model_state,
                   capacity=capacity, host_rows=host_rows)

    # -- engine protocol ---------------------------------------------------
    def score(self, ids: np.ndarray, vals: np.ndarray) -> np.ndarray:
        """probs [B] for ids/vals [B, F].  Misses fault through
        host←cold; a dead cold tier fails ONLY the faulting request.

        The instance lock covers the slot-map bookkeeping ONLY — never
        the cold-tier fault-in (host/cold I/O with its own locking) and
        never the device dispatch.  A request stalled on a dead cold tier
        therefore cannot block concurrent hot-resident requests — the
        stale-but-serving contract the chaos drill measures.  Predict
        runs on an immutable SNAPSHOT of the hot arrays captured with the
        slot translation, so a concurrent refill (which rebinds
        ``self._hot`` to NEW arrays) can never tear an in-flight score."""
        ids = np.asarray(ids)
        vals = np.asarray(vals, np.float32)
        slot_ids, hot = self._translate(ids)
        with self._lock:
            self._stats["requests"] += 1
            self._stats["scored_rows"] += int(ids.shape[0])
        probs = self._predict(
            self._rest, self._model_state, hot,
            {"slot_ids": slot_ids, "feat_vals": vals},
        )
        return np.asarray(probs)

    def score_instances(self, instances: list[dict]) -> np.ndarray:
        from ..serve.batcher import instances_to_arrays

        ids, vals = instances_to_arrays(instances)
        return self.score(ids, vals)

    # -- paging ------------------------------------------------------------
    _FAULT_ROUNDS = 4

    def _translate(self, ids: np.ndarray):
        """``(slot_ids, hot_snapshot)``: probe under the lock, fault
        misses in OUTSIDE it (host/cold I/O), re-probe and commit.  The
        probe/commit loop is bounded: a concurrent eviction storm can
        displace a fetched row before commit, but each round re-fetches
        only the still-missing remainder (the shared :class:`SlotMap`
        pins this request's rows for the epoch)."""
        flat = np.asarray(ids).reshape(-1).astype(np.int64)
        np.clip(flat, 0, self.cold.rows - 1, out=flat)
        uniq, inv = np.unique(flat, return_inverse=True)
        first = True
        for _ in range(self._FAULT_ROUNDS):
            with self._lock:
                if first:
                    self._map.begin()
                slots, miss_ix = self._map.probe(uniq)
                if first:
                    self._stats["hits"] += uniq.size - len(miss_ix)
                    self._stats["misses"] += len(miss_ix)
                    first = False
                if not miss_ix:
                    hot = dict(self._hot)
                    return (slots[inv].astype(np.int32)
                            .reshape(np.asarray(ids).shape), hot)
            rows = uniq[miss_ix]
            try:
                recs = self.host.get_records(rows)   # I/O: lock NOT held
            except Exception:
                with self._lock:
                    self._stats["cold_errors"] += 1
                raise
            r_vals, _, _ = self.cold.layout.unpack(recs)
            with self._lock:
                # commit: another request may have resident'ed some rows
                # meanwhile — probe() refreshes; assign only the gaps
                now, still = self._map.probe(uniq)
                fetched = set(miss_ix)
                gap = [j for j in still if j in fetched]
                take = self._map.select(len(gap), "serving slots")
                pos = {j: i for i, j in enumerate(miss_ix)}
                sel = np.asarray([pos[j] for j in gap], np.int64)
                self._stats["evictions"] += int(
                    (self._map.slot_row[take] >= 0).sum())
                self._map.release(take)
                # swap via index update: new arrays bind under the
                # precompiled predict; in-flight scores keep their
                # snapshots of the OLD (immutable) arrays
                for k in self._hot:
                    vals_k = np.asarray(r_vals[k])[sel]
                    self._hot[k] = self._hot[k].at[take].set(
                        vals_k, mode="drop"
                    )
                self._map.assign(take, uniq[gap])
                self._stats["refill_bytes"] += int(
                    len(gap) * self.cold.layout.width * 4)
        raise RuntimeError(
            f"slot translation did not converge in {self._FAULT_ROUNDS} "
            f"rounds — serving cache of {self.capacity} slots is thrashing "
            f"under concurrent requests; raise capacity"
        )

    def warm(self, ids) -> None:
        """Pre-resident rows (the drill warms the serve set before the
        outage; production warms from the id stream's head)."""
        flat = np.unique(np.asarray(ids).reshape(-1))
        self._translate(flat.reshape(1, -1))

    # -- metrics -----------------------------------------------------------
    def paging_snapshot(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            probed = max(1, out["hits"] + out["misses"])
            out["hit_rate"] = round(out["hits"] / probed, 6)
            out["resident_slots"] = len(self._map)
        out["host"] = self.host.stats()
        out["cold"] = self.cold.stats()
        return out

    def metrics_snapshot(self) -> dict:
        snap = self.paging_snapshot()
        return {"requests": snap["requests"], "paging": snap}
