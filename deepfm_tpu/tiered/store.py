"""Cold tier: object-store row pages with ranged reads + COW overlays.

The cold tier is the system of record for every row the hot/host tiers do
not hold.  Rows are grouped into fixed-size **pages** (``page_rows`` rows
of ``RecordLayout.width`` f32s each); pages are stored two ways:

* **base segments** — immutable bulk objects of ``pages_per_segment``
  pages each (``segments/<seg>.bin``), written once by
  :meth:`ColdTier.import_dense` (or a bulk-import job).  A page read
  fetches ONLY its byte span via an HTTP ``Range`` GET
  (``HttpObjectStore.get_range``) — never the whole segment, which at
  north-star scale is tens of MB of other rows.
* **page overlays** — copy-on-write objects ``pages/<page>.v<ver>.bin``
  holding dirty pages written back from the host tier.  ``page_versions``
  maps page → committed overlay version; a reader holding a snapshot of
  that map sees a CONSISTENT table no matter what the writer flushes
  afterwards — the property the online publisher's manifest records
  (``snapshot()``).

Pages absent from both (a giant table nobody ever wrote) materialize from
``init_fn(page) -> [rows, width]`` — the virtual-initializer trick that
lets a 100M-row table exist without 40 GB of objects; only touched pages
ever hit storage.

Every remote byte moves through ``HttpObjectStore`` and therefore under
its ``RetryPolicy`` (PR 3): the trainer installs a patient policy so a
cold-tier outage stalls paging (and training) until the store heals,
while serving keeps a fail-fast policy and keeps answering from resident
rows.  All reads/writes are accounted in ``stats()`` — the paging
bandwidth the large-vocab bench curves come from.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from ..data.object_store import HttpObjectStore, is_url, join_url

# a cold read slower than this counts its excess toward ``stall_secs`` —
# the stalls-then-resumes signal the chaos drill asserts on
_STALL_BUDGET_SECS = 0.5

_ITEM = np.dtype(np.float32).itemsize


class RecordLayout:
    """Per-row record: ``[value | m | v]`` per table, tables concatenated.

    One record carries a row of EVERY lazy table plus both Adam moments,
    so a single page fetch (and a single writeback) services the whole
    co-evicted unit — the reason rows and moments can share one paging
    decision.  ``widths`` maps table name → row width (fm_w: 1, fm_v: K)
    in a fixed iteration order shared by every tier.
    """

    def __init__(self, widths: dict[str, int]):
        if not widths:
            raise ValueError("RecordLayout needs at least one table")
        self.widths = dict(widths)
        self.keys = tuple(widths)
        self._off: dict[str, int] = {}
        off = 0
        for k, w in widths.items():
            self._off[k] = off
            off += 3 * int(w)
        self.width = off  # floats per row record

    def value_slice(self, key: str) -> slice:
        """Columns holding table ``key``'s row VALUE (serving reads only
        values; moments ride along for training)."""
        o, w = self._off[key], self.widths[key]
        return slice(o, o + w)

    def moment_slices(self, key: str) -> tuple[slice, slice]:
        o, w = self._off[key], self.widths[key]
        return slice(o + w, o + 2 * w), slice(o + 2 * w, o + 3 * w)

    def pack(self, rows: dict, m: dict, v: dict) -> np.ndarray:
        """dicts of [n(, w)] arrays -> [n, width] records."""
        n = np.asarray(rows[self.keys[0]]).shape[0]
        out = np.empty((n, self.width), np.float32)
        for k in self.keys:
            w = self.widths[k]
            for sl, src in zip(
                (self.value_slice(k), *self.moment_slices(k)),
                (rows[k], m[k], v[k]),
            ):
                out[:, sl] = np.asarray(src, np.float32).reshape(n, w)
        return out

    def unpack(self, recs: np.ndarray) -> tuple[dict, dict, dict]:
        """[n, width] records -> (rows, m, v) dicts shaped like the tables
        ([n] for width-1 tables, [n, w] otherwise)."""
        rows, m, v = {}, {}, {}
        for k in self.keys:
            w = self.widths[k]
            msl, vsl = self.moment_slices(k)
            parts = [recs[:, self.value_slice(k)], recs[:, msl], recs[:, vsl]]
            if w == 1:
                parts = [a[:, 0] for a in parts]
            rows[k], m[k], v[k] = parts
        return rows, m, v


class ColdTier:
    """Page-granular row storage on a directory or object-store prefix."""

    def __init__(
        self,
        root: str,
        *,
        rows: int,
        layout: RecordLayout,
        page_rows: int = 1024,
        pages_per_segment: int = 64,
        init_fn=None,
        retry=None,
        page_versions: dict[int, int] | None = None,
    ):
        if page_rows < 1 or pages_per_segment < 1:
            raise ValueError("page_rows and pages_per_segment must be >= 1")
        self.root = root.rstrip("/")
        self.rows = int(rows)
        self.layout = layout
        self.page_rows = int(page_rows)
        self.pages_per_segment = int(pages_per_segment)
        self.num_pages = -(-self.rows // self.page_rows)
        self._init_fn = init_fn
        self._remote = is_url(root)
        self._store = HttpObjectStore(retry=retry) if self._remote else None
        self._lock = threading.Lock()
        self._page_versions: dict[int, int] = dict(page_versions or {})
        self._superseded: dict[int, list[int]] = {}
        self._next_version = 1 + max(self._page_versions.values(), default=0)
        self._seg_exists: dict[int, bool] = {}
        self._stats = {
            "cold_reads": 0, "cold_read_bytes": 0, "cold_read_secs": 0.0,
            "cold_writes": 0, "cold_write_bytes": 0, "init_pages": 0,
            "stall_secs": 0.0,
        }

    # -- keys --------------------------------------------------------------
    def _seg_key(self, seg: int) -> str:
        name = f"segments/{seg:06d}.bin"
        return (join_url(self.root, name) if self._remote
                else os.path.join(self.root, *name.split("/")))

    def _page_key(self, page: int, version: int) -> str:
        name = f"pages/{page:08d}.v{version:06d}.bin"
        return (join_url(self.root, name) if self._remote
                else os.path.join(self.root, *name.split("/")))

    def page_len(self, page: int) -> int:
        return min(self.page_rows, self.rows - page * self.page_rows)

    # -- read --------------------------------------------------------------
    def read_page(self, page: int) -> np.ndarray:
        """One page's records ``[page_len, width]``: committed overlay if
        any, else a ranged read of its base-segment span, else the virtual
        initializer."""
        if not 0 <= page < self.num_pages:
            raise IndexError(f"page {page} out of range [0, {self.num_pages})")
        eff = self.page_len(page)
        nbytes = eff * self.layout.width * _ITEM
        with self._lock:
            version = self._page_versions.get(page)
        t0 = time.monotonic()
        data = None
        if version is not None:
            data = self._read_object(self._page_key(page, version))
        else:
            seg = page // self.pages_per_segment
            if self._segment_exists(seg):
                off = (page % self.pages_per_segment) \
                    * self.page_rows * self.layout.width * _ITEM
                data = self._read_range(self._seg_key(seg), off, nbytes)
        elapsed = time.monotonic() - t0
        if data is None:
            if self._init_fn is None:
                raise KeyError(
                    f"page {page} has no overlay, no base segment, and no "
                    f"init_fn under {self.root}"
                )
            arr = np.asarray(self._init_fn(page), np.float32)
            if arr.shape != (eff, self.layout.width):
                raise ValueError(
                    f"init_fn(page={page}) returned {arr.shape}, expected "
                    f"{(eff, self.layout.width)}"
                )
            with self._lock:
                self._stats["init_pages"] += 1
            return arr
        with self._lock:
            self._stats["cold_reads"] += 1
            self._stats["cold_read_bytes"] += len(data)
            self._stats["cold_read_secs"] += elapsed
            if elapsed > _STALL_BUDGET_SECS:
                self._stats["stall_secs"] += elapsed - _STALL_BUDGET_SECS
        return np.frombuffer(data, np.float32).reshape(
            eff, self.layout.width
        ).copy()

    def _segment_exists(self, seg: int) -> bool:
        with self._lock:
            cached = self._seg_exists.get(seg)
        if cached is not None:
            return cached
        key = self._seg_key(seg)
        found = (self._store.exists(key) if self._remote
                 else os.path.isfile(key))
        with self._lock:
            # only a positive probe is cached: a segment published later
            # (bulk import racing readers) must stay discoverable
            if found:
                self._seg_exists[seg] = True
        return found

    def _read_object(self, key: str) -> bytes:
        if self._remote:
            return self._store.get(key)
        with open(key, "rb") as f:
            return f.read()

    def _read_range(self, key: str, offset: int, length: int) -> bytes:
        if self._remote:
            return self._store.get_range(key, offset, length)
        with open(key, "rb") as f:
            f.seek(offset)
            return f.read(length)

    # -- write -------------------------------------------------------------
    def write_page(self, page: int, recs: np.ndarray) -> int:
        """Commit a dirty page as a NEW overlay version (copy-on-write —
        readers pinned to an older ``page_versions`` snapshot keep seeing
        their version).  Returns the committed version."""
        eff = self.page_len(page)
        recs = np.ascontiguousarray(recs, np.float32)
        if recs.shape != (eff, self.layout.width):
            raise ValueError(
                f"page {page} write has shape {recs.shape}, expected "
                f"{(eff, self.layout.width)}"
            )
        with self._lock:
            version = self._next_version
            self._next_version += 1
            if page in self._superseded:
                self._superseded[page].append(self._page_versions[page])
            elif page in self._page_versions:
                self._superseded[page] = [self._page_versions[page]]
        data = recs.tobytes()
        key = self._page_key(page, version)
        if self._remote:
            self._store.put(key, data)
        else:
            os.makedirs(os.path.dirname(key), exist_ok=True)
            tmp = key + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, key)
        with self._lock:
            self._page_versions[page] = version
            self._stats["cold_writes"] += 1
            self._stats["cold_write_bytes"] += len(data)
        # NOTE: the superseded overlay is NOT deleted here — copy-on-write
        # is the consistency mechanism: a publisher manifest or paged
        # checkpoint pinning the old page_versions must keep reading the
        # old object.  Space is reclaimed explicitly via gc_overlays().
        return version

    def gc_overlays(self, pinned: list[dict] | None = None) -> int:
        """Delete superseded overlay objects not referenced by the LIVE
        map nor by any ``pinned`` snapshot (``snapshot()`` dicts from
        still-readable manifests/checkpoints).  Explicit — never called on
        the write path — so retention policy stays with the caller (the
        publisher's keep-window, the checkpoint's keep count).  Returns
        objects deleted; failures are skipped (an orphan costs space,
        never correctness)."""
        keep: set[tuple[int, int]] = set()
        for snap in pinned or []:
            for p, ver in snap.get("page_versions", {}).items():
                keep.add((int(p), int(ver)))
        with self._lock:
            keep.update(
                (p, ver) for p, ver in self._page_versions.items()
            )
            doomed = [
                (p, ver)
                for p, vers in self._superseded.items()
                for ver in vers if (p, ver) not in keep
            ]
            self._superseded = {}
        deleted = 0
        for p, ver in doomed:
            try:
                if self._remote:
                    self._store.delete(self._page_key(p, ver))
                else:
                    os.remove(self._page_key(p, ver))
                deleted += 1
            except OSError:
                import logging

                logging.getLogger(__name__).warning(
                    "cold tier: could not gc overlay page=%d v=%d", p, ver,
                )
        return deleted

    # -- bulk import / export ----------------------------------------------
    def import_dense(self, rows: dict, m: dict, v: dict) -> int:
        """Write a fully-materialized table (+moments) as BASE SEGMENTS —
        the bulk-ingest path (and the parity tests' seed), exercising the
        ranged-read format end to end.  Returns segments written."""
        seg_rows = self.page_rows * self.pages_per_segment
        n_segs = -(-self.rows // seg_rows)
        for seg in range(n_segs):
            lo = seg * seg_rows
            hi = min(self.rows, lo + seg_rows)
            recs = self.layout.pack(
                {k: np.asarray(rows[k])[lo:hi] for k in self.layout.keys},
                {k: np.asarray(m[k])[lo:hi] for k in self.layout.keys},
                {k: np.asarray(v[k])[lo:hi] for k in self.layout.keys},
            )
            data = np.ascontiguousarray(recs).tobytes()
            key = self._seg_key(seg)
            if self._remote:
                self._store.put(key, data)
            else:
                os.makedirs(os.path.dirname(key), exist_ok=True)
                tmp = key + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, key)
            with self._lock:
                self._seg_exists[seg] = True
        return n_segs

    def export_dense(self) -> tuple[dict, dict, dict]:
        """Materialize the whole logical table (+moments) — SMALL vocabs
        only (parity tests); the point of this package is that production
        tables never do this."""
        rows = {k: np.empty(
            (self.rows,) if w == 1 else (self.rows, w), np.float32)
            for k, w in self.layout.widths.items()}
        m = {k: np.empty_like(a) for k, a in rows.items()}
        v = {k: np.empty_like(a) for k, a in rows.items()}
        for page in range(self.num_pages):
            lo = page * self.page_rows
            pr, pm, pv = self.layout.unpack(self.read_page(page))
            for k in self.layout.keys:
                rows[k][lo:lo + self.page_len(page)] = pr[k]
                m[k][lo:lo + self.page_len(page)] = pm[k]
                v[k][lo:lo + self.page_len(page)] = pv[k]
        return rows, m, v

    # -- snapshot / stats --------------------------------------------------
    def snapshot(self) -> dict:
        """Consistent-read descriptor: everything a reader needs to see
        exactly the rows committed so far (the publisher manifests this;
        the paged checkpoint persists it)."""
        with self._lock:
            return {
                "root": self.root,
                "rows": self.rows,
                "page_rows": self.page_rows,
                "pages_per_segment": self.pages_per_segment,
                "widths": dict(self.layout.widths),
                "page_versions": {
                    str(p): int(ver)
                    for p, ver in self._page_versions.items()
                },
            }

    def stats(self) -> dict:
        with self._lock:
            return dict(self._stats)
