"""Slot-space train/predict steps over the device-resident hot cache.

The steady-state step is ONE jit-stable executable whose only host-data
entry points are its ARGUMENTS (the paging trace-audit contract,
``analysis/trace_audit.py`` ``audit_paged_step``): the batch arrives with
ids already translated to cache slots, and the pager's staged miss pack
``(stage_slots, {table: rows/m/v})`` swaps into the cache via one
sorted-unique index update — the "swap via index update" leg of
fetch → stage → swap.  Nothing inside the trace reads the host.

Bit-parity with the fully-resident lazy step (``train/step.py``
``_make_lazy_train_step``) holds by construction:

* slot translation is a bijection between the batch's unique rows and
  slots, so the dedup/segment structure over slots groups EXACTLY the
  occurrences the resident path groups over row ids, in the same stable
  (position-tie-broken) order — per-row summed gradients are bitwise
  identical;
* the per-row Adam arithmetic is literally the same function
  (``train/lazy.py`` ``lazy_adam_update`` — slots are just another id
  stream with ``id_bound = capacity``, which ALWAYS fits the packed
  single-key sort: the cache-probe key stream is the cheapest sort in
  the repo);
* rows/moments round-trip the host/cold tiers as raw f32 bytes.

``tests/test_tiered.py`` asserts the parity (same seeds, forced
evictions, crash-resume) to zero tolerance.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

from ..core.config import Config
from ..models.base import get_model
from ..ops.embedding import dense_lookup
from ..train.lazy import lazy_adam_update, shared_segments
from ..train.optimizer import build_lr_schedule, build_optimizer, schedule_value
from ..train.step import _dp_size, sigmoid_cross_entropy


class PagedHot(NamedTuple):
    """Device-resident cache: rows + both lazy-Adam moments, co-located so
    one eviction decision moves the whole record."""

    rows: dict        # {table: [C(, K)]}
    m: dict
    v: dict


class PagedState(NamedTuple):
    step: jnp.ndarray
    rest: Any          # non-table params (fm_b, mlp, bn, ...)
    model_state: Any
    rest_opt: Any
    hot: PagedHot
    rng: jax.Array


def init_hot(widths: dict[str, int], capacity: int) -> PagedHot:
    def zeros():
        return {
            k: jnp.zeros((capacity,) if w == 1 else (capacity, w),
                         jnp.float32)
            for k, w in widths.items()
        }
    return PagedHot(rows=zeros(), m=zeros(), v=zeros())


def _stage_swap(hot: PagedHot, stage_slots, stage) -> PagedHot:
    """The designated staging op: one sorted-unique scatter per array.
    ``stage_slots`` are sorted ascending with out-of-range sentinels
    (``capacity + i``) as padding — the same fast-scatter contract as the
    lazy update (train/lazy.py), dropped by ``mode="drop"``."""
    kw = dict(indices_are_sorted=True, unique_indices=True, mode="drop")
    return PagedHot(
        rows={k: hot.rows[k].at[stage_slots].set(stage[k]["rows"], **kw)
              for k in hot.rows},
        m={k: hot.m[k].at[stage_slots].set(stage[k]["m"], **kw)
           for k in hot.m},
        v={k: hot.v[k].at[stage_slots].set(stage[k]["v"], **kw)
           for k in hot.v},
    )


def make_paged_train_step(
    cfg: Config, capacity: int, *, donate: bool = True
) -> Callable:
    """``(state, batch, stage_slots, stage) -> (state, metrics)`` jitted
    with the state donated (hot-cache buffers update in place in HBM).

    ``batch`` carries ``slot_ids`` [B, F] int32 (host-translated),
    ``feat_vals`` [B, F] f32 and ``label`` [B].  ``stage_slots`` [P] int32
    + ``stage`` {table: {rows, m, v}} is the pager's miss pack for THIS
    batch — applied before the gather so every batch slot is live."""
    model = get_model(cfg.model)
    tx = build_optimizer(cfg.optimizer, data_parallel_size=_dp_size(cfg))
    lr_sched = build_lr_schedule(
        cfg.optimizer, data_parallel_size=_dp_size(cfg)
    )
    emb_mult = cfg.optimizer.embedding_lr_multiplier

    def step(state: PagedState, batch: dict, stage_slots, stage):
        hot = _stage_swap(state.hot, stage_slots, stage)
        keys = list(hot.rows)
        lr = schedule_value(lr_sched, state.step) * emb_mult
        step_rng = jax.random.fold_in(state.rng, state.step)
        slot_ids = batch["slot_ids"]
        rows = {k: dense_lookup(hot.rows[k], slot_ids) for k in keys}

        def loss_fn(rest, rows):
            def row_lookup(table, _ids):
                # CTR families gather fm_w (1-D) and fm_v (2-D) exactly
                # once each; ndim disambiguates (train/step.py)
                return rows["fm_w"] if table.ndim == 1 else rows["fm_v"]

            logits, new_state = model.apply(
                {**rest, **hot.rows},
                state.model_state,
                slot_ids,
                batch["feat_vals"],
                cfg=cfg.model,
                train=True,
                rng=step_rng,
                lookup_fn=row_lookup,
            )
            labels = batch["label"].reshape(-1).astype(jnp.float32)
            return jnp.mean(sigmoid_cross_entropy(logits, labels)), (
                logits, new_state,
            )

        grad_fn = jax.value_and_grad(loss_fn, argnums=(0, 1), has_aux=True)
        (loss, (logits, new_model_state)), (g_rest, g_rows) = grad_fn(
            state.rest, rows
        )
        updates, new_rest_opt = tx.update(g_rest, state.rest_opt, state.rest)
        new_rest = optax.apply_updates(state.rest, updates)

        # the cache-probe key stream: slots are bounded by the capacity,
        # so the packed single-key sort always engages (ops/embedding.py)
        flat_slots = slot_ids.reshape(-1)
        segs = shared_segments(flat_slots, capacity)
        step1 = state.step + 1
        new_rows, new_m, new_v = {}, {}, {}
        for k in keys:
            new_rows[k], new_m[k], new_v[k] = lazy_adam_update(
                hot.rows[k], hot.m[k], hot.v[k],
                flat_slots, g_rows[k], step1, cfg.optimizer,
                learning_rate=lr, l2_reg=cfg.model.l2_reg, segmented=segs,
            )
        metrics = {
            "loss": loss,
            "ce": loss,
            "pred_mean": jnp.mean(jax.nn.sigmoid(logits)),
            "label_mean": jnp.mean(batch["label"].astype(jnp.float32)),
        }
        return (
            PagedState(
                step=step1,
                rest=new_rest,
                model_state=new_model_state,
                rest_opt=new_rest_opt,
                hot=PagedHot(rows=new_rows, m=new_m, v=new_v),
                rng=state.rng,
            ),
            metrics,
        )

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_readback(*, donate: bool = False) -> Callable:
    """The designated device→host exit: gather the records at ``slots``
    (fixed shape [P]; out-of-range sentinels gather garbage the host
    ignores) so the pager can write dirty victims back before their slots
    are reused.  Jitted once; every writeback shares the executable."""

    def readback(hot: PagedHot, slots):
        return (
            {k: jnp.take(hot.rows[k], slots, axis=0, mode="clip")
             for k in hot.rows},
            {k: jnp.take(hot.m[k], slots, axis=0, mode="clip")
             for k in hot.m},
            {k: jnp.take(hot.v[k], slots, axis=0, mode="clip")
             for k in hot.v},
        )

    return jax.jit(readback, donate_argnums=(0,) if donate else ())


def make_paged_predict(cfg: Config) -> Callable:
    """``(rest, model_state, hot_rows, batch) -> probs`` — the serving
    gather over a read-only hot cache (moments never leave the training
    tier).  Weight-parameterized like serve/reload.py: a cache refill or
    hot swap is a jit cache hit."""
    model = get_model(cfg.model)

    def predict(rest, model_state, hot_rows, batch):
        slot_ids = batch["slot_ids"]
        rows = {k: dense_lookup(hot_rows[k], slot_ids) for k in hot_rows}

        def row_lookup(table, _ids):
            return rows["fm_w"] if table.ndim == 1 else rows["fm_v"]

        logits, _ = model.apply(
            {**rest, **hot_rows},
            model_state,
            slot_ids,
            batch["feat_vals"],
            cfg=cfg.model,
            train=False,
            rng=None,
            lookup_fn=row_lookup,
        )
        return jax.nn.sigmoid(logits)

    return jax.jit(predict)
