"""Host-side pager: id→slot translation, eviction, writeback, staging.

The pager owns the hot cache's MAP (row → slot) while the device owns its
DATA.  Per step, between dispatches, it:

1. dedups the batch's id stream (the probe-key stream — the same unique
   structure PR 5's exchange plan computes on-device for sharded lookups),
2. probes the slot map: hits are marked used; misses pick victims
   (free slots first, then approximate-LRU among slots not pinned by this
   batch),
3. writes dirty victims back: ONE fixed-shape jitted gather (the
   designated device→host readback, ``step.make_readback``) pulls their
   records, which land in the host tier,
4. fetches miss records from the host tier (which faults pages in from
   the cold tier),
5. fills one of two preallocated pinned staging buffers (double-buffered:
   the buffer the device is still consuming from step N is never the one
   being filled for step N+1) and returns the translated slot ids + the
   staged pack for the step's index-update swap.

Everything here is host numpy; the device never sees a global row id.
:class:`SlotMap` is the bare bookkeeping (probe/victim-select/assign) —
shared with the serving cache (``serving.py``), which layers no dirty
tracking on it, so the eviction/pinning algorithm exists exactly once.
"""

from __future__ import annotations

import threading

import numpy as np

from ..obs import flight as obs_flight
from ..obs.metrics import MetricsRegistry
from .host import HostTier
from .store import RecordLayout


class SlotMap:
    """Row→slot cache bookkeeping: probe, pinned-LRU victim selection,
    assignment.  No I/O, no locking — callers (the training pager, the
    serving cache) hold their own locks and handle writeback/fetch around
    these primitives.

    The pinning model: ``begin()`` opens a translation epoch; every row
    probed or assigned in the epoch carries ``slot_use == clock`` and is
    not evictable until the next epoch — a batch's working set can never
    evict itself mid-translation."""

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._slot_of: dict[int, int] = {}
        self.slot_row = np.full(self.capacity, -1, np.int64)
        self.slot_use = np.zeros(self.capacity, np.int64)
        self._free = list(range(self.capacity - 1, -1, -1))
        self.clock = 0

    def __len__(self) -> int:
        return len(self._slot_of)

    def begin(self) -> None:
        self.clock += 1

    def probe(self, uniq: np.ndarray) -> tuple[np.ndarray, list[int]]:
        """``(slots, miss_ix)``: per-unique-row slot (-1 for misses, whose
        positions land in ``miss_ix``); hits are pinned for this epoch."""
        slots = np.full(uniq.size, -1, np.int64)
        miss_ix: list[int] = []
        for j, r in enumerate(uniq):
            s = self._slot_of.get(int(r))
            if s is None:
                miss_ix.append(j)
            else:
                slots[j] = s
                self.slot_use[s] = self.clock
        return slots, miss_ix

    def select(self, n: int, what: str = "slots") -> np.ndarray:
        """``n`` reusable slots: the free list first, then approximate-LRU
        victims among unpinned occupied slots (``argpartition`` on
        ``slot_use``).  Victims remain MAPPED — the caller inspects
        ``slot_row[victims]`` (writeback!) then calls :meth:`release`."""
        take: list[int] = []
        while self._free and len(take) < n:
            take.append(self._free.pop())
        need = n - len(take)
        if need > 0:
            cand = np.flatnonzero(
                (self.slot_row >= 0) & (self.slot_use < self.clock)
            )
            if cand.size < need:
                raise ValueError(
                    f"cache of {self.capacity} {what} cannot hold one "
                    f"translation's unique rows (need {need} more "
                    f"evictable slots, have {cand.size}); raise the "
                    f"capacity"
                )
            if cand.size > need:
                cand = cand[
                    np.argpartition(self.slot_use[cand], need - 1)[:need]
                ]
            take.extend(int(s) for s in cand)
        return np.asarray(take[:n], np.int64)

    def release(self, slots: np.ndarray) -> None:
        """Drop the mappings of the OCCUPIED slots among ``slots`` (after
        any writeback) so they can be reassigned."""
        for s in slots:
            r = int(self.slot_row[s])
            if r >= 0:
                del self._slot_of[r]
                self.slot_row[s] = -1

    def assign(self, slots: np.ndarray, rows: np.ndarray) -> None:
        for s, r in zip(slots, rows):
            self._slot_of[int(r)] = int(s)
            self.slot_row[s] = int(r)
            self.slot_use[s] = self.clock

    def reset(self) -> None:
        self._slot_of.clear()
        self.slot_row[:] = -1
        self.slot_use[:] = 0
        self._free = list(range(self.capacity - 1, -1, -1))


class DevicePager:
    def __init__(
        self,
        *,
        capacity: int,
        layout: RecordLayout,
        host: HostTier,
        stage_rows: int,
        readback_fn,
        vocab: int,
        registry: MetricsRegistry | None = None,
    ):
        if stage_rows < 1:
            raise ValueError("stage_rows must be >= 1")
        self.capacity = int(capacity)
        self.stage_rows = int(stage_rows)
        self.layout = layout
        self.host = host
        self.vocab = int(vocab)
        self._readback = readback_fn
        self._map = SlotMap(self.capacity)
        self._slot_dirty = np.zeros(self.capacity, bool)
        self._lock = threading.Lock()
        # double-buffered staging: [2][stage_slots + per-table packs]
        self._bufs = [self._new_stage_buf() for _ in range(2)]
        self._buf_ix = 0
        # counters live in the obs registry (one labeled family per
        # unit), so the paging section scrapes via GET /metrics with
        # labels; stats() re-renders the pinned snapshot dict from the
        # same values
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        events = self.registry.counter(
            "deepfm_paging_events_total",
            "pager lifecycle events by kind", labels=("event",))
        rows = self.registry.counter(
            "deepfm_paging_rows_total",
            "rows moved between tiers", labels=("kind",))
        byts = self.registry.counter(
            "deepfm_paging_bytes_total",
            "bytes moved between tiers", labels=("kind",))
        self._stats = {
            "probe_ids": events.labels("probe_ids"),
            "probe_unique": events.labels("probe_unique"),
            "hits": events.labels("hit"),
            "misses": events.labels("miss"),
            "evictions": events.labels("eviction"),
            "writeback_rows": rows.labels("writeback"),
            "staged_rows": rows.labels("staged"),
            "stage_bytes": byts.labels("stage"),
            "writeback_bytes": byts.labels("writeback"),
            "steps": events.labels("step"),
        }

    def _new_stage_buf(self) -> dict:
        p = self.stage_rows
        buf: dict = {
            "slots": np.empty(p, np.int32),
            "stage": {},
        }
        for k, w in self.layout.widths.items():
            shape = (p,) if w == 1 else (p, w)
            buf["stage"][k] = {
                "rows": np.empty(shape, np.float32),
                "m": np.empty(shape, np.float32),
                "v": np.empty(shape, np.float32),
            }
        return buf

    # -- the per-step probe/translate path ---------------------------------
    def translate(self, ids: np.ndarray, hot) -> tuple[np.ndarray, dict]:
        """Translate batch ids to slots, resolving misses.

        ``hot`` is the CURRENT device cache (``PagedState.hot``) — needed
        to read dirty victims back before their slots are recycled.
        Returns ``(slot_ids int32, staging)`` where staging carries
        ``slots`` [P] int32 (sorted, sentinel-padded) and per-table
        ``rows/m/v`` packs for the step's swap."""
        with self._lock:
            # da:allow[blocking-under-lock] a page fault must complete under the lock: the slot map mutation is atomic with the miss fill, and the step cannot proceed without its rows anyway (stall-don't-corrupt, mirrors HostTier)
            return self._translate_locked(ids, hot)

    def _translate_locked(self, ids: np.ndarray, hot):
        shape = np.asarray(ids).shape
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        np.clip(ids, 0, self.vocab - 1, out=ids)
        uniq, inv = np.unique(ids, return_inverse=True)
        self._map.begin()
        self._stats["steps"].inc()
        self._stats["probe_ids"].inc(int(ids.size))
        self._stats["probe_unique"].inc(int(uniq.size))

        slots, miss_ix = self._map.probe(uniq)
        n_miss = len(miss_ix)
        self._stats["hits"].inc(int(uniq.size) - n_miss)
        self._stats["misses"].inc(n_miss)
        if n_miss > self.stage_rows:
            # a paging stall severe enough to refuse the step is an
            # incident landmark — one line in the flight timeline
            obs_flight.record("paging_stage_overflow", subsystem="tiered",
                              misses=n_miss, stage_rows=self.stage_rows)
            raise ValueError(
                f"batch needs {n_miss} staged rows > stage capacity "
                f"{self.stage_rows}; raise tiered_stage_rows"
            )

        buf = self._bufs[self._buf_ix]
        self._buf_ix ^= 1
        if n_miss:
            victims = self._take_slots(n_miss, hot)
            miss_rows = uniq[miss_ix]
            recs = self.host.get_records(miss_rows)
            rows, m, v = self.layout.unpack(recs)
            # sorted staging slots keep the swap a sorted-unique scatter
            order = np.argsort(victims, kind="stable")
            sv = victims[order]
            buf["slots"][:n_miss] = sv
            for k in self.layout.keys:
                buf["stage"][k]["rows"][:n_miss] = np.asarray(rows[k])[order]
                buf["stage"][k]["m"][:n_miss] = np.asarray(m[k])[order]
                buf["stage"][k]["v"][:n_miss] = np.asarray(v[k])[order]
            self._map.assign(victims, miss_rows)
            slots[miss_ix] = victims
            self._stats["staged_rows"].inc(n_miss)
            self._stats["stage_bytes"].inc(n_miss * self.layout.width * 4)
        # padding: distinct ascending out-of-range sentinels (dropped by
        # mode="drop", keep the index vector sorted AND unique)
        pad = np.arange(self.capacity, self.capacity
                        + (self.stage_rows - n_miss), dtype=np.int32)
        buf["slots"][n_miss:] = pad
        for k in self.layout.keys:
            for part in buf["stage"][k].values():
                part[n_miss:] = 0.0
        # every batch slot will be touched by the lazy update → dirty
        self._slot_dirty[slots] = True
        slot_ids = slots[inv].astype(np.int32).reshape(shape)
        return slot_ids, buf

    def _take_slots(self, n: int, hot) -> np.ndarray:
        """``n`` reusable slots via the shared :class:`SlotMap` victim
        selection; dirty victims write back through the designated
        readback before their mappings drop."""
        take = self._map.select(n, "hot slots")
        victims = take[self._map.slot_row[take] >= 0]
        if victims.size:
            dirty = victims[self._slot_dirty[victims]]
            if dirty.size:
                self._writeback(dirty, hot)
            self._map.release(victims)
            self._stats["evictions"].inc(int(victims.size))
        return take

    def _writeback(self, slots: np.ndarray, hot) -> None:
        """Chunked readback of dirty slots into the host tier."""
        for lo in range(0, slots.size, self.stage_rows):
            chunk = slots[lo:lo + self.stage_rows]
            padded = np.full(self.stage_rows, self.capacity, np.int32)
            padded[:chunk.size] = chunk
            rows_d, m_d, v_d = self._readback(hot, padded)
            q = chunk.size
            recs = self.layout.pack(
                {k: np.asarray(rows_d[k])[:q] for k in self.layout.keys},
                {k: np.asarray(m_d[k])[:q] for k in self.layout.keys},
                {k: np.asarray(v_d[k])[:q] for k in self.layout.keys},
            )
            self.host.put_records(self._map.slot_row[chunk], recs)
            self._stats["writeback_rows"].inc(q)
            self._stats["writeback_bytes"].inc(q * self.layout.width * 4)
        self._slot_dirty[slots] = False

    # -- checkpoint / publish barrier --------------------------------------
    def writeback_all(self, hot) -> int:
        """Flush EVERY dirty slot to the host tier (cache itself stays
        warm) — the hot→host leg of the streaming checkpoint/publish
        flush.  Returns rows written back."""
        with self._lock:
            dirty = np.flatnonzero(
                self._slot_dirty & (self._map.slot_row >= 0)
            )
            if dirty.size:
                # da:allow[blocking-under-lock] checkpoint/publish barrier (see HostTier.flush): every dirty slot must be durable before the barrier returns; a translate racing the flush must wait
                self._writeback(dirty, hot)
        obs_flight.record("paging_flush", subsystem="tiered",
                          rows=int(dirty.size))
        return int(dirty.size)

    def drop_clean(self) -> None:
        """Forget every (now-clean) mapping — crash-resume starts cache
        cold by construction; tests use this to force re-faulting."""
        with self._lock:
            if self._slot_dirty.any():
                raise RuntimeError("drop_clean with dirty slots — "
                                   "writeback_all first")
            self._map.reset()

    def stats(self) -> dict:
        out = {k: int(c.value) for k, c in self._stats.items()}
        probed = max(1, out["probe_unique"])
        out["hit_rate"] = round(out["hits"] / probed, 6)
        return out
