"""Remote (object-store) model_dir — the S3-checkpoint capability.

The reference checkpoints to a shared S3 ``model_dir`` (ps nb cell 4
``model_dir = 's3://.../{now}'``, README.md:63) with SageMaker doing the
transfers.  Here the equivalent is explicit: a ``RemoteCheckpointer`` wraps
the local Orbax :class:`~deepfm_tpu.checkpoint.ckpt.Checkpointer` with a
staging-directory mirror against any URL the S3-wire-subset client
(``data.object_store``) can reach:

* **save**: Orbax writes the step into the local staging dir (async as
  usual); a background uploader then PUTs the step tree to
  ``<url>/<step>/...`` and publishes a ``_COMMIT_<step>`` marker object
  LAST — readers treat only marker-bearing steps as complete, so a crash
  mid-upload never yields a half checkpoint (the atomic-publish semantics
  Orbax gets from a rename on a filesystem).  Transient upload errors
  retry with bounded exponential backoff; a step that exhausts its
  retries is logged on the next ``save()`` and re-enqueued there (while
  it still exists locally and lacks a marker) — the explicit barriers
  (``wait_until_finished``, ``close``, ``save(block=True)``) raise — so
  an object-store outage costs latency, not checkpoints or the training
  process.
* **restore / latest_step**: list remote committed steps; any step missing
  locally is downloaded into staging first, then restored through the
  normal sharding-aware path.
* **retention**: after upload, remote steps that fell out of the local
  ``max_to_keep`` window are deleted (marker first, so a partial delete
  still reads as "not committed").
* **single-writer**: only process 0 uploads — the same invariant the
  reference enforces by rank-0-only checkpointing (hvd:402-415); all
  processes may download.

On Google Cloud, Orbax/TensorStore can target ``gs://`` natively and this
mirror is unnecessary; it exists for the generic S3-style endpoint where no
filesystem driver is available.
"""

from __future__ import annotations

import os
import threading

import jax

from ..data.object_store import get_store, is_url, join_url
from ..train.step import TrainState
from .ckpt import Checkpointer

_MARKER = "_COMMIT_"


def _staging_dir_for(url: str) -> str:
    import hashlib
    import tempfile

    h = hashlib.sha1(url.encode()).hexdigest()[:12]
    return os.path.join(
        tempfile.gettempdir(), f"deepfm_ckpt_stage_{h}_p{jax.process_index()}"
    )


class RemoteCheckpointer:
    """Checkpointer-compatible facade over a remote object-store URL."""

    def __init__(
        self,
        url: str,
        *,
        max_to_keep: int = 3,
        async_save: bool = True,
        staging_dir: str | None = None,
        upload_retries: int = 3,
        retry_backoff_secs: float = 0.2,
    ):
        if jax.process_count() > 1:
            # Orbax's collective save needs ONE shared directory all
            # processes write into; per-host staging mirrors would upload
            # only process 0's shards — silent data loss.  (Measured, not
            # assumed: a 2-process probe passing per-process staging dirs
            # deadlocks inside the save's directory-sync barrier.)
            # Multi-host runs should point model_dir at shared storage
            # (NFS/gcsfuse) or a gs:// path Orbax handles natively; the
            # S3-wire mirror serves the reference's actual topology (a
            # single logical writer, hvd:402-415 / PS master).
            raise ValueError(
                "remote (URL) model_dir is single-process only; multi-host "
                "runs need a shared filesystem or an Orbax-native gs:// "
                "path (see checkpoint/remote.py docstring)"
            )
        self._url = url.rstrip("/")
        self._store = get_store()
        self._staging = staging_dir or _staging_dir_for(self._url)
        os.makedirs(self._staging, exist_ok=True)
        self._max_to_keep = max_to_keep
        self._async_save = async_save
        # staging is a CACHE of the remote store (the reference's model_dir
        # IS S3; the local copy is ephemeral).  Local steps with no remote
        # commit marker are leftovers — from a crashed mid-upload run or a
        # remote clear_existing_model — and must not resurrect as
        # `latest_step`; drop them before the manager scans the directory.
        committed = set(self._remote_steps())
        for name in os.listdir(self._staging):
            if name.isdigit() and int(name) not in committed:
                import shutil

                shutil.rmtree(os.path.join(self._staging, name),
                              ignore_errors=True)
        self._local = Checkpointer(
            self._staging, max_to_keep=max_to_keep, async_save=async_save
        )
        self._is_writer = jax.process_index() == 0
        self._uploader: threading.Thread | None = None
        self._upload_err: BaseException | None = None
        # step-level retry tier on top of the store's own per-op retries:
        # one schedule (bounded attempts, full-jitter backoff) instead of
        # the ad-hoc loop this module used to carry
        from ..utils.retry import RetryPolicy

        self._upload_policy = RetryPolicy(
            max_attempts=max(1, int(upload_retries)),
            base_delay_secs=float(retry_backoff_secs),
            max_delay_secs=max(float(retry_backoff_secs), 30.0),
        )
        # steps whose upload exhausted its retries: re-enqueued on the next
        # save() so a transient outage costs latency, not a lost checkpoint
        self._failed_steps: set[int] = set()

    # -- remote index ------------------------------------------------------
    def _remote_steps(self) -> list[int]:
        steps = []
        for url in self._store.list_prefix(self._url + "/"):
            name = url.rsplit("/", 1)[-1]
            if name.startswith(_MARKER):
                try:
                    steps.append(int(name[len(_MARKER):]))
                except ValueError:
                    continue
        return sorted(steps)

    # -- upload side -------------------------------------------------------
    def _join_uploader(self) -> None:
        if self._uploader is not None:
            self._uploader.join()
            self._uploader = None
        if self._upload_err is not None:
            err, self._upload_err = self._upload_err, None
            raise err

    def _upload_step(self, step: int) -> None:
        self._local.wait_until_finished()  # files must be on disk
        step_dir = os.path.join(self._staging, str(step))
        self._store.upload_tree(step_dir, join_url(self._url, str(step)))
        self._store.put(join_url(self._url, f"{_MARKER}{step}"), b"ok")
        # retention: mirror the local window; marker first so a partially
        # deleted step is simply invisible, never half-readable
        keep = set(self._local.all_steps())
        for s in self._remote_steps():
            if s not in keep:
                self._store.delete(join_url(self._url, f"{_MARKER}{s}"))
                self._store.delete_prefix(join_url(self._url, str(s)) + "/")

    # -- Checkpointer interface --------------------------------------------
    def save(self, state: TrainState, *, block: bool = False) -> bool:
        # serialize uploads; a PRIOR upload failure is logged, not raised —
        # raising here would skip this state's local save and kill the
        # (uncatching) train loops, turning an object-store outage into
        # lost checkpoints.  The failed step stays in _failed_steps and is
        # re-enqueued below; explicit barriers (wait_until_finished, close,
        # block=True) still raise for callers that demand durability.
        try:
            self._join_uploader()
        except Exception as e:
            import logging

            logging.getLogger(__name__).warning(
                "remote checkpoint upload failed (step re-enqueued, will "
                "retry on this save): %s", e
            )
        saved = self._local.save(state, block=block)
        if self._is_writer and (saved or self._pending_steps()):
            steps = self._pending_steps()
            if saved:
                steps = [s for s in steps if s != int(state.step)]
                steps.append(int(state.step))
            self._uploader = threading.Thread(
                target=self._try_upload_many, args=(steps,), daemon=True
            )
            self._uploader.start()
            if block:
                self._join_uploader()
        return saved

    def _pending_steps(self) -> list[int]:
        """Previously-failed uploads still worth retrying: the step must
        still exist locally (retention may have dropped it) and still lack
        a remote commit marker (a step that failed only in its post-marker
        retention phase is already committed — re-uploading it would be
        pure waste)."""
        if not self._failed_steps:
            return []
        self._failed_steps &= set(self._local.all_steps())
        if self._failed_steps:
            try:
                self._failed_steps -= set(self._remote_steps())
            # da:allow[swallowed-exception] listing outage: re-uploading a committed step is idempotent waste, losing one is not
            except Exception:
                pass
        return sorted(self._failed_steps)

    def _try_upload_many(self, steps: list[int]) -> None:
        for step in steps:
            try:
                self._upload_with_retries(step)
                self._failed_steps.discard(step)
            except BaseException as e:
                self._upload_err = e
                self._failed_steps.add(step)

    def _upload_with_retries(self, step: int) -> None:
        """Bounded retry-with-backoff for transient object-store errors —
        one flaky PUT must not orphan a whole checkpoint step.  The whole
        step upload re-runs (uploads are idempotent full-object PUTs and
        the marker is written last, so a re-run converges); any exception
        counts as transient here because the local tree is known-good."""
        self._upload_policy.call(lambda: self._upload_step(step),
                                 classify=lambda e: True)

    def wait_until_finished(self) -> None:
        self._local.wait_until_finished()
        self._join_uploader()

    def latest_step(self) -> int | None:
        remote = self._remote_steps()
        local = self._local.latest_step()
        if not remote:
            return local
        if local is None:
            return remote[-1]
        return max(local, remote[-1])

    def all_steps(self) -> list[int]:
        return sorted(set(self._local.all_steps()) | set(self._remote_steps()))

    def _ensure_local(self, step: int) -> None:
        if step in self._local.all_steps():
            return
        self._store.download_tree(
            join_url(self._url, str(step)),
            os.path.join(self._staging, str(step)),
        )
        # recreate the manager so it re-scans the newly landed step dir
        self._local.close()
        self._local = Checkpointer(
            self._staging, max_to_keep=self._max_to_keep,
            async_save=self._async_save,
        )

    def restore(self, target_state: TrainState, step: int | None = None) -> TrainState:
        self._join_uploader()
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint to restore at {self._url}")
        self._ensure_local(step)
        return self._local.restore(target_state, step)

    @property
    def _mngr(self):
        """The underlying Orbax manager — the cross-topology reshard path
        (checkpoint/reshard.py) reaches for ``ckpt._mngr`` after a failed
        sharding-aware restore; by then ``restore`` has already downloaded
        the step into staging, so delegating to the local manager is
        exactly right."""
        return self._local._mngr

    def close(self) -> None:
        self._join_uploader()
        self._local.close()


def make_checkpointer(
    directory: str | os.PathLike, **kwargs
) -> Checkpointer | RemoteCheckpointer:
    """Checkpointer for a local dir, RemoteCheckpointer for an object URL —
    the one switch every model_dir consumer goes through."""
    if is_url(directory):
        return RemoteCheckpointer(str(directory), **kwargs)
    return Checkpointer(directory, **kwargs)


def maybe_clear_remote(url: str, enabled: bool) -> None:
    """``clear_existing_model`` for remote model_dirs (hvd:66-68)."""
    if enabled:
        get_store().delete_prefix(url.rstrip("/") + "/")
