from .ckpt import Checkpointer, maybe_clear  # noqa: F401
from .remote import RemoteCheckpointer, make_checkpointer  # noqa: F401
from .reshard import restore_resharded  # noqa: F401
