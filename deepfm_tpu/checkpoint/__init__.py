from .ckpt import Checkpointer, maybe_clear  # noqa: F401
from .remote import RemoteCheckpointer, make_checkpointer  # noqa: F401
from .reshard import (  # noqa: F401
    restore_resharded,
    restore_resharded_payload,
)


def save_paged(trainer, directory: str) -> dict:
    """Streaming paged checkpoint for a tiered trainer
    (deepfm_tpu/tiered): flush dirty rows+moments hot→host→cold, then
    commit a small metadata record — bytes moved scale with DIRTY rows,
    never the table, unlike the gather-everything Orbax path above
    (3.96 GB state took 322 s to even dispatch at 10M rows,
    docs/BENCH_LARGE_VOCAB.json).  Thin indirection so checkpoint/ is
    the one place callers look for every save flavor; the mechanics
    live in ``tiered.trainer.TieredTrainer.save``/``restore``."""
    return trainer.save(directory)


def restore_paged(cfg, directory: str, **kwargs):
    """Counterpart of :func:`save_paged`: cache-COLD resume (tiers
    refill on demand; training continues bit-identically —
    tests/test_tiered.py)."""
    from ..tiered.trainer import TieredTrainer

    return TieredTrainer.restore(cfg, directory, **kwargs)
