from .ckpt import Checkpointer, maybe_clear  # noqa: F401
