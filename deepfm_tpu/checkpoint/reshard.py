"""Cross-topology checkpoint restore.

A TrainState checkpoint records embedding tables at the PADDED vocabulary of
the mesh it was trained on (``padded_vocab`` = next multiple of
lcm(model_parallel, window_multiple), parallel/spmd.py) — so a run saved on
a [4, 2] mesh cannot restore byte-for-byte into a [2, 4] context whose
padding differs.  The reference had no notion of this (one fixed topology
per job, SURVEY §5); here reshaping the mesh between runs is routine
(train wide, debug narrow, serve single-chip), so restore must adapt.

``restore_resharded`` restores a checkpoint saved under ANY mesh topology
into a target :class:`~deepfm_tpu.parallel.spmd.SPMDContext`: every leaf
living under a table key whose leading dimension is the SAVED padded vocab
is sliced (dropping only all-zero pad rows — verified, never data) or
zero-padded to the target padded vocab, then the whole state is placed into
the target shardings.  Non-table leaves must match shapes exactly.

Single-controller path: the saved arrays are materialized on host during
adaptation (fine up to tens of millions of rows; a shard-streaming variant
is the north-star-scale follow-up).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

from ..train.step import TrainState
from .ckpt import Checkpointer

# mirror parallel/spmd.TABLE_KEYS without importing (keeps this module free
# of the parallel -> models import chain at import time)
_TABLE_KEYS = ("fm_w", "fm_v", "embedding", "user_embedding", "item_embedding")


def _is_table_leaf(path) -> bool:
    keys = {getattr(p, "key", None) for p in path}
    return bool(keys & set(_TABLE_KEYS))


def _dictify(x):
    """Mirror Orbax's on-disk pytree form: NamedTuples -> field dicts
    (field-less ones -> None), tuples -> lists."""
    if isinstance(x, tuple) and hasattr(x, "_fields"):
        if not x._fields:
            return None
        return {f: _dictify(getattr(x, f)) for f in x._fields}
    if isinstance(x, (tuple, list)):
        return [_dictify(v) for v in x]
    if isinstance(x, dict):
        return {k: _dictify(v) for k, v in x.items()}
    return x


def _undictify(template, d):
    """Rebuild the template's pytree types around dict-form leaves."""
    if isinstance(template, tuple) and hasattr(template, "_fields"):
        if not template._fields:
            return template
        return type(template)(
            **{f: _undictify(getattr(template, f), d[f]) for f in template._fields}
        )
    if isinstance(template, tuple):
        return tuple(_undictify(t, v) for t, v in zip(template, d))
    if isinstance(template, list):
        return [_undictify(t, v) for t, v in zip(template, d)]
    if isinstance(template, dict):
        return {k: _undictify(v, d[k]) for k, v in template.items()}
    return d


def restore_resharded(
    ckpt: Checkpointer,
    ctx,
    step: int | None = None,
) -> TrainState:
    """Restore ``ckpt``'s latest (or ``step``) checkpoint into ``ctx``'s
    mesh/shardings, adapting table row padding between topologies.

    Raises if a slice would drop non-zero rows (i.e. the target vocabulary
    is genuinely smaller than the data in the checkpoint).
    """
    from ..parallel.spmd import _build_full_init

    mngr = ckpt._mngr
    mngr.wait_until_finished()
    step = mngr.latest_step() if step is None else step
    if step is None:
        raise FileNotFoundError("no checkpoint to restore")

    # target template (shape inference only — nothing materializes)
    init_fn = _build_full_init(ctx.cfg, ctx.true_feature_size)
    target_shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    # Orbax stores the state in dict form (NamedTuples -> field dicts,
    # tuples -> lists); adapt in that form, then rebuild the TrainState
    target_dict = _dictify(target_shapes)

    # saved template from checkpoint metadata (same dict-form structure)
    import orbax.checkpoint as ocp

    meta = mngr.item_metadata(step)
    saved_abstract = jax.tree_util.tree_map(
        lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype)
        if hasattr(m, "shape")
        else m,
        meta,
    )
    raw = mngr.restore(step, args=ocp.args.StandardRestore(saved_abstract))

    def adapt(path, saved, target_shape: jax.ShapeDtypeStruct):
        saved = np.asarray(saved)
        if saved.shape == target_shape.shape:
            return saved
        if not _is_table_leaf(path) or saved.ndim == 0 or (
            saved.shape[1:] != target_shape.shape[1:]
        ):
            raise ValueError(
                f"checkpoint leaf {jax.tree_util.keystr(path)} has shape "
                f"{saved.shape}, target needs {target_shape.shape} — only "
                f"table row counts (vocab padding) can be adapted"
            )
        rows_t = target_shape.shape[0]
        if saved.shape[0] > rows_t:
            dropped = saved[rows_t:]
            if np.any(dropped != 0):
                raise ValueError(
                    f"resharding {jax.tree_util.keystr(path)} from "
                    f"{saved.shape[0]} to {rows_t} rows would drop non-zero "
                    f"data — the target feature_size is smaller than the "
                    f"checkpoint's true vocabulary"
                )
            return saved[:rows_t]
        pad = np.zeros((rows_t - saved.shape[0], *saved.shape[1:]), saved.dtype)
        return np.concatenate([saved, pad], axis=0)

    adapted = jax.tree_util.tree_map_with_path(adapt, raw, target_dict)
    state: Any = _undictify(target_shapes, adapted)

    def place(leaf, sharding):
        return jax.device_put(leaf, sharding)

    return jax.tree_util.tree_map(place, state, ctx.state_shardings)
