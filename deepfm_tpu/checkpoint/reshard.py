"""Cross-topology checkpoint restore.

A TrainState checkpoint records embedding tables at the PADDED vocabulary of
the mesh it was trained on (``padded_vocab`` = next multiple of
lcm(model_parallel, window_multiple), parallel/spmd.py) — so a run saved on
a [4, 2] mesh cannot restore byte-for-byte into a [2, 4] context whose
padding differs.  The reference had no notion of this (one fixed topology
per job, SURVEY §5); here reshaping the mesh between runs is routine
(train wide, debug narrow, serve single-chip), so restore must adapt.

``restore_resharded`` restores a checkpoint saved under ANY mesh topology
into a target :class:`~deepfm_tpu.parallel.spmd.SPMDContext`: every leaf
living under a table key whose leading dimension is the SAVED padded vocab
is sliced (dropping only all-zero pad rows — verified, never data) or
zero-padded to the target padded vocab, then the whole state is placed into
the target shardings.  Non-table leaves must match shapes exactly.

North-star-scale streaming: nothing is materialized on host.  Every leaf is
restored by Orbax directly INTO a sharding on the target mesh (each device
reads only its chunks from disk); table leaves whose row count differs are
restored at the SAVED shape sharded over the target mesh, then sliced or
zero-padded to the target padded vocab on-device (a jitted, distributed
reshape — the all-zero-pad-rows verification is a sharded reduction, not a
host scan).  Host memory stays O(checkpoint-chunk buffer) regardless of
vocabulary size; `benchmarks/large_vocab.py` exercises this at 10M-100M
rows and records peak RSS.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..train.step import TrainState
from .ckpt import Checkpointer

# sharding -> verdict of _reshape_under_sharding_ok (one tiny probe compile
# per distinct (mesh, spec) pair per process)
_RESHAPE_PROBE_CACHE: dict = {}


def _reshape_under_sharding_ok(sharding) -> bool:
    """Probe whether jitted row-reshapes with ``out_shardings=sharding``
    are value-correct on this backend.

    Some XLA:CPU builds (observed on jaxlib 0.4.36's 8-virtual-device
    mesh) MISCOMPILE ``concatenate``/slice under an ``out_shardings`` whose
    mesh has a replicated axis: the replicated output is assembled by
    SUMMING partial shards, silently doubling every value.  Restoring a
    checkpoint across topologies would corrupt the tables, so the jitted
    streaming reshape is only used after this tiny probe proves it honest;
    otherwise the adapt falls back to a host-staged pad/slice (correct
    everywhere, O(leaf) host memory — acceptable on the small backends
    that exhibit the bug)."""
    key = (sharding.mesh, sharding.spec)
    hit = _RESHAPE_PROBE_CACHE.get(key)
    if hit is not None:
        return hit
    # dim0 divisible by any axis product; dim1 broadcastable for 1-D specs
    rows = 8
    for name in sharding.mesh.axis_names:
        rows *= sharding.mesh.shape[name]
    probe = np.arange(1, rows + 1, dtype=np.float32)
    try:
        cat = jax.jit(
            lambda a: jnp.concatenate([a[: rows // 2], a[: rows // 2]]),
            out_shardings=jax.sharding.NamedSharding(
                sharding.mesh, jax.sharding.PartitionSpec(
                    *(sharding.spec[:1] or [None])
                )
            ),
        )(jnp.asarray(probe))
        want = np.concatenate([probe[: rows // 2], probe[: rows // 2]])
        # verify per ADDRESSABLE shard, not via a full device_get: on a
        # multi-host mesh fetching the whole output raises for
        # addressability, which says nothing about value-correctness —
        # a blanket fetch would route every multi-host restore onto the
        # O(full-leaf) host-staged fallback exactly where it can't afford
        # to.  The summed-shard miscompile corrupts local shards too, so
        # the local view is a sufficient witness.
        ok = all(
            np.array_equal(np.asarray(s.data), want[s.index])
            for s in cat.addressable_shards
        )
    # da:allow[swallowed-exception] probe: a compile/execute failure fails the jitted path identically — fall back
    except Exception:
        ok = False
    _RESHAPE_PROBE_CACHE[key] = ok
    return ok



class ReshardDataLossError(ValueError):
    """Deliberate refusal: the target vocabulary is smaller than the
    checkpoint's true data.  Semantic — NOT a torn checkpoint, so the
    latest-step fallback must propagate it instead of silently restoring
    an older payload (which would hold the same data and refuse again,
    or worse, mask the misconfiguration)."""


def jit_row_adapter(sharding, rows_to: int):
    """The device-to-device row reshape at the heart of every reshard:
    slice dim0 down to ``rows_to`` or zero-pad it up, with the OUTPUT
    committed to ``sharding`` — XLA emits the collective plan (all-gather /
    dynamic-slice of owned rows across the target mesh) and no row ever
    stages on the host.  Shared by the cross-topology restore below, the
    elastic live reshard (``deepfm_tpu/elastic/plan.py``), and the
    ``audit_elastic`` trace contract, which lowers exactly this executable
    under ``transfer_guard('disallow')`` to prove the no-host-round-trip
    claim."""

    def _reshape_rows(a):
        if a.shape[0] >= rows_to:
            return a[:rows_to]
        pad = rows_to - a.shape[0]
        return jnp.concatenate(
            [a, jnp.zeros((pad, *a.shape[1:]), a.dtype)]
        )

    return jax.jit(_reshape_rows, out_shardings=sharding)


def _is_table_leaf(path) -> bool:
    # the authoritative row-sharded-table key list, read at CALL time (a
    # module-level import would drag the parallel -> models chain into
    # this module's import; a copy would silently miss new tables)
    from ..parallel.spmd import TABLE_KEYS

    keys = {getattr(p, "key", None) for p in path}
    return bool(keys & set(TABLE_KEYS))


def _is_zero_leaf(path) -> bool:
    """A leaf of the ZeRO dp-partitioned optimizer state
    (train/optimizer.ZeroDpState).  Its flattened layout is CANONICAL —
    the row-major flatten of the param (plus trailing zero padding), see
    ``zero_layout_size`` — so adapting between topologies is the same
    dim0 slice/pad the table row-padding adapt already does.  The marker
    appears as a dict key in Orbax's on-disk form and as a NamedTuple
    attr on live states."""
    return any(
        getattr(p, "key", None) == "zero_dp"
        or getattr(p, "name", None) == "zero_dp"
        for p in path
    )


def _dictify(x):
    """Mirror Orbax's on-disk pytree form: NamedTuples -> field dicts
    (field-less ones -> None), tuples -> lists."""
    if isinstance(x, tuple) and hasattr(x, "_fields"):
        if not x._fields:
            return None
        return {f: _dictify(getattr(x, f)) for f in x._fields}
    if isinstance(x, (tuple, list)):
        return [_dictify(v) for v in x]
    if isinstance(x, dict):
        return {k: _dictify(v) for k, v in x.items()}
    return x


def _undictify(template, d):
    """Rebuild the template's pytree types around dict-form leaves."""
    if isinstance(template, tuple) and hasattr(template, "_fields"):
        if not template._fields:
            return template
        return type(template)(
            **{f: _undictify(getattr(template, f), d[f]) for f in template._fields}
        )
    if isinstance(template, tuple):
        return tuple(_undictify(t, v) for t, v in zip(template, d))
    if isinstance(template, list):
        return [_undictify(t, v) for t, v in zip(template, d)]
    if isinstance(template, dict):
        return {k: _undictify(v, d[k]) for k, v in template.items()}
    return d


def relayout_state(state, target_shapes, target_shardings):
    """Re-lay a restored tree whose opt_state is in the OTHER zero-sharding
    layout (replicated moments ↔ the flattened dp-partitioned
    ``ZeroDpState`` layout) into ``target_shapes``/``target_shardings``.

    The zero wrapper adds exactly ONE structure level around the same
    inner optax state and flattens leaves without reordering them, so the
    two layouts' flattened leaf orders are congruent — leaves pair by
    position.  A pair with equal shapes re-places; a mismatched pair
    relays through the canonical flat form (row-major flatten + trailing
    zero padding, ``train/optimizer.zero_layout_size``): reshape, then
    pad or slice — slicing verifies the dropped tail is all-zero padding
    (anything else is real data and raises
    :class:`ReshardDataLossError`).  Everything stays on-device through
    jitted reshapes (probe-guarded like the row adapt; the host fallback
    only engages on backends whose sharded reshape miscompiles)."""
    src_leaves = jax.tree_util.tree_leaves(state)
    tgt_paths = jax.tree_util.tree_flatten_with_path(target_shapes)[0]
    tgt_def = jax.tree_util.tree_structure(target_shapes)
    shard_leaves = jax.tree_util.tree_leaves(target_shardings)
    if not (len(src_leaves) == len(tgt_paths) == len(shard_leaves)):
        raise ValueError(
            f"cannot relayout: {len(src_leaves)} source leaves vs "
            f"{len(tgt_paths)} target leaves — the trees are not "
            f"layout-congruent"
        )
    out = []
    for s, (path, t), sh in zip(src_leaves, tgt_paths, shard_leaves):
        if not hasattr(t, "shape") or not hasattr(s, "shape") \
                or tuple(s.shape) == tuple(t.shape):
            out.append(jax.device_put(s, sh) if hasattr(s, "shape") else s)
            continue
        n_t = 1
        for d in t.shape:
            n_t *= int(d)
        n_s = int(np.prod(s.shape)) if s.shape else 1
        if n_s > n_t:
            dropped = bool(jax.jit(
                lambda a, n=n_t: jnp.any(a.reshape(-1)[n:] != 0)
            )(s))
            if dropped:
                raise ReshardDataLossError(
                    f"relayout of {jax.tree_util.keystr(path)} from "
                    f"{tuple(s.shape)} to {tuple(t.shape)} would drop "
                    f"non-zero data — the flat tail is not padding"
                )

        def _reform(a, n=n_t, shape=tuple(t.shape)):
            flat = a.reshape(-1)
            if flat.shape[0] < n:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((n - flat.shape[0],), flat.dtype)]
                )
            return flat[:n].reshape(shape)

        if _reshape_under_sharding_ok(sh):
            # one jitted executable cannot span two device sets: when the
            # source lives on a different mesh (the live reshard path),
            # stage it onto the target mesh first
            src_devs = getattr(getattr(s, "sharding", None),
                               "device_set", None)
            if src_devs is not None and src_devs != sh.device_set:
                from jax.sharding import (
                    NamedSharding, PartitionSpec as P2,
                )

                s = jax.device_put(
                    s, NamedSharding(sh.mesh, P2(*([None] * s.ndim)))
                )
            out.append(jax.jit(_reform, out_shardings=sh)(s))
        else:
            host = np.asarray(jax.device_get(s)).reshape(-1)
            if host.size < n_t:
                host = np.concatenate(
                    [host, np.zeros((n_t - host.size,), host.dtype)]
                )
            out.append(jax.device_put(
                host[:n_t].reshape(tuple(t.shape)), sh
            ))
    return jax.tree_util.tree_unflatten(tgt_def, out)


def _alt_layout_context(ctx):
    """An SPMDContext over the SAME cfg/mesh whose opt_state templates
    describe the OTHER zero-sharding layout — the shape a payload
    committed under a different data-parallel degree (or a pre-zero
    framework version) actually has.  ``make_context`` re-pads the
    already-padded vocab idempotently, so shapes line up exactly."""
    from ..parallel.spmd import make_context

    return make_context(
        ctx.cfg, ctx.mesh, zero_layout=not ctx.zero_layout
    )


def restore_resharded(
    ckpt: Checkpointer,
    ctx,
    step: int | None = None,
    *,
    plan=None,
) -> TrainState:
    """Restore ``ckpt``'s latest (or ``step``) checkpoint into ``ctx``'s
    mesh/shardings, adapting table row padding between topologies.

    ``plan`` (an :class:`~deepfm_tpu.elastic.plan.ReshardPlan`) is the
    elastic controller's pre-computed N→M redistribution: when given, the
    target topology is validated against it BEFORE any bytes move (a plan
    drawn for a different mesh or padding fails loudly instead of
    restoring into the wrong shardings).

    Raises if a slice would drop non-zero rows (i.e. the target vocabulary
    is genuinely smaller than the data in the checkpoint).

    The optimizer-state LAYOUT adapts too: a checkpoint whose moments are
    in the other ``optimizer.zero_sharding`` layout (a legacy replicated
    payload restoring into the dp-sharded layout, or a dp-sharded payload
    restoring onto a dp'=1 mesh where the sharded update is inactive)
    restores through a template of ITS layout and relays on-device
    (:func:`relayout_state`).
    """
    from ..parallel.spmd import _build_full_init

    if plan is not None:
        plan.validate_target(ctx)
    # target template (shape inference only — nothing materializes)
    init_fn = _build_full_init(ctx.cfg, ctx.true_feature_size,
                               ctx.zero_layout)
    target_shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(0))

    def alt_candidate():
        # the checkpoint may hold the OTHER opt-state layout (committed
        # under a different dp, or by a pre-zero framework version):
        # restore through a template of that layout, relayout on-device.
        # Built lazily — the steady state restores under the target
        # template and never pays this second abstract init trace.
        alt = _alt_layout_context(ctx)
        alt_shapes = jax.eval_shape(
            _build_full_init(alt.cfg, alt.true_feature_size,
                             alt.zero_layout),
            jax.random.PRNGKey(0),
        )
        return (alt_shapes, alt.state_shardings,
                lambda got: relayout_state(
                    got, target_shapes, ctx.state_shardings))

    candidates = [
        lambda: (target_shapes, ctx.state_shardings, None),
        alt_candidate,
    ]
    return _restore_resharded_tree(ckpt, candidates, step)


def restore_resharded_payload(
    ckpt: Checkpointer,
    ctx,
    step: int | None = None,
    *,
    plan=None,
):
    """Cross-topology restore of an :class:`~deepfm_tpu.online.trainer.
    OnlinePayload` — the elastic trainer's resume point: {weights,
    optimizer state, stream cursor} adapt to the new mesh as ONE atomic
    tree, so the cursor can never resume against weights from a different
    commit (the exactly-once invariant survives the topology change).
    Table leaves inside ``payload.train`` reshard exactly as in
    :func:`restore_resharded`; the cursor arrays restore replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..online.trainer import _CURSOR_BYTES, OnlinePayload
    from ..parallel.spmd import _build_full_init

    if plan is not None:
        plan.validate_target(ctx)

    def payload_templates(c):
        init_fn = _build_full_init(c.cfg, c.true_feature_size,
                                   c.zero_layout)
        train_shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
        shapes = OnlinePayload(
            step=jax.ShapeDtypeStruct((), jnp.int32),
            train=train_shapes,
            cursor_segment=jax.ShapeDtypeStruct(
                (_CURSOR_BYTES,), jnp.uint8),
            cursor_len=jax.ShapeDtypeStruct((), jnp.int32),
            cursor_record=jax.ShapeDtypeStruct((), jnp.int64),
            fence_token=jax.ShapeDtypeStruct((), jnp.int64),
        )
        repl = NamedSharding(c.mesh, P())
        shardings = OnlinePayload(
            step=repl,
            train=c.state_shardings,
            cursor_segment=repl,
            cursor_len=repl,
            cursor_record=repl,
            fence_token=repl,
        )
        return shapes, shardings

    target_shapes, shardings = payload_templates(ctx)

    # candidate templates, most-likely first: the target layout, then the
    # OTHER opt-state layout (a payload committed under a different dp —
    # the elastic grow/shrink across the dp==1 boundary — or by a
    # pre-zero framework version); each also tried as the pre-fencing
    # 5-field legacy tree.  A hit on an alternate-layout template relays
    # on-device into the target layout (relayout_state).  All templates
    # are tried PER STEP (newest first), so a layout mismatch never
    # masquerades as a torn step and regresses the resume point; the
    # alternate-layout templates build lazily (thunks) so the steady
    # state never pays their extra abstract init trace.
    from ..online.trainer import _LegacyOnlinePayload, _upgrade_legacy

    def _relayout(got):
        return relayout_state(got, target_shapes, shardings)

    def _legacy_of(shapes_c, shards_c, post):
        return (
            _LegacyOnlinePayload(*shapes_c[:5]),
            _LegacyOnlinePayload(*shards_c[:5]),
            (lambda got, p=post: p(_upgrade_legacy(got)) if p
             else _upgrade_legacy(got)),
        )

    alt_cache: list = []

    def _alt_templates():
        if not alt_cache:
            alt_cache.append(payload_templates(_alt_layout_context(ctx)))
        return alt_cache[0]

    candidates = [
        lambda: (target_shapes, shardings, None),
        lambda: _legacy_of(target_shapes, shardings, None),
        lambda: (*_alt_templates(), _relayout),
        lambda: _legacy_of(*_alt_templates(), _relayout),
    ]
    return _restore_resharded_tree(ckpt, candidates, step)


def _restore_resharded_tree(
    ckpt: Checkpointer, candidates, step: int | None
):
    """The shared cross-topology restore engine: stream every leaf from
    the checkpoint directly INTO a sharding on the target mesh, adapting
    table-leaf row counts on-device (``jit_row_adapter``).

    ``candidates`` is a list of zero-arg thunks, each returning a
    ``(target_shapes, target_shardings, post_fn | None)`` template,
    tried IN ORDER at each step — the target tree first, then alternate
    layouts (the other zero-sharding layout, the pre-fencing legacy
    payload) whose ``post_fn`` converts the restored tree into the
    target form.  Thunks keep the alternate templates UNBUILT on the
    happy path (the steady state restores under the first template; the
    alternates' extra abstract init trace is paid only after a failure).
    All templates are exhausted at one step before falling back to an
    older one, so a layout mismatch is never mistaken for a torn step.

    When no step is pinned, steps unreadable under EVERY template fall
    back to the previous complete one — the same discipline as
    ``online.trainer.restore_latest_payload``: a reshard triggered right
    after a commit was torn mid-write must resume from the previous
    payload, not die on the step it was hardened against."""
    import logging

    mngr = ckpt._mngr
    mngr.wait_until_finished()
    steps = [step] if step is not None else sorted(
        mngr.all_steps(), reverse=True
    )
    if not steps:
        raise FileNotFoundError("no checkpoint to restore")
    step_err: Exception | None = None
    resolved: list = [None] * len(candidates)
    for s in steps:
        # per-STEP first failure (the target template's — the most
        # representative story for THIS step); reset across steps so the
        # fallback warnings and the terminal error never blame a failure
        # on the wrong step
        step_err = None
        for i, candidate in enumerate(candidates):
            if resolved[i] is None:
                resolved[i] = candidate()
            shapes_c, shards_c, post = resolved[i]
            try:
                got = _restore_tree_at(ckpt, shapes_c, shards_c, s)
            except ReshardDataLossError:
                raise  # deliberate refusal, not a torn step
            except Exception as e:
                step_err = step_err or e
                continue
            return post(got) if post else got
        if step is not None:
            raise RuntimeError(
                f"checkpoint step {step} is unreadable under every "
                f"template; first error: {type(step_err).__name__}: "
                f"{step_err}"
            ) from step_err
        logging.getLogger(__name__).warning(
            "checkpoint step %d unreadable for resharded restore under "
            "every template (first: %s: %s) — falling back to the "
            "previous complete step",
            s, type(step_err).__name__, step_err)
    raise RuntimeError(
        f"every checkpoint step {steps} is unreadable; last step's "
        f"error: {type(step_err).__name__}: {step_err}"
    ) from step_err


def _restore_tree_at(
    ckpt: Checkpointer, target_shapes, target_shardings, step: int
):
    mngr = ckpt._mngr
    # Orbax stores the state in dict form (NamedTuples -> field dicts,
    # tuples -> lists); adapt in that form, then rebuild the pytree
    target_dict = _dictify(target_shapes)
    shard_dict = _dictify(target_shardings)

    # saved template from checkpoint metadata (same dict-form structure).
    # Every leaf restores INTO a sharding over the target mesh: exact-shape
    # leaves get their final sharding; row-mismatched table leaves restore
    # at the SAVED shape under the target leaf's sharding spec (uneven
    # trailing shards are fine), adapted on-device below.
    import orbax.checkpoint as ocp

    meta = mngr.item_metadata(step)
    if not jax.tree_util.tree_leaves(meta):
        # a FRESH manager (restart path) has no handler registered yet and
        # returns an empty placeholder instead of the saved tree structure;
        # read the metadata through a throwaway manager with the standard
        # handler pre-registered (managers that already saved or restored
        # in-process take the fast path above)
        with ocp.CheckpointManager(
            mngr.directory,
            item_handlers=ocp.StandardCheckpointHandler(),
        ) as meta_mngr:
            meta = meta_mngr.item_metadata(step)
    # meta's treedef is an Orbax wrapper type that cannot be tree-mapped
    # together with the plain dict-form target trees — but its LEAF order is
    # congruent with them (same logical structure, same sorted-dict
    # flattening), so align by flattened leaves and rebuild with meta's own
    # treedef.
    meta_leaves, meta_def = jax.tree_util.tree_flatten(meta)
    tgt_paths_leaves = jax.tree_util.tree_flatten_with_path(target_dict)[0]
    shard_leaves = jax.tree_util.tree_leaves(shard_dict)
    if not (len(meta_leaves) == len(tgt_paths_leaves) == len(shard_leaves)):
        raise ValueError(
            f"checkpoint structure does not match the target state: "
            f"{len(meta_leaves)} saved leaves vs {len(tgt_paths_leaves)} "
            f"target leaves"
        )

    def _dim0_partitions(sharding) -> int:
        spec = getattr(sharding, "spec", None)
        if not spec or spec[0] is None:
            return 1
        names = spec[0] if isinstance(spec[0], tuple) else (spec[0],)
        p = 1
        for nm in names:
            p *= sharding.mesh.shape[nm]
        return p

    def make_abstract(m, path, target_sds, sharding):
        if not hasattr(m, "shape"):
            return m
        if tuple(m.shape) == tuple(target_sds.shape):
            return jax.ShapeDtypeStruct(m.shape, m.dtype, sharding=sharding)
        if (
            not (_is_table_leaf(path) or _is_zero_leaf(path))
            or len(m.shape) == 0
            or tuple(m.shape[1:]) != tuple(target_sds.shape[1:])
        ):
            raise ValueError(
                f"checkpoint leaf {jax.tree_util.keystr(path)} has shape "
                f"{tuple(m.shape)}, target needs {tuple(target_sds.shape)} — "
                f"only table row counts (vocab padding) and dp-sharded "
                f"zero-layout moment lengths can be adapted"
            )
        if m.shape[0] % _dim0_partitions(sharding) == 0:
            # streaming path: restore at the SAVED row count, sharded over
            # the target mesh; rows adapt on-device below
            return jax.ShapeDtypeStruct(m.shape, m.dtype, sharding=sharding)
        # saved rows don't divide the target partition count (possible only
        # for toy/odd paddings — large-vocab paddings are lcm-multiples of
        # every practical mesh): stage this one leaf on host
        return jax.ShapeDtypeStruct(m.shape, m.dtype)

    abstract = meta_def.unflatten(
        make_abstract(m, path, sds, sh)
        for m, (path, sds), sh in zip(
            meta_leaves, tgt_paths_leaves, shard_leaves
        )
    )
    raw = mngr.restore(step, args=ocp.args.StandardRestore(abstract))

    def adapt(path, saved, target_sds: jax.ShapeDtypeStruct, sharding):
        if not hasattr(saved, "shape") or tuple(saved.shape) == tuple(
            target_sds.shape
        ):
            return saved
        rows_t, rows_s = target_sds.shape[0], saved.shape[0]
        if rows_s > rows_t:
            # sharded reduction — never pulls the rows to host
            dropped_nonzero = bool(
                jax.jit(lambda a: jnp.any(a[rows_t:] != 0))(saved)
            )
            if dropped_nonzero:
                raise ReshardDataLossError(
                    f"resharding {jax.tree_util.keystr(path)} from "
                    f"{rows_s} to {rows_t} rows would drop non-zero "
                    f"data — the target feature_size is smaller than the "
                    f"checkpoint's true vocabulary"
                )
            if _reshape_under_sharding_ok(sharding):
                return jit_row_adapter(sharding, rows_t)(saved)
            return jax.device_put(
                np.asarray(jax.device_get(saved))[:rows_t], sharding
            )
        if _reshape_under_sharding_ok(sharding):
            return jit_row_adapter(sharding, rows_t)(saved)
        pad = rows_t - rows_s
        host = np.asarray(jax.device_get(saved))
        host = np.concatenate(
            [host, np.zeros((pad, *host.shape[1:]), host.dtype)]
        )
        return jax.device_put(host, sharding)

    adapted = jax.tree_util.tree_map_with_path(
        adapt, raw, target_dict, shard_dict
    )
    state: Any = _undictify(target_shapes, adapted)

    # no-op for leaves already in their final sharding; places stragglers
    return jax.tree_util.tree_map(
        jax.device_put, state, target_shardings
    )
