"""Sharded checkpoint/resume — the tf.estimator model_dir capability.

The reference delegates checkpointing to the Estimator: PS mode writes to a
shared S3 ``model_dir`` (ps nb cell 4, README.md:63), HVD mode writes locally
on rank 0 only — "to prevent other workers from corrupting them" (hvd:397,
hvd:402-415) — and spot-instance restart resumes from the latest checkpoint
(SURVEY §5).  Here:

* **single-logical-writer by construction**: Orbax coordinates all processes
  of a multi-host run in one atomic save of the sharded TrainState — each
  host writes only its addressable shards; no rank-0 funnel, no corruption
  window to work around.
* **resume = restore latest** into the exact shardings of the running mesh.
* retention (``keep_checkpoints``) and cadence (``checkpoint_every_steps``)
  replace RunConfig's save_checkpoints_* knobs.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import orbax.checkpoint as ocp

from ..train.step import TrainState


class Checkpointer:
    def __init__(
        self,
        directory: str | os.PathLike,
        *,
        max_to_keep: int = 3,
    ):
        self._mngr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                create=True,
                enable_async_checkpointing=False,
            ),
        )

    def save(self, state: TrainState) -> bool:
        """Save at ``state.step``.  Cadence is the CALLER's policy (the train
        loop's ``step % checkpoint_every_steps`` gate) — this class holds no
        interval logic.  A step already on disk is a no-op (so a final save
        after a periodic save at the same step is safe); returns whether a
        save happened."""
        step = int(state.step)
        if step in self._mngr.all_steps():
            return False
        saved = self._mngr.save(step, args=ocp.args.StandardSave(state), force=True)
        self._mngr.wait_until_finished()
        return bool(saved)

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def restore(self, target_state: TrainState, step: int | None = None) -> TrainState:
        """Restore into the shardings/dtypes of ``target_state`` (an existing
        or abstract TrainState from the running mesh)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint to restore")
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape")
            else x,
            target_state,
        )
        return self._mngr.restore(step, args=ocp.args.StandardRestore(abstract))

    def all_steps(self) -> list[int]:
        return list(self._mngr.all_steps())

    def close(self) -> None:
        self._mngr.close()


def maybe_clear(directory: str, enabled: bool) -> None:
    """``clear_existing_model`` capability (hvd:66-68, hvd:372-378)."""
    if enabled and os.path.isdir(directory):
        import shutil

        shutil.rmtree(directory)
