"""Sharded checkpoint/resume — the tf.estimator model_dir capability.

The reference delegates checkpointing to the Estimator: PS mode writes to a
shared S3 ``model_dir`` (ps nb cell 4, README.md:63), HVD mode writes locally
on rank 0 only — "to prevent other workers from corrupting them" (hvd:397,
hvd:402-415) — and spot-instance restart resumes from the latest checkpoint
(SURVEY §5).  Here:

* **single-logical-writer by construction**: Orbax coordinates all processes
  of a multi-host run in one atomic save of the sharded TrainState — each
  host writes only its addressable shards; no rank-0 funnel, no corruption
  window to work around.
* **resume = restore latest** into the exact shardings of the running mesh.
* retention (``keep_checkpoints``) and cadence (``checkpoint_every_steps``)
  replace RunConfig's save_checkpoints_* knobs.
"""

from __future__ import annotations

import os

import jax
import orbax.checkpoint as ocp

from ..train.step import TrainState


class Checkpointer:
    def __init__(
        self,
        directory: str | os.PathLike,
        *,
        max_to_keep: int = 3,
        async_save: bool = True,
    ):
        self._mngr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                create=True,
                # async: the device->host copy completes before save()
                # returns (so donated train-state buffers are safe to reuse
                # immediately); only the file serialization runs in the
                # background, overlapped with subsequent train steps.  At
                # north-star table sizes a blocking save would stall training
                # for the full write.
                enable_async_checkpointing=async_save,
            ),
        )

    def save(self, state: TrainState, *, block: bool = False) -> bool:
        """Save at ``state.step``.  Cadence is the CALLER's policy (the train
        loop's ``step % checkpoint_every_steps`` gate) — this class holds no
        interval logic.  A step already on disk is a no-op (so a final save
        after a periodic save at the same step is safe); returns whether a
        save happened.

        Async semantics: each save first barriers on any in-flight previous
        save (``wait_until_finished`` at the next save point), then kicks off
        the new one and returns as soon as the device->host copy is done.
        ``block=True`` additionally waits for the write to hit disk."""
        self._mngr.wait_until_finished()
        step = int(state.step)
        if step in self._mngr.all_steps():
            return False
        saved = self._mngr.save(step, args=ocp.args.StandardSave(state), force=True)
        if block:
            self._mngr.wait_until_finished()
        return bool(saved)

    def wait_until_finished(self) -> None:
        """Barrier on any in-flight async save."""
        self._mngr.wait_until_finished()

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def restore(self, target_state: TrainState, step: int | None = None) -> TrainState:
        """Restore into the shardings/dtypes of ``target_state`` (an existing
        or abstract TrainState from the running mesh)."""
        self._mngr.wait_until_finished()  # an in-flight save may be `step`
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint to restore")
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape")
            else x,
            target_state,
        )
        try:
            return self._mngr.restore(step, args=ocp.args.StandardRestore(abstract))
        except Exception as e:
            if "fm_v" in str(e) and (
                "shape" in str(e).lower() or "Sizes" in str(e)
            ):
                raise RuntimeError(
                    f"checkpoint restore failed on a shape mismatch involving "
                    f"fm_v: {e}\nHint: checkpoints written with "
                    f"model.fused_kernel != 'off' store a window-padded fm_v "
                    f"(rows rounded up to a multiple of 128 // embedding_size "
                    f"when feature_size doesn't divide it); restoring under a "
                    f"different fused_kernel setting changes the expected "
                    f"shape.  Restore with the same fused_kernel value the "
                    f"checkpoint was trained with (docs/PARITY.md)."
                ) from e
            raise

    def all_steps(self) -> list[int]:
        self._mngr.wait_until_finished()
        return list(self._mngr.all_steps())

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()


def maybe_clear(directory: str, enabled: bool) -> None:
    """``clear_existing_model`` capability (hvd:66-68, hvd:372-378); remote
    model_dirs clear the object prefix instead."""
    if not enabled:
        return
    from ..data.object_store import get_store, is_url

    if is_url(directory):
        get_store().delete_prefix(directory.rstrip("/") + "/")
    elif os.path.isdir(directory):
        import shutil

        shutil.rmtree(directory)
