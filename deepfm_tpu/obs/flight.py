"""Crash flight recorder: a bounded ring of structured lifecycle events.

Every subsystem appends through ONE hook — :func:`record` — instead of
scattering stdout lines: breaker transitions (utils/retry.py), hot-swap
stage/commit/rollback (serve/reload.py, serve/pool/worker.py), router
ejection/re-admission (serve/pool/router.py), elastic
drain/reshard/resume (elastic/controller.py), segment quarantine
(online/stream.py), paging stalls (tiered/pager.py).  The ring is
bounded (old events evict) so it can run forever; every event carries a
monotonic sequence number and a wall-clock timestamp so a dump is a
totally-ordered incident timeline even across subsystems.

The recorder surfaces three ways:

* ``GET /v1/flight`` on every HTTP surface (server, pool worker,
  router) — the live ring as JSON;
* :func:`install` registers a **termination dump**: a JSONL artifact is
  written when a SIGTERM/SIGINT lands (riding the PreemptionGuard's
  stop-callback hook — the same signal path that triggers the
  preemption checkpoint) and on an unhandled crash (``sys.excepthook``
  chain), so a chaos drill or production incident leaves a correlated
  event timeline instead of scattered prints;
* :meth:`FlightRecorder.dump` on demand.

Module-global by design: the subsystems that record are constructed all
over the process and a per-component recorder would defeat the one
correlated timeline.  Tests swap the global via :func:`set_recorder`.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque


class FlightRecorder:
    """Bounded ring of ``{"seq", "t_unix", "kind", ...}`` events."""

    def __init__(self, capacity: int = 4096):
        # RLock, deliberately: the termination hooks (install /
        # dump_on_signal) call record()+dump() from inside a signal
        # handler, which CPython runs on the main thread — if the signal
        # interrupted the main thread mid-record() with the lock held, a
        # plain Lock would deadlock the graceful stop.  Re-entry is safe:
        # the critical sections only append/read the deque, so an
        # interrupted append still leaves a consistent ring.
        self._lock = threading.RLock()
        self._events: deque[dict] = deque(maxlen=max(1, int(capacity)))
        self._seq = 0
        self._dump_path: str | None = None
        self.recorded_total = 0

    @property
    def capacity(self) -> int:
        return self._events.maxlen or 0

    def record(self, kind: str, **fields) -> None:
        """Append one event.  Values pass through untouched (numpy
        scalars etc. are coerced at dump/serve time), so the record path
        stays allocation-light."""
        with self._lock:
            self._seq += 1
            self.recorded_total += 1
            self._events.append(
                {"seq": self._seq, "t_unix": round(time.time(), 6),
                 "kind": kind, **fields}
            )

    def events(self, limit: int | None = None,
               kind: str | None = None) -> list[dict]:
        with self._lock:
            out = list(self._events)
        if kind is not None:
            out = [e for e in out if e["kind"] == kind]
        return out if limit is None else out[-int(limit):]

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    # -- dumps --------------------------------------------------------------
    def configure_dump(self, path: str) -> None:
        """Arm the termination dump: :meth:`dump` (and the signal/crash
        hooks installed by :func:`install`) write here."""
        with self._lock:
            self._dump_path = path

    def dump(self, path: str | None = None, *, reason: str = "manual"
             ) -> str | None:
        """Write the ring as JSONL; returns the path (None when no path
        is configured).  Never raises — a failing dump on the way down
        must not mask the original crash."""
        with self._lock:
            target = path or self._dump_path
            events = list(self._events)
            seq = self._seq
        if not target:
            return None
        try:
            with open(target, "w") as f:
                f.write(json.dumps(
                    {"seq": seq + 1, "t_unix": round(time.time(), 6),
                     "kind": "flight_dump", "reason": reason,
                     "events": len(events)}, default=str) + "\n")
                for e in events:
                    f.write(json.dumps(e, default=str) + "\n")
            return target
        except OSError:
            return None


_LOCK = threading.Lock()
_RECORDER = FlightRecorder()
_INSTALLED = False


def get_recorder() -> FlightRecorder:
    return _RECORDER


def set_recorder(recorder: FlightRecorder) -> FlightRecorder:
    """Swap the process-global recorder (tests); returns the previous."""
    global _RECORDER
    with _LOCK:
        prev, _RECORDER = _RECORDER, recorder
        return prev


def record(kind: str, **fields) -> None:
    """THE append hook every subsystem calls."""
    _RECORDER.record(kind, **fields)


def render_events() -> list[dict]:
    """The ``GET /v1/flight`` document body: the live ring, coerced
    JSON-safe (record() stores values untouched — numpy scalars etc.
    stringify here, at scrape time, the one place every HTTP surface
    shares)."""
    return json.loads(json.dumps(_RECORDER.events(), default=str))


def install(dump_path: str, *, capacity: int | None = None) -> FlightRecorder:
    """Arm termination/crash dumps onto ``dump_path``.

    * registers with the PreemptionGuard stop-callback hook
      (launch/preemption.py): the first SIGTERM/SIGINT records a
      ``termination_signal`` event and writes the JSONL dump — the same
      cooperative path that triggers the preemption checkpoint;
    * chains ``sys.excepthook``: an unhandled exception records a
      ``crash`` event (type + message) and dumps before the original
      hook prints the traceback.

    Idempotent per process (re-installing just re-points the path)."""
    global _INSTALLED
    rec = _RECORDER
    if capacity is not None and capacity != rec.capacity:
        rec = FlightRecorder(capacity)
        set_recorder(rec)
    rec.configure_dump(dump_path)
    with _LOCK:
        if _INSTALLED:
            return rec
        _INSTALLED = True
    from ..launch.preemption import register_stop_callback

    def _on_stop(signum=None) -> None:
        r = _RECORDER
        r.record("termination_signal",
                 signum=signum, pid=os.getpid())
        r.dump(reason="termination_signal")

    register_stop_callback(_on_stop)

    prev_hook = sys.excepthook

    def _on_crash(exc_type, exc, tb):
        r = _RECORDER
        r.record("crash", error=f"{exc_type.__name__}: {exc}",
                 pid=os.getpid())
        r.dump(reason="crash")
        prev_hook(exc_type, exc, tb)

    sys.excepthook = _on_crash
    return rec


def dump_on_signal(sig: int | None = None) -> bool:
    """Arm the dump for processes WITHOUT a PreemptionGuard (the serve
    surfaces keep default SIGTERM semantics — the stop-callback path of
    :func:`install` never fires there).  The handler writes the dump,
    then re-delivers the signal with the default action, so termination
    behavior is unchanged — the process still dies, it just leaves the
    timeline first.  Returns False off the main thread (CPython only
    allows ``signal.signal`` there) or when no dump path is configured
    yet; call :func:`install` first."""
    import signal as _signal

    sig = _signal.SIGTERM if sig is None else sig
    if threading.current_thread() is not threading.main_thread():
        return False

    def _handler(signum, frame):
        r = _RECORDER
        r.record("termination_signal", signum=signum, pid=os.getpid())
        r.dump(reason="termination_signal")
        _signal.signal(signum, _signal.SIG_DFL)
        os.kill(os.getpid(), signum)

    _signal.signal(sig, _handler)
    return True
