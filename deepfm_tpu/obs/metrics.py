"""Typed, labeled metrics registry + THE sliding-window percentile.

Before this module the p50/p95/p99 snapshot lived three times (the
MicroBatcher's ``_Metrics``, the pool router's ``_Window``, the funnel
scorer's ``_Window``) — three copies of the same quantile math, free to
drift independently.  :class:`SlidingWindow` is now the single
implementation (the ``DEFAULT_BUCKETS`` discipline applied to quantile
math), and :class:`MetricsRegistry` is the single place counters, gauges
and histograms live, so every subsystem's ``/v1/metrics`` JSON section
re-renders from registry values and every HTTP surface can additionally
serve ``GET /metrics`` in Prometheus text exposition format.

Lock discipline (the hot path must stay cheap and clean under the
guarded-by analyzer): each metric CHILD owns one small lock around its own
mutation — an ``inc()`` is one uncontended lock + one float add, no
registry-wide lock is ever taken on the record path.  The registry lock
guards only family creation and collection (rare).

Label conventions: ``engine`` (micro-batcher name), ``bucket`` (dispatch
shape), ``group`` (shard group), ``event``/``kind`` (enumerated event
families).  Metric names follow Prometheus norms: ``deepfm_<area>_<what>``
with ``_total`` for counters and ``_seconds`` for latency histograms.
"""

from __future__ import annotations

import re
import threading
from typing import Callable, Sequence

import numpy as np

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# the quantiles every latency section reports — one definition, like the
# serving engine's DEFAULT_BUCKETS
DEFAULT_QUANTILES = (0.50, 0.95, 0.99)


class SlidingWindow:
    """Fixed ring of the last ``size`` observations with percentile
    snapshots — recent-traffic truth, O(size) to compute, never grows
    with uptime.  NOT internally locked: callers (the Histogram child,
    the legacy lock-holding snapshot paths) own synchronization.
    """

    def __init__(self, size: int = 2048):
        if size < 1:
            raise ValueError(f"window size must be >= 1, got {size}")
        self._buf = np.zeros(size, np.float64)
        self._n = 0  # total recorded (ring write cursor)

    @property
    def count(self) -> int:
        return self._n

    def record(self, value: float) -> None:
        self._buf[self._n % self._buf.size] = value
        self._n += 1

    def values(self) -> np.ndarray:
        """The (unsorted) live window contents."""
        return self._buf[: min(self._n, self._buf.size)].copy()

    def quantiles(
        self, qs: Sequence[float] = DEFAULT_QUANTILES
    ) -> dict[float, float]:
        """Raw (unscaled) quantile values over the window; {} when empty.
        Index math is the historical snapshot's: ``sorted[int((n-1)*q)]``."""
        n = min(self._n, self._buf.size)
        if not n:
            return {}
        w = np.sort(self._buf[:n])
        return {float(q): float(w[int((n - 1) * q)]) for q in qs}

    def snapshot(
        self,
        *,
        quantiles: Sequence[float] = DEFAULT_QUANTILES,
        scale: float = 1e3,
        digits: int = 3,
        include_max: bool = False,
    ) -> dict:
        """The legacy ``latency_ms`` document: ``{"count": N[, "p50": ...,
        "p95": ..., "p99": ...[, "max": ...]]}`` — seconds recorded,
        milliseconds reported (``scale``).  ``count`` is TOTAL recorded,
        not window occupancy (the pinned schema)."""
        n = min(self._n, self._buf.size)
        out: dict = {"count": int(self._n)}
        if n:
            w = np.sort(self._buf[:n])
            for q in quantiles:
                out[f"p{int(round(q * 100))}"] = round(
                    scale * float(w[int((n - 1) * q)]), digits
                )
            if include_max:
                out["max"] = round(scale * float(w[-1]), digits)
        return out


def _escape_label(v: str) -> str:
    return (
        str(v)
        .replace("\\", r"\\")
        .replace("\n", r"\n")
        .replace('"', r"\"")
    )


def _fmt_labels(names: tuple[str, ...], values: tuple[str, ...],
                extra: tuple[tuple[str, str], ...] = ()) -> str:
    pairs = [f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)]
    pairs += [f'{n}="{_escape_label(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


class Counter:
    """Monotonic counter child: ``inc(amount)``; negative increments are
    refused (a decreasing 'counter' corrupts every rate() downstream)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0, got {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value child: ``set``/``inc``/``dec``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Sliding-window distribution child: ``observe(v)`` records into the
    shared :class:`SlidingWindow`; exported as a Prometheus *summary*
    (quantile series + ``_sum``/``_count``) and snapshot as the pinned
    ``latency_ms``-style JSON document."""

    def __init__(self, window: int = 2048,
                 quantiles: Sequence[float] = DEFAULT_QUANTILES):
        self._lock = threading.Lock()
        self._window = SlidingWindow(window)
        self._quantiles = tuple(quantiles)
        self._sum = 0.0

    def observe(self, value: float) -> None:
        with self._lock:
            self._window.record(value)
            self._sum += value

    @property
    def count(self) -> int:
        with self._lock:
            return self._window.count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile_values(self) -> dict[float, float]:
        with self._lock:
            return self._window.quantiles(self._quantiles)

    def snapshot(self, *, scale: float = 1e3, digits: int = 3,
                 include_max: bool = False,
                 quantiles: Sequence[float] | None = None) -> dict:
        with self._lock:
            return self._window.snapshot(
                quantiles=self._quantiles if quantiles is None else quantiles,
                scale=scale, digits=digits, include_max=include_max,
            )


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """One named metric family: label names + a child per label-value
    tuple.  ``labels(...)`` is get-or-create and cached; families with no
    labels proxy the child API directly (``family.inc()``)."""

    def __init__(self, kind: str, name: str, help: str,
                 label_names: tuple[str, ...], child_kw: dict):
        self.kind = kind
        self.name = name
        self.help = help
        self.label_names = label_names
        self._child_kw = child_kw
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], object] = {}
        if not label_names:
            self._default = self._make_child(())
        else:
            self._default = None

    def _make_child(self, key: tuple[str, ...]):
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = _KINDS[self.kind](**self._child_kw)
                self._children[key] = child
            return child

    def labels(self, *values) -> object:
        key = tuple(str(v) for v in values)
        if len(key) != len(self.label_names):
            raise ValueError(
                f"{self.name}: got {len(key)} label value(s) for label "
                f"names {self.label_names}"
            )
        child = self._children.get(key)
        return child if child is not None else self._make_child(key)

    def children(self) -> dict[tuple[str, ...], object]:
        with self._lock:
            return dict(self._children)

    # unlabeled convenience proxies
    def _only(self):
        if self._default is None:
            raise ValueError(
                f"{self.name} is labeled {self.label_names}; call "
                f".labels(...) first"
            )
        return self._default

    def inc(self, amount: float = 1.0) -> None:
        self._only().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._only().dec(amount)

    def set(self, value: float) -> None:
        self._only().set(value)

    def observe(self, value: float) -> None:
        self._only().observe(value)

    @property
    def value(self) -> float:
        return self._only().value

    @property
    def count(self) -> int:
        return self._only().count

    @property
    def sum(self) -> float:
        return self._only().sum

    def snapshot(self, **kw) -> dict:
        return self._only().snapshot(**kw)


class MetricsRegistry:
    """Instance-scoped registry: each serving/training process composes
    ONE and threads it through its components (engine, swapper, pager,
    router) so ``GET /metrics`` renders that process's full picture, and
    tests stay hermetic (no cross-test global counter bleed).

    ``counter``/``gauge``/``histogram`` are get-or-create on (name): a
    second call with the same name returns the same family; a call that
    disagrees on kind or label names raises — silent divergence between
    two call sites claiming one name is how metrics rot."""

    def __init__(self, namespace: str = ""):
        self.namespace = namespace
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}
        self._collect_hooks: list[Callable[[], None]] = []

    def _register(self, kind: str, name: str, help: str,
                  labels: Sequence[str], child_kw: dict) -> _Family:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        label_names = tuple(labels)
        for ln in label_names:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r} on {name}")
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.label_names != label_names:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.label_names}, requested "
                        f"{kind}{label_names}"
                    )
                return fam
            fam = _Family(kind, name, help, label_names, child_kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> _Family:
        return self._register("counter", name, help, labels, {})

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> _Family:
        return self._register("gauge", name, help, labels, {})

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (), *, window: int = 2048,
                  quantiles: Sequence[float] = DEFAULT_QUANTILES) -> _Family:
        return self._register(
            "histogram", name, help, labels,
            {"window": window, "quantiles": quantiles},
        )

    def on_collect(self, hook: Callable[[], None]) -> None:
        """Register a pre-scrape hook (e.g. refresh queue-depth gauges);
        runs at every :meth:`render_prometheus`."""
        with self._lock:
            self._collect_hooks.append(hook)

    def families(self) -> list[_Family]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    def render_prometheus(self) -> str:
        """Prometheus text exposition (version 0.0.4).  Counters/gauges
        render one sample per child; histograms render as summaries
        (quantile series + ``_sum``/``_count``)."""
        with self._lock:
            hooks = list(self._collect_hooks)
        for hook in hooks:
            try:
                hook()
            except Exception as e:
                # a broken gauge refresher must not take down the scrape
                # of every healthy metric; surface it once per scrape
                import logging

                logging.getLogger(__name__).warning(
                    "metrics collect hook failed: %s: %s",
                    type(e).__name__, e)
        lines: list[str] = []
        for fam in self.families():
            ptype = "summary" if fam.kind == "histogram" else fam.kind
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {ptype}")
            for key, child in sorted(fam.children().items()):
                lbl = _fmt_labels(fam.label_names, key)
                if fam.kind == "histogram":
                    for q, v in sorted(child.quantile_values().items()):
                        qlbl = _fmt_labels(
                            fam.label_names, key,
                            extra=(("quantile", f"{q:g}"),),
                        )
                        lines.append(f"{fam.name}{qlbl} {v:g}")
                    lines.append(f"{fam.name}_sum{lbl} {child.sum:g}")
                    lines.append(f"{fam.name}_count{lbl} {child.count}")
                else:
                    lines.append(f"{fam.name}{lbl} {child.value:g}")
        return "\n".join(lines) + "\n"
