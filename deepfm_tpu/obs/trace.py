"""End-to-end request tracing + train-loop step-phase timers.

A trace is minted where the request enters the system (the pool router —
or accepted from the client via ``X-Trace-Id``) and propagated over HTTP
through worker predict/recommend into the MicroBatcher, so one request
accumulates per-stage spans: router forward attempts, handler scoring,
queue wait, bucket choice, device dispatch.  Head-based sampling: the
HEAD of the request path decides (``sample_rate``), and a propagated
trace id is always recorded downstream — the decision travels with the
id, so a trace is never half-collected.

Design constraints the audit (``audit_observability``) pins:

* spans are **host-side timers around dispatch boundaries** — nothing in
  here may run under ``jax.jit`` or close over a traced value, so the
  lowered executables carry no instrumentation;
* the non-sampled fast path is one ``ContextVar.get`` (no allocation);
* the recent-traces buffer is bounded (a ring), served by
  ``GET /v1/trace/recent``; optional JSONL span export for offline
  correlation with the flight recorder.

``StepPhases`` is the train-side sibling: per-step host phases (data
wait vs host prep vs dispatch) accumulated between ``MetricLogger``
emits, so a throughput regression is attributable to input starvation
vs host work vs device time without a profiler run.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
from collections import deque

TRACE_HEADER = "X-Trace-Id"
SPAN_HEADER = "X-Span-Id"

# the serving tier's shipped head-sampling rate: fresh requests trace at
# this probability (BENCH_OBS gates the throughput tax of exactly this
# config); a request that ARRIVES with an X-Trace-Id — from the router
# head or the client — is always recorded, so end-to-end traces are
# never half-collected and tests/debugging pin a trace by supplying the
# id.  Override per server via --trace-sample / Tracer(sample_rate=...).
DEFAULT_SAMPLE_RATE = 0.1

_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "deepfm_trace", default=None
)


def current_trace() -> "TraceContext | None":
    """The active request's trace context on THIS thread (None when the
    request is unsampled or there is no request) — the one hook the
    MicroBatcher and handlers read; costs a ContextVar.get."""
    return _CURRENT.get()


@contextlib.contextmanager
def span(name: str, **attrs):
    """Record a span on the current trace (no-op when none is active)."""
    ctx = _CURRENT.get()
    if ctx is None:
        yield None
        return
    t0 = time.perf_counter()
    try:
        yield ctx
    finally:
        ctx.add_span(name, t0, time.perf_counter(), **attrs)


class TraceContext:
    """One request's accumulating trace: id pair + span list.

    ``spans`` is appended from multiple threads (the handler thread and
    the batcher's dispatch thread); ``list.append`` is atomic under the
    GIL and entries are immutable tuples, so no lock is needed on the
    record path.  Record-time work is deliberately minimal — raw
    perf_counter readings and attr dicts are stored as tuples, and ALL
    rendering (ms conversion, rounding, document assembly) is deferred
    to :meth:`to_dict`, which runs at scrape time (``/v1/trace/recent``)
    or export, never on the request path."""

    __slots__ = ("trace_id", "span_id", "parent_span_id", "name",
                 "service", "t_start", "t_end", "start_unix", "spans",
                 "attrs")

    def __init__(self, name: str, service: str, *,
                 trace_id: str | None = None,
                 parent_span_id: str | None = None):
        # one urandom syscall covers both ids (hot path: once per
        # sampled request)
        rnd = os.urandom(16).hex()
        self.trace_id = trace_id or rnd[:16]
        self.span_id = rnd[16:]
        self.parent_span_id = parent_span_id
        self.name = name
        self.service = service
        self.t_start = time.perf_counter()
        self.t_end: float | None = None
        self.start_unix = time.time()
        self.spans: list[tuple] = []   # (name, t0, t1, attrs | None)
        self.attrs: dict = {}

    def add_span(self, name: str, t0: float, t1: float, **attrs) -> None:
        """Record one completed stage; ``t0``/``t1`` are perf_counter
        readings taken by the caller AROUND the stage (never inside
        traced code)."""
        self.spans.append((name, t0, t1, attrs or None))

    def set_attrs(self, **kv) -> None:
        self.attrs.update(kv)

    def headers(self) -> dict[str, str]:
        """The propagation pair a forwarding hop sends downstream."""
        return {TRACE_HEADER: self.trace_id, SPAN_HEADER: self.span_id}

    def to_dict(self) -> dict:
        """Render the trace document (scrape/export time only)."""
        spans = []
        for name, t0, t1, attrs in list(self.spans):
            s = {
                "name": name,
                "start_ms": round(1e3 * (t0 - self.t_start), 3),
                "duration_ms": round(1e3 * (t1 - t0), 3),
            }
            if attrs:
                s.update(attrs)
            spans.append(s)
        out = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "service": self.service,
            "name": self.name,
            "start_unix": round(self.start_unix, 6),
            "spans": spans,
        }
        if self.parent_span_id:
            out["parent_span_id"] = self.parent_span_id
        if self.t_end is not None:
            out["duration_ms"] = round(1e3 * (self.t_end - self.t_start), 3)
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        return out


class Tracer:
    """Per-process trace head: sampling, activation, the bounded
    recent-traces ring, optional JSONL export.

    ``begin()`` at the request edge; ``finish()`` in the handler's
    ``finally``.  A request carrying a propagated ``X-Trace-Id`` is
    always recorded (the head already sampled it); fresh requests are
    head-sampled at ``sample_rate``."""

    def __init__(self, service: str, *, sample_rate: float = 1.0,
                 capacity: int = 256, export_path: str | None = None):
        if not 0.0 <= sample_rate <= 1.0:
            raise ValueError(f"sample_rate must be in [0,1], got {sample_rate}")
        self.service = service
        self.sample_rate = float(sample_rate)
        self._lock = threading.Lock()
        self._recent: deque[dict] = deque(maxlen=max(1, int(capacity)))
        self._export_path = export_path
        self._export_file = None
        # exports serialize on their own lock so a slow disk only stalls
        # exporting threads — never the ring (recent() scrapes) or the
        # counters under self._lock
        self._export_lock = threading.Lock()
        self.traces_total = 0
        self.dropped_unsampled_total = 0

    # -- lifecycle ----------------------------------------------------------
    def begin(self, name: str, headers=None) -> "TraceContext | None":
        """Mint (or adopt) a trace for one request and activate it on the
        current thread.  Returns None (and activates nothing) when the
        head-based sampler drops it."""
        trace_id = parent = None
        if headers is not None:
            trace_id = headers.get(TRACE_HEADER) or None
            parent = headers.get(SPAN_HEADER) or None
        if trace_id is None and not self._sample():
            with self._lock:
                self.dropped_unsampled_total += 1
            return None
        return TraceContext(name, self.service, trace_id=trace_id,
                            parent_span_id=parent)

    def _sample(self) -> bool:
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        # id-independent head sampling; os.urandom avoids sharing any
        # seeded RNG with model code
        return int.from_bytes(os.urandom(2), "big") < 65536 * self.sample_rate

    def activate(self, ctx: "TraceContext | None"):
        """Install ``ctx`` as the current trace; returns the reset token
        (None when ctx is None)."""
        if ctx is None:
            return None
        return _CURRENT.set(ctx)

    def finish(self, ctx: "TraceContext | None", token=None, *,
               status: str | int | None = None) -> None:
        """Close the request: deactivate, stamp duration/status, push to
        the recent ring, export.  No-op for unsampled requests.  The ring
        holds live contexts; rendering to documents happens at scrape
        time (:meth:`recent`) so the request path pays an append, not a
        serialization."""
        if token is not None:
            _CURRENT.reset(token)
        if ctx is None:
            return
        ctx.t_end = time.perf_counter()
        if status is not None:
            ctx.attrs["status"] = status
        with self._lock:
            self.traces_total += 1
            self._recent.append(ctx)
        if self._export_path:
            # render + write OUTSIDE the ring lock: a stalled disk must
            # not block request completion on other threads or scrapes
            self._export(ctx.to_dict())

    # -- surfaces -----------------------------------------------------------
    def recent(self, limit: int | None = None) -> list[dict]:
        """Most-recent-last trace documents for ``GET /v1/trace/recent``."""
        with self._lock:
            out = list(self._recent)
        if limit is not None:
            out = out[-int(limit):]
        return [c.to_dict() for c in out]

    def find(self, trace_id: str) -> list[dict]:
        with self._lock:
            out = [c for c in self._recent if c.trace_id == trace_id]
        return [c.to_dict() for c in out]

    def _export(self, doc: dict) -> None:
        line = json.dumps(doc, default=str) + "\n"
        with self._export_lock:
            if not self._export_path:
                return
            try:
                if self._export_file is None:
                    self._export_file = open(self._export_path, "a")
                self._export_file.write(line)
                self._export_file.flush()
            except OSError:
                # a broken export must not fail serving
                self._export_path = None

    def close(self) -> None:
        with self._export_lock:
            if self._export_file is not None:
                self._export_file.close()
                self._export_file = None


class StepPhases:
    """Host-side per-step phase accumulator for the train loop.

    Phases (``data_wait`` — blocking on the input pipeline, ``host`` —
    host-side prep/bookkeeping, ``dispatch`` — handing the step to the
    device) accumulate between snapshots; :meth:`snapshot_ms` returns
    per-step averages and resets, sized to feed ``MetricLogger.step``'s
    ``extra`` hook (evaluated only on emitting boundaries).  Single
    consumer thread (the train loop) — no locking."""

    def __init__(self):
        self._acc: dict[str, float] = {}
        self._steps = 0

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._acc[name] = (
                self._acc.get(name, 0.0) + time.perf_counter() - t0
            )

    def step_done(self, n: int = 1) -> None:
        self._steps += n

    def snapshot_ms(self) -> dict[str, float]:
        """{"<phase>_ms": avg per optimizer step} since the last call."""
        steps = max(1, self._steps)
        out = {
            f"{k}_ms": round(1e3 * v / steps, 3)
            for k, v in sorted(self._acc.items())
        }
        self._acc.clear()
        self._steps = 0
        return out
