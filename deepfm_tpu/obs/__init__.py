"""Unified observability: metrics registry, request tracing, flight
recorder.

One layer, three surfaces, shared by train→publish→serve:

* :mod:`.metrics` — typed, labeled Counter/Gauge/Histogram registry with
  ONE sliding-window percentile implementation (the snapshot idiom that
  used to be copied across the MicroBatcher, the pool router and the
  funnel scorer) and Prometheus text exposition (``GET /metrics``).
* :mod:`.trace` — end-to-end request tracing: an ``X-Trace-Id`` context
  minted at the router (or accepted from the client), propagated through
  worker predict/recommend and the MicroBatcher so each request
  accumulates per-stage spans; bounded recent-traces buffer behind
  ``GET /v1/trace/recent``; host-side step-phase timers for the train
  loop.
* :mod:`.flight` — a bounded ring of structured events every subsystem
  appends to through one hook, dumped as JSONL on SIGTERM/crash (riding
  PreemptionGuard) and on demand via ``GET /v1/flight``.

Everything here is host-side and dependency-light (numpy only, no jax):
instrumentation must never enter lowered code — the ``audit_observability``
trace contract (analysis/trace_audit.py) proves the jitted predict and
train step stay free of host callbacks and baked timer values.
"""

from .flight import FlightRecorder, get_recorder, record
from .metrics import Counter, Gauge, Histogram, MetricsRegistry, SlidingWindow
from .trace import (
    SPAN_HEADER,
    TRACE_HEADER,
    StepPhases,
    TraceContext,
    Tracer,
    current_trace,
    span,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "SlidingWindow",
    "Tracer",
    "TraceContext",
    "StepPhases",
    "current_trace",
    "span",
    "TRACE_HEADER",
    "SPAN_HEADER",
    "FlightRecorder",
    "get_recorder",
    "record",
]
