"""The 4-way data-shard decision matrix as explicit, testable config.

Reproduces the partitioning semantics of the reference
(README.md:87-92; code: hvd:127-149 for the Horovod path, ps:153-156 for the
PS path) with named concepts instead of nested ifs:

* ``pre_sharded``  — the platform already assigned each *host* a disjoint
  file subset (the reference's ``enable_s3_shard`` / S3 ShardedByS3Key).
* ``multi_path``   — streaming mode where each local worker has its own
  stream channel carrying a distinct path (hvd notebook cell 8).
* file vs stream   — File mode vs Pipe mode.

The output says: of ``num_shards`` ways, this worker takes ``shard_index``,
and (streaming only) reads channel ``channel_index``.  Invariant (tested):
across all workers the shards tile the record space exactly — no overlap,
no gaps.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class WorkerTopology:
    num_hosts: int
    host_rank: int
    workers_per_host: int
    local_rank: int

    @property
    def world_size(self) -> int:
        return self.num_hosts * self.workers_per_host

    @property
    def global_rank(self) -> int:
        # rank ordering matches MPI/Horovod: host-major (hvd:134-149 relies on
        # rank // worker_per_host == host index)
        return self.host_rank * self.workers_per_host + self.local_rank


@dataclass(frozen=True)
class ShardDecision:
    """``dataset.shard(num_shards, shard_index)`` arguments + stream channel."""

    num_shards: int
    shard_index: int
    channel_index: int = 0  # streaming: which per-worker channel to read

    @property
    def is_noop(self) -> bool:
        return self.num_shards == 1


def shard_plan(
    topo: WorkerTopology,
    *,
    stream_mode: bool,
    pre_sharded: bool,
    multi_path: bool = False,
) -> ShardDecision:
    """The decision matrix (README.md:87-92, hvd:127-149).

    File mode (hvd:127-133):
      pre_sharded  -> shard(workers_per_host, local_rank)   # host files are disjoint
      else         -> shard(world_size, global_rank)
    Stream mode (hvd:134-149):
      multi_path and not pre_sharded and num_hosts > 1
                   -> shard(num_hosts, host_rank)           # channels split by worker,
                                                            # hosts see same paths
      multi_path and pre_sharded -> no shard                # fully pre-partitioned
      not multi_path and pre_sharded
                   -> shard(workers_per_host, local_rank)
      not multi_path and not pre_sharded
                   -> shard(world_size, global_rank)

    Stream channels: with multi_path each local worker reads its own channel
    (hvd:442-456 uses channel ``1 + local_rank``); otherwise all workers read
    channel 0.
    """
    channel = topo.local_rank if (stream_mode and multi_path) else 0
    if not stream_mode:
        if pre_sharded:
            return ShardDecision(topo.workers_per_host, topo.local_rank, channel)
        return ShardDecision(topo.world_size, topo.global_rank, channel)
    # streaming
    if multi_path and pre_sharded:
        return ShardDecision(1, 0, channel)
    if multi_path:
        if topo.num_hosts > 1:
            return ShardDecision(topo.num_hosts, topo.host_rank, channel)
        return ShardDecision(1, 0, channel)
    if pre_sharded:
        return ShardDecision(topo.workers_per_host, topo.local_rank, channel)
    return ShardDecision(topo.world_size, topo.global_rank, channel)


def shard_records(num_records: int, decision: ShardDecision) -> range:
    """Indices this worker owns under round-robin ``dataset.shard`` semantics
    (record i goes to shard i % num_shards)."""
    return range(decision.shard_index, num_records, decision.num_shards)
